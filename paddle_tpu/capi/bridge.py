"""Python side of the C inference API (imported by the embedded
interpreter inside libpaddle_tpu_c — see pd_capi.cc).

Keeps the C layer free of numpy/tensor ABI knowledge: tensors cross the
boundary as (raw pointer, shape) on the way in — viewed zero-copy via
ctypes, then copied once into an owned array — and as `bytes` on the way
out (PyBytes_AsStringAndSize is the one stable C-side accessor that needs
no numpy C API). Handles are process-local ints so the C structs stay
POD.

Reference analog: capi_exp's pd_utils.cc marshaling between C structs and
the C++ predictor's tensors.
"""
import ctypes

import numpy as np

_predictors = {}
_next_handle = [1]


def _cpu_guard(device):
    # CPU selection must beat the first backend touch (same recipe as
    # tests/conftest.py); harmless no-op if jax already initialized cpu
    if device == 'cpu':
        import jax
        jax.config.update('jax_platforms', 'cpu')


def create(model_dir, device):
    _cpu_guard(device)
    from ..inference import Config, create_predictor
    cfg = Config(model_dir)
    if device == 'cpu':
        cfg.disable_gpu()
    pred = create_predictor(cfg)
    h = _next_handle[0]
    _next_handle[0] += 1
    _predictors[h] = {'pred': pred, 'outputs': []}
    return h


def _get(handle):
    state = _predictors.get(handle)
    if state is None:
        raise ValueError('invalid predictor handle %r' % (handle,))
    return state


def input_num(handle):
    # get_input_names() always returns a list (positional input_N names
    # when the model carries no spec), so a count always exists; -1 on
    # the C side exclusively means error
    return len(_get(handle)['pred'].get_input_names())


def input_name(handle, idx):
    names = _get(handle)['pred'].get_input_names()
    if not names or idx < 0 or idx >= len(names):
        raise IndexError('input index %d out of range (%d inputs)'
                         % (idx, len(names or [])))
    return names[idx]


def set_input_f32(handle, name, ptr, shape):
    pred = _get(handle)['pred']
    count = 1
    for d in shape:
        if d < 0:
            raise ValueError('negative dim in shape %r' % (shape,))
        count *= int(d)
    view = (ctypes.c_float * count).from_address(ptr)
    arr = np.frombuffer(view, dtype=np.float32, count=count).reshape(
        [int(d) for d in shape]).copy()
    pred.get_input_handle(name).copy_from_cpu(arr)
    return 0


def run(handle):
    state = _get(handle)
    pred = state['pred']
    pred.run()
    state['outputs'] = [
        np.ascontiguousarray(
            pred.get_output_handle(n).copy_to_cpu())
        for n in pred.get_output_names()]
    return 0


def output_num(handle):
    return len(_get(handle)['outputs'])


def _output(handle, idx):
    outs = _get(handle)['outputs']
    if idx < 0 or idx >= len(outs):
        raise IndexError('output index %d out of range (%d outputs)'
                         % (idx, len(outs)))
    return outs[idx]


def output_shape(handle, idx):
    return tuple(int(d) for d in _output(handle, idx).shape)


def output_bytes_f32(handle, idx):
    return _output(handle, idx).astype(np.float32, copy=False).tobytes()


def destroy(handle):
    _predictors.pop(handle, None)
    return 0
