// C inference API implementation: a thin extern-"C" shell around an
// embedded CPython interpreter running paddle_tpu.capi.bridge.
//
// Reference analog: paddle/fluid/inference/capi_exp/pd_predictor.cc wraps
// the C++ AnalysisPredictor; here the "runtime" is the Python-hosted
// Predictor whose hot path is one cached XLA executable, so the C layer
// only marshals buffers (ctypes pointer-in, bytes-out) and never touches
// tensor math. All Python access is GIL-guarded so callers may invoke
// from any (single) thread.
#include "pd_capi.h"

#include <Python.h>

#include <cstring>
#include <string>

namespace {

PyObject* g_bridge = nullptr;     // paddle_tpu.capi.bridge module
thread_local std::string g_err = "";

void capture_py_error(const char* where) {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  std::string msg = std::string(where) + ": ";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      msg += (c != nullptr) ? c : "<unprintable>";
      Py_DECREF(s);
    }
  } else {
    msg += "unknown error";
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  g_err = msg;
}

class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

// Call a bridge function; returns new reference or nullptr (error set).
PyObject* bridge_call(const char* fn, PyObject* args) {
  if (args == nullptr) {
    // a failed Py_BuildValue at the call site: surface the REAL Python
    // error instead of calling the bridge with zero args and reporting
    // the resulting misleading TypeError
    capture_py_error(fn);
    return nullptr;
  }
  if (g_bridge == nullptr) {
    g_err = "PD_Init has not been called";
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* f = PyObject_GetAttrString(g_bridge, fn);
  if (f == nullptr) {
    capture_py_error(fn);
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (out == nullptr) capture_py_error(fn);
  return out;
}

}  // namespace

struct PD_Config {
  std::string model_dir;
  std::string device = "tpu";
};

struct PD_Predictor {
  long handle;
};

extern "C" {

int PD_Init(const char* repo_root) {
  if (g_bridge != nullptr) return 0;
  bool we_initialized = false;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);  // no signal handlers: the host app owns them
    we_initialized = true;
  }
  int rc = 0;
  {
    Gil gil;
    if (repo_root != nullptr && repo_root[0] != '\0') {
      PyObject* path = PySys_GetObject("path");  // borrowed
      PyObject* root = PyUnicode_FromString(repo_root);
      if (path != nullptr && root != nullptr) {
        PyList_Insert(path, 0, root);
      }
      Py_XDECREF(root);
    }
    PyObject* mod = PyImport_ImportModule("paddle_tpu.capi.bridge");
    if (mod == nullptr) {
      capture_py_error("import paddle_tpu.capi.bridge");
      rc = -1;
    } else {
      g_bridge = mod;  // keep the reference for the process lifetime
    }
  }
  if (we_initialized) {
    // Py_InitializeEx left this thread holding the GIL; release it so
    // later calls (from this OR another thread) can PyGILState_Ensure
    // without deadlocking — the header's serialized-callers contract
    PyEval_SaveThread();
  }
  return rc;
}

namespace {

// Every entry point must refuse before touching the GIL machinery: a
// PyGILState_Ensure on an uninitialized interpreter aborts the process
// instead of returning the documented error.
bool pd_ready(const char* where) {
  if (g_bridge != nullptr && Py_IsInitialized()) return true;
  g_err = std::string(where) + ": PD_Init has not been called";
  return false;
}

}  // namespace

const char* PD_GetLastError(void) { return g_err.c_str(); }

PD_Config* PD_ConfigCreate(void) { return new PD_Config(); }

void PD_ConfigSetModel(PD_Config* config, const char* model_dir) {
  if (config != nullptr && model_dir != nullptr) {
    config->model_dir = model_dir;
  }
}

void PD_ConfigSetDevice(PD_Config* config, const char* device) {
  if (config != nullptr && device != nullptr) {
    config->device = device;
  }
}

void PD_ConfigDestroy(PD_Config* config) { delete config; }

PD_Predictor* PD_PredictorCreate(const PD_Config* config) {
  if (config == nullptr || config->model_dir.empty()) {
    g_err = "PD_PredictorCreate: config with a model path is required";
    return nullptr;
  }
  if (!pd_ready("PD_PredictorCreate")) return nullptr;
  Gil gil;
  PyObject* out = bridge_call(
      "create", Py_BuildValue("(ss)", config->model_dir.c_str(),
                              config->device.c_str()));
  if (out == nullptr) return nullptr;
  long h = PyLong_AsLong(out);
  Py_DECREF(out);
  if (h < 0) {
    g_err = "PD_PredictorCreate: bridge returned an invalid handle";
    return nullptr;
  }
  PD_Predictor* p = new PD_Predictor();
  p->handle = h;
  return p;
}

int PD_PredictorGetInputNum(const PD_Predictor* predictor) {
  if (predictor == nullptr || !pd_ready("PD_PredictorGetInputNum"))
    return -1;
  Gil gil;
  PyObject* out =
      bridge_call("input_num", Py_BuildValue("(l)", predictor->handle));
  if (out == nullptr) return -1;
  long n = PyLong_AsLong(out);
  Py_DECREF(out);
  return static_cast<int>(n);
}

int PD_PredictorGetInputName(const PD_Predictor* predictor, int idx,
                             char* buf, int cap) {
  if (predictor == nullptr || buf == nullptr || cap <= 0 ||
      !pd_ready("PD_PredictorGetInputName"))
    return -1;
  Gil gil;
  PyObject* out = bridge_call(
      "input_name", Py_BuildValue("(li)", predictor->handle, idx));
  if (out == nullptr) return -1;
  const char* name = PyUnicode_AsUTF8(out);
  if (name == nullptr) {
    capture_py_error("input_name");
    Py_DECREF(out);
    return -1;
  }
  int full = static_cast<int>(strlen(name));
  snprintf(buf, cap, "%s", name);
  Py_DECREF(out);
  return full;
}

int PD_PredictorSetInputFloat(PD_Predictor* predictor, const char* name,
                              const float* data, const int64_t* shape,
                              int ndim) {
  if (predictor == nullptr || name == nullptr || data == nullptr ||
      shape == nullptr || ndim < 0) {
    g_err = "PD_PredictorSetInputFloat: null argument";
    return -1;
  }
  if (!pd_ready("PD_PredictorSetInputFloat")) return -1;
  Gil gil;
  PyObject* dims = PyTuple_New(ndim);
  if (dims == nullptr) {
    capture_py_error("PD_PredictorSetInputFloat");
    return -1;
  }
  for (int i = 0; i < ndim; ++i) {
    PyObject* d = PyLong_FromLongLong(shape[i]);
    if (d == nullptr) {
      capture_py_error("PD_PredictorSetInputFloat");
      Py_DECREF(dims);
      return -1;
    }
    PyTuple_SET_ITEM(dims, i, d);
  }
  // "O" (not "N"): we keep our reference and drop it ourselves, so a
  // Py_BuildValue failure cannot leak the dims tuple
  PyObject* args =
      Py_BuildValue("(lsKO)", predictor->handle, name,
                    (unsigned long long)(uintptr_t)data, dims);
  Py_DECREF(dims);
  PyObject* out = bridge_call("set_input_f32", args);
  if (out == nullptr) return -1;
  Py_DECREF(out);
  return 0;
}

int PD_PredictorRun(PD_Predictor* predictor) {
  if (predictor == nullptr || !pd_ready("PD_PredictorRun")) return -1;
  Gil gil;
  PyObject* out =
      bridge_call("run", Py_BuildValue("(l)", predictor->handle));
  if (out == nullptr) return -1;
  Py_DECREF(out);
  return 0;
}

int PD_PredictorGetOutputNum(const PD_Predictor* predictor) {
  if (predictor == nullptr || !pd_ready("PD_PredictorGetOutputNum"))
    return -1;
  Gil gil;
  PyObject* out =
      bridge_call("output_num", Py_BuildValue("(l)", predictor->handle));
  if (out == nullptr) return -1;
  long n = PyLong_AsLong(out);
  Py_DECREF(out);
  return static_cast<int>(n);
}

int PD_PredictorGetOutputShape(const PD_Predictor* predictor, int idx,
                               int64_t* shape, int cap) {
  if (predictor == nullptr || shape == nullptr ||
      !pd_ready("PD_PredictorGetOutputShape"))
    return -1;
  Gil gil;
  PyObject* out = bridge_call(
      "output_shape", Py_BuildValue("(li)", predictor->handle, idx));
  if (out == nullptr) return -1;
  if (!PyTuple_Check(out)) {
    g_err = "output_shape: bridge returned a non-tuple";
    Py_DECREF(out);
    return -1;
  }
  int rank = static_cast<int>(PyTuple_GET_SIZE(out));
  for (int i = 0; i < rank && i < cap; ++i) {
    shape[i] = PyLong_AsLongLong(PyTuple_GET_ITEM(out, i));
  }
  Py_DECREF(out);
  return rank;
}

int64_t PD_PredictorGetOutputFloat(const PD_Predictor* predictor, int idx,
                                   float* buf, int64_t cap) {
  if (predictor == nullptr || buf == nullptr || cap < 0 ||
      !pd_ready("PD_PredictorGetOutputFloat"))
    return -1;
  Gil gil;
  PyObject* out = bridge_call(
      "output_bytes_f32", Py_BuildValue("(li)", predictor->handle, idx));
  if (out == nullptr) return -1;
  char* raw = nullptr;
  Py_ssize_t nbytes = 0;
  if (PyBytes_AsStringAndSize(out, &raw, &nbytes) != 0) {
    capture_py_error("output_bytes_f32");
    Py_DECREF(out);
    return -1;
  }
  int64_t count = nbytes / static_cast<int64_t>(sizeof(float));
  int64_t ncopy = count < cap ? count : cap;
  memcpy(buf, raw, ncopy * sizeof(float));
  Py_DECREF(out);
  return count;
}

void PD_PredictorDestroy(PD_Predictor* predictor) {
  if (predictor == nullptr) return;
  if (g_bridge != nullptr && Py_IsInitialized()) {
    Gil gil;
    PyObject* out =
        bridge_call("destroy", Py_BuildValue("(l)", predictor->handle));
    Py_XDECREF(out);
  }
  delete predictor;
}

}  // extern "C"
