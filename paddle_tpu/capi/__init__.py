"""C inference API (reference: paddle/fluid/inference/capi_exp/ — the
plain-C surface over the predictor that the reference's Go API and
third-party runtimes build on).

`build_capi()` compiles libpaddle_tpu_c.so from pd_capi.cc with the
host CPython's embed flags (g++, content-hashed artifact cache — the same
JIT pattern as utils.cpp_extension). C programs include pd_capi.h, link
against the .so, call PD_Init(repo_root) once, then drive Config /
Predictor / Run exactly like the Python surface.

R / Go bindings remain waived (no R or Go toolchain in the image); this
C ABI is the layer both would wrap.
"""
import hashlib
import os
import subprocess
import sysconfig
import tempfile

__all__ = ['build_capi', 'header_path']

_DIR = os.path.dirname(os.path.abspath(__file__))


def header_path():
    return os.path.join(_DIR, 'pd_capi.h')


def _embed_flags():
    inc = sysconfig.get_path('include')
    libdir = sysconfig.get_config_var('LIBDIR') or ''
    ver = sysconfig.get_config_var('LDVERSION') or \
        sysconfig.get_config_var('VERSION')
    cflags = ['-I', inc]
    ldflags = ['-L', libdir, '-lpython%s' % ver, '-ldl', '-lm']
    return cflags, ldflags


def build_capi(build_directory=None, verbose=False):
    """Compile (or reuse) libpaddle_tpu_c.so; returns its path."""
    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), 'paddle_tpu_capi')
    os.makedirs(build_dir, exist_ok=True)
    src = os.path.join(_DIR, 'pd_capi.cc')
    cflags, ldflags = _embed_flags()
    key = hashlib.sha256()
    for path in (src, header_path()):
        with open(path, 'rb') as f:
            key.update(f.read())
    # flags are part of the identity: an interpreter upgrade changes
    # -lpythonX.Y and must not reuse a .so linked against the old one
    key.update(' '.join(cflags + ldflags).encode())
    out = os.path.join(build_dir,
                       'libpaddle_tpu_c_%s.so' % key.hexdigest()[:12])
    if os.path.exists(out):
        return out
    # compile to a private temp path and rename into place: two builders
    # racing on a cache miss each write their own file, and a concurrent
    # dlopen can never map a half-written library (rename is atomic on
    # the same filesystem)
    tmp = '%s.tmp.%d' % (out, os.getpid())
    cmd = (['g++', '-O2', '-shared', '-fPIC', '-std=c++17', '-I', _DIR]
           + cflags + ['-o', tmp, src] + ldflags)
    if verbose:
        print('compiling:', ' '.join(cmd))
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise RuntimeError('capi build failed:\n%s' % proc.stderr[-2000:])
    os.rename(tmp, out)
    return out
