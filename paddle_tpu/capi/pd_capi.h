/* paddle_tpu C inference API.
 *
 * TPU-native analog of the reference's C API
 * (/root/reference/paddle/fluid/inference/capi_exp/pd_inference_api.h):
 * a plain-C surface over the Predictor so non-Python runtimes (C, C++,
 * Go via cgo, Rust via FFI) can serve models. The reference's C API
 * wraps its C++ AnalysisPredictor; here the library embeds a CPython
 * interpreter hosting the XLA-compiled Predictor — the compiled XLA
 * executable is the same object a pure-Python server would run, so
 * there is no extra per-call dispatch beyond one C->Python hop per
 * Run (the hot loop stays inside the compiled program).
 *
 * Threading contract: calls must come from one thread at a time (the
 * library takes the GIL per call; concurrent callers serialize).
 */
#ifndef PADDLE_TPU_CAPI_PD_CAPI_H_
#define PADDLE_TPU_CAPI_PD_CAPI_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct PD_Config PD_Config;
typedef struct PD_Predictor PD_Predictor;

/* Start the embedded interpreter and import the bridge. repo_root is
 * prepended to sys.path (pass the directory containing `paddle_tpu/`,
 * or NULL if the package is importable already). Idempotent.
 * Returns 0 on success; on failure PD_GetLastError() explains. */
int PD_Init(const char* repo_root);

/* Message of the most recent failure on this thread's calls (static
 * storage; valid until the next failing call). Never NULL. */
const char* PD_GetLastError(void);

PD_Config* PD_ConfigCreate(void);
void PD_ConfigSetModel(PD_Config* config, const char* model_dir);
/* device: "cpu" or "tpu" (default). CPU selection must happen before
 * the first predictor is created in the process. */
void PD_ConfigSetDevice(PD_Config* config, const char* device);
void PD_ConfigDestroy(PD_Config* config);

/* NULL on failure (see PD_GetLastError). The config stays owned by the
 * caller and may be destroyed right after. */
PD_Predictor* PD_PredictorCreate(const PD_Config* config);

/* Number of inputs; -1 only on error. Models saved without an input
 * spec report positional names (input_0, input_1, ...). */
int PD_PredictorGetInputNum(const PD_Predictor* predictor);
/* Copy input idx's name into buf (NUL-terminated, truncated to cap).
 * Returns the full name length, or -1 on error. */
int PD_PredictorGetInputName(const PD_Predictor* predictor, int idx,
                             char* buf, int cap);

/* Copy a float32 row-major tensor in as input `name`. Returns 0 on
 * success. */
int PD_PredictorSetInputFloat(PD_Predictor* predictor, const char* name,
                              const float* data, const int64_t* shape,
                              int ndim);

/* Execute. Compiles on first call per input signature (cached after —
 * the AnalysisPredictor "analysis" step); returns 0 on success. */
int PD_PredictorRun(PD_Predictor* predictor);

int PD_PredictorGetOutputNum(const PD_Predictor* predictor);
/* Write output idx's dims into shape (up to cap entries). Returns the
 * tensor rank, or -1 on error. */
int PD_PredictorGetOutputShape(const PD_Predictor* predictor, int idx,
                               int64_t* shape, int cap);
/* Copy output idx as float32 into buf (up to cap elements). Returns
 * the total element count, or -1 on error. */
int64_t PD_PredictorGetOutputFloat(const PD_Predictor* predictor, int idx,
                                   float* buf, int64_t cap);

void PD_PredictorDestroy(PD_Predictor* predictor);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* PADDLE_TPU_CAPI_PD_CAPI_H_ */
