#!/usr/bin/env bash
# Build and run the Go inference demo against a saved model — the
# CI-runnable path for the goapi shim (tests/test_goapi.py drives the
# same steps under pytest and compares outputs numerically).
#
# Usage: run_demo.sh [model_dir]
#   With no model_dir, a small MLP is jit.save'd to a temp dir first
#   (the same recipe as tests/test_capi.py's saved_model fixture).
set -euo pipefail

HERE="$(cd "$(dirname "$0")" && pwd)"
CAPI_DIR="$(dirname "$HERE")"
REPO="$(cd "$CAPI_DIR/../.." && pwd)"
PY="${PYTHON:-python}"

command -v go >/dev/null || { echo "go toolchain not found" >&2; exit 2; }

LIB="$($PY -c 'from paddle_tpu.capi import build_capi; print(build_capi())')"
LIBDIR="$(dirname "$LIB")"

MODEL="${1:-}"
if [ -z "$MODEL" ]; then
  MODEL="$(mktemp -d)/mlp"
  $PY - "$MODEL" <<'EOF'
import sys
import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.static import InputSpec
paddle.seed(1234)
model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
model.eval()
paddle.jit.save(model, sys.argv[1],
                input_spec=[InputSpec([2, 8], name='features')])
EOF
fi

cd "$HERE"
export CGO_ENABLED=1
export CGO_CFLAGS="-I$CAPI_DIR"
export CGO_LDFLAGS="-L$LIBDIR -lpaddle_tpu_c -Wl,-rpath,$LIBDIR"
go build -o "${GOAPI_DEMO_BIN:-./demo_client}" ./cmd/demo
unset XLA_FLAGS
exec "${GOAPI_DEMO_BIN:-./demo_client}" "$REPO" "$MODEL"
