// Demo inference client over the goapi package — the Go analog of the
// C client embedded in tests/test_capi.py, printing the identical
// rank/dim/value format so both are checked by the same comparison.
package main

import (
	"fmt"
	"os"

	"paddletpu/goapi"
)

func fail(err error, code int) {
	fmt.Fprintln(os.Stderr, err)
	os.Exit(code)
}

func main() {
	if len(os.Args) < 3 {
		fmt.Fprintln(os.Stderr, "usage: demo <repo_root> <model_dir>")
		os.Exit(2)
	}
	if err := goapi.Init(os.Args[1]); err != nil {
		fail(err, 3)
	}
	cfg := goapi.NewConfig()
	cfg.SetModel(os.Args[2])
	cfg.SetDevice("cpu")
	pred, err := goapi.NewPredictor(cfg)
	cfg.Destroy()
	if err != nil {
		fail(err, 4)
	}
	defer pred.Destroy()

	names, err := pred.GetInputNames()
	if err != nil || len(names) < 1 {
		fail(fmt.Errorf("inputs: %v", err), 5)
	}
	data := make([]float32, 2*8)
	for i := range data {
		data[i] = 0.125 * float32(i-8)
	}
	if err := pred.SetInputFloat32(names[0], data,
		[]int64{2, 8}); err != nil {
		fail(err, 6)
	}
	if err := pred.Run(); err != nil {
		fail(err, 6)
	}
	shape, err := pred.GetOutputShape(0)
	if err != nil {
		fail(err, 8)
	}
	out, err := pred.GetOutputFloat32(0)
	if err != nil {
		fail(err, 9)
	}
	fmt.Printf("rank %d\n", len(shape))
	for _, d := range shape {
		fmt.Printf("dim %d\n", d)
	}
	for _, v := range out {
		fmt.Printf("%.8e\n", v)
	}
}
