// Package goapi is the Go inference API over libpaddle_tpu_c.so —
// the analog of the reference framework's paddle/fluid/inference/goapi
// (config -> predictor -> input/output tensors), reduced to the flat C
// surface in ../pd_capi.h.
//
// Build: the shared library is produced by paddle_tpu.capi.build_capi();
// point cgo at it, e.g.
//
//	CGO_CFLAGS="-I/path/to/paddle_tpu/capi" \
//	CGO_LDFLAGS="-L$LIBDIR -lpaddle_tpu_c -Wl,-rpath,$LIBDIR" \
//	go build ./...
//
// Threading contract is the C one: calls serialize on the embedded
// interpreter's GIL — use one Predictor from one goroutine at a time.
package goapi

/*
#include <stdint.h>
#include <stdlib.h>
#include "pd_capi.h"
*/
import "C"

import (
	"fmt"
	"unsafe"
)

// lastError wraps PD_GetLastError into a Go error with a call label.
func lastError(op string) error {
	return fmt.Errorf("%s: %s", op, C.GoString(C.PD_GetLastError()))
}

// Init starts the embedded interpreter (idempotent). repoRoot is the
// directory containing the paddle_tpu package, or "" if importable.
func Init(repoRoot string) error {
	var cRoot *C.char
	if repoRoot != "" {
		cRoot = C.CString(repoRoot)
		defer C.free(unsafe.Pointer(cRoot))
	}
	if C.PD_Init(cRoot) != 0 {
		return lastError("Init")
	}
	return nil
}

// Config mirrors the reference goapi Config: model location + device.
type Config struct {
	c *C.PD_Config
}

func NewConfig() *Config {
	return &Config{c: C.PD_ConfigCreate()}
}

// SetModel points the config at a jit.save'd model directory/prefix.
func (cfg *Config) SetModel(modelDir string) {
	cDir := C.CString(modelDir)
	defer C.free(unsafe.Pointer(cDir))
	C.PD_ConfigSetModel(cfg.c, cDir)
}

// SetDevice selects "cpu" or "tpu" (default). CPU must be chosen
// before the first predictor exists in the process.
func (cfg *Config) SetDevice(device string) {
	cDev := C.CString(device)
	defer C.free(unsafe.Pointer(cDev))
	C.PD_ConfigSetDevice(cfg.c, cDev)
}

// Destroy releases the config (the predictor does not keep it).
func (cfg *Config) Destroy() {
	if cfg.c != nil {
		C.PD_ConfigDestroy(cfg.c)
		cfg.c = nil
	}
}

// Predictor mirrors the reference goapi Predictor.
type Predictor struct {
	p *C.PD_Predictor
}

func NewPredictor(cfg *Config) (*Predictor, error) {
	p := C.PD_PredictorCreate(cfg.c)
	if p == nil {
		return nil, lastError("NewPredictor")
	}
	return &Predictor{p: p}, nil
}

func (pred *Predictor) GetInputNum() (int, error) {
	n := int(C.PD_PredictorGetInputNum(pred.p))
	if n < 0 {
		return 0, lastError("GetInputNum")
	}
	return n, nil
}

// GetInputNames returns every input name in declaration order (the
// reference goapi's GetInputNames over GetInputNameById).
func (pred *Predictor) GetInputNames() ([]string, error) {
	n, err := pred.GetInputNum()
	if err != nil {
		return nil, err
	}
	names := make([]string, 0, n)
	buf := make([]C.char, 256)
	for i := 0; i < n; i++ {
		ln := C.PD_PredictorGetInputName(pred.p, C.int(i), &buf[0],
			C.int(len(buf)))
		if ln < 0 {
			return nil, lastError("GetInputNames")
		}
		names = append(names, C.GoString(&buf[0]))
	}
	return names, nil
}

// SetInputFloat32 copies a row-major float32 tensor in as input `name`
// (the reference Tensor.CopyFromCpu + Reshape collapsed into one call).
func (pred *Predictor) SetInputFloat32(name string, data []float32,
	shape []int64) error {
	want := int64(1)
	for _, d := range shape {
		want *= d
	}
	if want != int64(len(data)) {
		return fmt.Errorf("SetInputFloat32: %d elements for shape %v",
			len(data), shape)
	}
	cName := C.CString(name)
	defer C.free(unsafe.Pointer(cName))
	var dPtr *C.float
	if len(data) > 0 {
		dPtr = (*C.float)(unsafe.Pointer(&data[0]))
	}
	var sPtr *C.int64_t
	if len(shape) > 0 {
		sPtr = (*C.int64_t)(unsafe.Pointer(&shape[0]))
	}
	if C.PD_PredictorSetInputFloat(pred.p, cName, dPtr, sPtr,
		C.int(len(shape))) != 0 {
		return lastError("SetInputFloat32")
	}
	return nil
}

// Run executes the model (compiles on first call per signature).
func (pred *Predictor) Run() error {
	if C.PD_PredictorRun(pred.p) != 0 {
		return lastError("Run")
	}
	return nil
}

func (pred *Predictor) GetOutputNum() (int, error) {
	n := int(C.PD_PredictorGetOutputNum(pred.p))
	if n < 0 {
		return 0, lastError("GetOutputNum")
	}
	return n, nil
}

// GetOutputShape returns output idx's dims.
func (pred *Predictor) GetOutputShape(idx int) ([]int64, error) {
	buf := make([]C.int64_t, 16)
	rank := C.PD_PredictorGetOutputShape(pred.p, C.int(idx), &buf[0],
		C.int(len(buf)))
	if rank < 0 {
		return nil, lastError("GetOutputShape")
	}
	shape := make([]int64, int(rank))
	for i := range shape {
		shape[i] = int64(buf[i])
	}
	return shape, nil
}

// GetOutputFloat32 copies output idx back as float32 (the reference
// Tensor.CopyToCpu).
func (pred *Predictor) GetOutputFloat32(idx int) ([]float32, error) {
	shape, err := pred.GetOutputShape(idx)
	if err != nil {
		return nil, err
	}
	n := int64(1)
	for _, d := range shape {
		n *= d
	}
	out := make([]float32, n)
	var ptr *C.float
	if n > 0 {
		ptr = (*C.float)(unsafe.Pointer(&out[0]))
	}
	got := C.PD_PredictorGetOutputFloat(pred.p, C.int(idx), ptr,
		C.int64_t(n))
	if got < 0 {
		return nil, lastError("GetOutputFloat32")
	}
	return out[:got], nil
}

// Destroy releases the predictor.
func (pred *Predictor) Destroy() {
	if pred.p != nil {
		C.PD_PredictorDestroy(pred.p)
		pred.p = nil
	}
}
