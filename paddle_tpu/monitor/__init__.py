"""Unified observability (reference: paddle/fluid/platform/monitor.h
StatRegistry/STAT_ADD grown into a scrapeable subsystem).

Four layers, each usable alone:

- ``registry``  — thread-safe Counter/Gauge/Histogram families with
  labels, get-or-create semantics, and a near-zero-cost disabled path;
- ``export``    — Prometheus text exposition + JSON snapshots;
- ``server``    — MetricsServer: stdlib http.server on /metrics,
  /healthz (and /metrics.json) for curl / Prometheus scrapes;
- ``runtime``   — RuntimeSampler: host RSS, live jax array bytes,
  device count, tracing-cache sizes on a background thread;
- ``tracing``   — distributed span tracer (trace_id/span_id/parent,
  contextvars propagation, cross-process context injection) with a
  flight-recorder ring served at /debug/traces and exportable as
  Chrome-trace JSON for profiler.merge_traces;
- ``perf``      — performance introspection: CompileWatchdog (recompile
  attribution + warmup barrier), StepTimeline (step phase split +
  straggler detection), and the cost-model roofline/MFU estimator;
- ``federation``— FleetCollector: pull-based cross-process metric
  federation (in-proc registries + HTTP /metrics.json targets, merged
  counters/gauges/histograms, staleness + fleet_target_up liveness)
  served at /fleet;
- ``alerts``    — AlertManager: declarative threshold + multi-window
  SLO burn-rate rules with a pending→firing→resolved lifecycle,
  flight dumps on firing edges, served at /alerts;
- ``events``    — RequestLog: ONE canonical wide event per serving
  request (lifecycle timestamps, tenant, KV page·seconds, failover
  history) in a bounded ring + rotating JSONL sink, served at
  /requests; TenantLabeler bounds per-tenant metric cardinality.

Built-in instrumentation (resilient RPC, the serving engine, PS/graph
clients, hapi TelemetryCallback, the dryrun telemetry line) feeds
``default_registry()``; point a MetricsServer at it and scrape. See
docs/observability.md for naming/cardinality conventions and the
metric inventory.
"""
from .registry import (Counter, Gauge, Histogram, MetricRegistry,
                       default_registry, exponential_buckets,
                       set_default_registry)
from .export import schema_of, to_dict, to_json, to_prometheus
from .server import MetricsServer
from .runtime import RuntimeSampler
from .tracing import (FlightRecorder, Span, TraceRetention, Tracer,
                      default_tracer, set_default_tracer,
                      spans_to_chrome)
from .events import (REQUEST_EVENT_FIELDS, RequestLog, TenantLabeler,
                     default_request_log, set_default_request_log)
from . import events
from .federation import FleetCollector, ScrapeTarget, merge_snapshots
from .alerts import (AlertManager, AlertRule, BurnRateRule,
                     ThresholdRule)
from . import alerts
from . import federation
from . import perf
from . import telemetry
from . import tracing
from .perf import CompileWatchdog, RecompileError, StepTimeline

__all__ = ['MetricRegistry', 'Counter', 'Gauge', 'Histogram',
           'exponential_buckets', 'default_registry',
           'set_default_registry', 'to_prometheus', 'to_dict', 'to_json',
           'schema_of', 'MetricsServer', 'RuntimeSampler', 'telemetry',
           'Tracer', 'Span', 'FlightRecorder', 'default_tracer',
           'set_default_tracer', 'spans_to_chrome', 'tracing', 'perf',
           'CompileWatchdog', 'RecompileError', 'StepTimeline',
           'FleetCollector', 'ScrapeTarget', 'merge_snapshots',
           'AlertManager', 'AlertRule', 'ThresholdRule', 'BurnRateRule',
           'federation', 'alerts', 'TraceRetention', 'RequestLog',
           'TenantLabeler', 'REQUEST_EVENT_FIELDS', 'default_request_log',
           'set_default_request_log', 'events']
