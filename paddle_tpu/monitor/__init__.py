"""Unified observability (reference: paddle/fluid/platform/monitor.h
StatRegistry/STAT_ADD grown into a scrapeable subsystem).

Four layers, each usable alone:

- ``registry``  — thread-safe Counter/Gauge/Histogram families with
  labels, get-or-create semantics, and a near-zero-cost disabled path;
- ``export``    — Prometheus text exposition + JSON snapshots;
- ``server``    — MetricsServer: stdlib http.server on /metrics,
  /healthz (and /metrics.json) for curl / Prometheus scrapes;
- ``runtime``   — RuntimeSampler: host RSS, live jax array bytes,
  device count, tracing-cache sizes on a background thread.

Built-in instrumentation (resilient RPC, the serving engine, PS/graph
clients, hapi TelemetryCallback, the dryrun telemetry line) feeds
``default_registry()``; point a MetricsServer at it and scrape. See
docs/observability.md for naming/cardinality conventions and the
metric inventory.
"""
from .registry import (Counter, Gauge, Histogram, MetricRegistry,
                       default_registry, exponential_buckets,
                       set_default_registry)
from .export import schema_of, to_dict, to_json, to_prometheus
from .server import MetricsServer
from .runtime import RuntimeSampler
from . import telemetry

__all__ = ['MetricRegistry', 'Counter', 'Gauge', 'Histogram',
           'exponential_buckets', 'default_registry',
           'set_default_registry', 'to_prometheus', 'to_dict', 'to_json',
           'schema_of', 'MetricsServer', 'RuntimeSampler', 'telemetry']
