"""Wide-event request log: ONE canonical structured record per serving
request.

Metrics answer "what is the fleet's p99"; this module answers "why was
request X slow" and "which tenant held the KV pool". Every serving
request — engine-direct or gateway-fronted — emits exactly one wide
event at completion carrying the whole request lifecycle: identity
(request_id, tenant, trace_id), the four lifecycle timestamps, queue
wait, prefill shape, token counts, prefix-cache and speculation
outcomes, the integrated KV page·seconds the request held, the failover
history, and the terminal outcome. The trace_id links the event to the
tail-retained span tree (tracing.TraceRetention), closing the
exemplar → full-trace join.

Discipline (matching registry/tracing):
- the disabled fast path is one attribute load + one branch (`enabled`
  is a plain attribute; disabled ``emit`` returns immediately);
- the in-memory ring is bounded and evictions are counted, never
  silent; the optional JSONL sink rotates at a size cap;
- the schema is single-source: REQUEST_EVENT_FIELDS below is the only
  place field names are declared, emission validates against it at
  runtime, and tools/graftlint's events checker diffs it two-way
  against tools/request_event_baseline.json so a renamed or dropped
  field breaks the gate.

Tenant labels are BOUNDED by construction: TenantLabeler interns the
first `cap` distinct tenants it sees and folds everything else into a
fixed set of hashed ``overflow_<n>`` buckets, so per-tenant metric
families can never explode cardinality no matter what callers send.
"""
import collections
import json
import os
import re
import threading
import zlib

from .registry import default_registry
from .telemetry import record_request_event_schema

__all__ = ['REQUEST_EVENT_FIELDS', 'FIELD_NAMES', 'RequestLog',
           'TenantLabeler', 'ModelLabeler', 'default_request_log',
           'set_default_request_log', 'event_line', 'parse_event_lines',
           'EVENT_LINE_RE']

# The canonical wide-event schema: (field, help). Single-source — the
# runtime validator, the /requests route, tools/request_report.py and
# the graftlint events checker all key off this tuple. Renaming or
# dropping a field here without updating the committed baseline
# (tools/request_event_baseline.json) fails the lint gate.
REQUEST_EVENT_FIELDS = (
    ('request_id', 'engine- or gateway-level request id'),
    ('tenant', 'normalized tenant label (bounded cardinality)'),
    ('model', 'normalized model label (bounded cardinality; None when '
     'the request did not target a named model)'),
    ('priority', 'scheduling priority (int, higher preempts lower)'),
    ('trace_id', 'trace id of the span tree that completed the request'),
    ('arrival_t', 'wall-clock submission time'),
    ('admit_t', 'wall-clock KV-slot admission time (None: never admitted)'),
    ('first_token_t', 'wall-clock time of the first generated token'),
    ('finish_t', 'wall-clock completion time'),
    ('queue_wait_s', 'admit_t - arrival_t'),
    ('prefill_chunks', 'chunked-prefill steps the prompt took'),
    ('prompt_tokens', 'prompt length in tokens'),
    ('output_tokens', 'generated tokens delivered'),
    ('prefix_hit_tokens', 'prompt tokens served from the prefix cache'),
    ('spec_proposed', 'speculative draft tokens proposed'),
    ('spec_accepted', 'speculative draft tokens accepted'),
    ('kv_page_seconds', 'integral of KV pages (slots) held x seconds'),
    ('failovers', 'times the request was re-placed after a replica loss'),
    ('replicas', 'replica endpoints traversed, in placement order'),
    ('outcome',
     "terminal outcome: 'ok' | 'error' | 'rejected' | 'preempted'"),
)

FIELD_NAMES = tuple(name for name, _ in REQUEST_EVENT_FIELDS)
_FIELD_SET = frozenset(FIELD_NAMES)

# parseable dryrun surface, the telemetry_snapshot pattern applied to
# wide events: `request_event(N)[tag]: {json}`
EVENT_LINE_RE = re.compile(r'request_event\((?P<n>\d+)\)'
                           r'\[(?P<tag>[^\]]*)\]:\s*(?P<json>\{.*\})\s*$')


class RequestLog:
    """Bounded ring + rotating JSONL sink of wide request events.

    ``enabled`` is a plain attribute so the hot path pays one load + one
    branch when the log is off (the registry's ~90 ns discipline). All
    ring/sink mutation happens under one private lock — ``emit`` is
    called from engine driver threads and the gateway collector thread
    concurrently (same audit as the gateway's _ttfts deque)."""

    def __init__(self, capacity=2048, sink_path=None,
                 max_sink_bytes=4 << 20, sink_backups=2,
                 registry=None, enabled=True):
        self.enabled = bool(enabled)
        self.capacity = int(capacity)
        self._ring = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.sink_path = sink_path if sink_path is not None \
            else os.environ.get('PADDLE_TPU_REQUEST_LOG') or None
        self.max_sink_bytes = int(max_sink_bytes)
        self.sink_backups = int(sink_backups)
        self._sink_bytes = None  # lazily sized on first write
        reg = registry if registry is not None else default_registry()
        fams = record_request_event_schema(reg)
        self._m_emitted = fams['request_events_total']
        self._m_dropped = fams['request_events_dropped_total']
        self._m_rotations = fams['request_sink_rotations_total']

    def enable(self):
        self.enabled = True

    def disable(self):
        """Freeze the log: ``emit`` becomes a branch; the ring keeps
        whatever it already holds."""
        self.enabled = False

    def emit(self, **fields):
        """Record one wide event. Unknown field names raise — emission
        sites must speak the canonical REQUEST_EVENT_FIELDS schema (the
        graftlint events checker enforces the same statically). Missing
        fields are recorded as None. Returns the canonical dict, or
        None when disabled."""
        if not self.enabled:
            return None
        unknown = [k for k in fields if k not in _FIELD_SET]
        if unknown:
            raise ValueError('unknown wide-event field(s) %s; the schema '
                             'is events.REQUEST_EVENT_FIELDS'
                             % sorted(unknown))
        event = {name: fields.get(name) for name in FIELD_NAMES}
        with self._lock:
            if len(self._ring) == self.capacity:
                self._m_dropped.inc()
            self._ring.append(event)
            self._m_emitted.inc()
            if self.sink_path:
                self._sink_write_locked(event)
        return event

    def _sink_write_locked(self, event):
        line = json.dumps(event, sort_keys=True) + '\n'
        data = line.encode('utf-8')
        if self._sink_bytes is None:
            try:
                self._sink_bytes = os.path.getsize(self.sink_path)
            except OSError:
                self._sink_bytes = 0
        if self._sink_bytes and \
                self._sink_bytes + len(data) > self.max_sink_bytes:
            self._rotate_locked()
        with open(self.sink_path, 'ab') as f:
            f.write(data)
        self._sink_bytes += len(data)

    def _rotate_locked(self):
        """path.(n-1) -> path.n ... path -> path.1; the oldest backup
        falls off the end."""
        for i in range(self.sink_backups, 0, -1):
            src = self.sink_path if i == 1 else \
                '%s.%d' % (self.sink_path, i - 1)
            dst = '%s.%d' % (self.sink_path, i)
            if os.path.exists(src):
                os.replace(src, dst)
        self._sink_bytes = 0
        self._m_rotations.inc()

    def events(self, tenant=None, model=None, outcome=None,
               min_failovers=None, since_ts=None, until_ts=None,
               limit=None):
        """Snapshot of the ring (oldest first), optionally filtered.
        ``since_ts``/``until_ts`` select the half-open arrival-time
        window [since, until) in the log's own clock (the gateway's
        monotonic timestamps) — how the capacity replay loader slices
        one run out of a longer recording. ``limit`` keeps the newest N
        after filtering."""
        with self._lock:
            out = list(self._ring)
        if tenant is not None:
            out = [e for e in out if e['tenant'] == tenant]
        if model is not None:
            out = [e for e in out if e.get('model') == model]
        if outcome is not None:
            out = [e for e in out if e['outcome'] == outcome]
        if min_failovers is not None:
            out = [e for e in out
                   if (e['failovers'] or 0) >= min_failovers]
        if since_ts is not None:
            since_ts = float(since_ts)
            out = [e for e in out
                   if e['arrival_t'] is not None
                   and e['arrival_t'] >= since_ts]
        if until_ts is not None:
            until_ts = float(until_ts)
            out = [e for e in out
                   if e['arrival_t'] is not None
                   and e['arrival_t'] < until_ts]
        if limit is not None and limit >= 0:
            out = out[-limit:]
        return out

    @property
    def dropped(self):
        """Events evicted from the ring since construction."""
        return int(self._m_dropped.value())

    def clear(self):
        with self._lock:
            self._ring.clear()

    def __len__(self):
        with self._lock:
            return len(self._ring)


class TenantLabeler:
    """Bounded-cardinality tenant → metric-label mapping.

    The first `cap` distinct tenants keep their own label; everything
    after that folds into one of `buckets` stable hashed
    ``overflow_<n>`` labels (crc32, not Python's randomized hash, so
    the bucket is the same across processes and restarts). None maps to
    'default'. Worst-case label cardinality: cap + buckets + 1."""

    def __init__(self, cap=16, buckets=4):
        self.cap = int(cap)
        self.buckets = int(buckets)
        self._seen = set()
        self._lock = threading.Lock()

    def label(self, tenant):
        if tenant is None:
            return 'default'
        t = str(tenant)
        with self._lock:
            if t in self._seen:
                return t
            if len(self._seen) < self.cap:
                self._seen.add(t)
                return t
        return 'overflow_%d' % (zlib.crc32(t.encode('utf-8'))
                                % self.buckets)


class ModelLabeler(TenantLabeler):
    """TenantLabeler's bounded-cardinality discipline applied to model
    names, with one semantic difference: None stays None — a request
    that never targeted a named model (every single-model deployment)
    records a null `model` field rather than inventing a default, so
    per-model rollups only ever contain models callers actually named.
    """

    def label(self, model):
        if model is None:
            return None
        return super().label(model)


def _env_enabled():
    v = os.environ.get('PADDLE_TPU_REQUEST_EVENTS', '1').strip().lower()
    return v not in ('0', 'false', 'off', 'no', '')


_default = RequestLog(enabled=_env_enabled())
_default_lock = threading.Lock()


def default_request_log():
    """The process-wide request log every built-in emission site uses
    unless handed an explicit one."""
    return _default


def set_default_request_log(log):
    """Swap the process default (tests/benches); returns the previous
    one. Objects that cached the old log at construction keep it —
    swap BEFORE constructing the engines/gateway under test."""
    global _default
    with _default_lock:
        prev, _default = _default, log
        return prev


def event_line(event, n_devices, tag):
    """One parseable dryrun line embedding a wide event — the
    telemetry_snapshot convention applied to the request log, so driver
    captures carry a schema-complete event for offline joins
    (tools/request_report.py parses these alongside JSONL sinks)."""
    return 'request_event(%d)%s: %s' % (
        n_devices, tag, json.dumps(event, sort_keys=True,
                                   separators=(',', ':')))


def parse_event_lines(text):
    """[(tag, event dict)] from captured driver output (tolerates
    interleaved non-event lines)."""
    out = []
    for line in (text or '').splitlines():
        m = EVENT_LINE_RE.search(line)
        if not m:
            continue
        try:
            out.append((m.group('tag'), json.loads(m.group('json'))))
        except ValueError:
            continue
    return out
