"""Cost-model estimator: analytic FLOPs/bytes -> roofline + MFU.

XLA's compiled executables carry their own cost model
(``compiled.cost_analysis()``: flops and bytes accessed of the
optimized program). This module turns that into the numbers VERDICT
keeps asking benches for:

  arithmetic intensity  — flops / bytes accessed;
  roofline bound        — 'compute' when intensity clears the ridge
                          (peak_flops / peak_bandwidth), else
                          'bandwidth';
  ideal_step_s          — max(flops/peak, bytes/bw), the roofline floor;
  mfu_est               — analytic flops / measured step time / peak,
                          given a measured wall time.

Peaks follow the repo's existing conventions (bench.py,
tools/profile_analysis.py): v5e bf16 197 TFLOP/s + 819 GB/s HBM; the
CPU numbers are nominal comparators so degraded smoke rows stay
self-consistent, not real hardware specs.

All jax imports are deferred — the module stays stdlib-importable for
the schema tooling.
"""

__all__ = ['PEAKS', 'platform_peaks', 'cost_of', 'roofline', 'estimate',
           'record']

# backend -> (peak FLOP/s, peak bytes/s)
PEAKS = {
    'tpu': (197e12, 819e9),     # v5e bf16 / HBM (bench.py convention)
    'gpu': (312e12, 2039e9),    # A100 bf16 / HBM2e nominal
    'cpu': (1e12, 50e9),        # nominal comparator (bench.py uses 1e12)
}


def platform_peaks(platform=None, peak_flops=None, peak_bandwidth=None):
    """(platform, peak_flops, peak_bytes_per_s) with overrides applied;
    platform defaults to the active jax backend ('cpu' without jax)."""
    if platform is None:
        try:
            import jax
            platform = jax.default_backend()
        except Exception:
            platform = 'cpu'
    pf, pb = PEAKS.get(platform, PEAKS['cpu'])
    return (platform,
            float(peak_flops) if peak_flops else pf,
            float(peak_bandwidth) if peak_bandwidth else pb)


def cost_of(compiled):
    """{'flops', 'bytes_accessed'} from a jax Compiled's cost analysis;
    None when the backend exposes none. Tolerates both the dict and the
    [dict] return shapes across jax versions."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = float(ca.get('flops', 0.0) or 0.0)
    nbytes = float(ca.get('bytes accessed', 0.0) or 0.0)
    if flops <= 0.0 and nbytes <= 0.0:
        return None
    return {'flops': flops, 'bytes_accessed': nbytes}


def roofline(flops, bytes_accessed, platform=None, peak_flops=None,
             peak_bandwidth=None):
    """Roofline classification of an analytic (flops, bytes) point."""
    platform, pf, pb = platform_peaks(platform, peak_flops,
                                      peak_bandwidth)
    intensity = (flops / bytes_accessed) if bytes_accessed > 0 \
        else float('inf')
    ridge = pf / pb
    return {
        'platform': platform,
        'peak_flops': pf,
        'peak_bandwidth': pb,
        'arithmetic_intensity': intensity,
        'ridge_intensity': ridge,
        'roofline_bound': 'compute' if intensity >= ridge
        else 'bandwidth',
        'ideal_step_s': max(flops / pf, bytes_accessed / pb),
    }


def estimate(compiled_or_fn, args=None, step_seconds=None, platform=None,
             peak_flops=None, peak_bandwidth=None):
    """Full cost-model estimate of a compiled program.

    Pass a jax Compiled directly, or a callable plus example `args` (it
    is jitted, lowered and compiled here — the persistent compilation
    cache makes the repeat cheap). Returns the cost_of + roofline
    fields, plus 'measured_step_s' / 'mfu_est' / 'roofline_frac' when a
    measured wall time is given; None when no cost model is available.
    """
    compiled = compiled_or_fn
    if args is not None:
        import jax
        compiled = jax.jit(compiled_or_fn).lower(*args).compile()
    cost = cost_of(compiled)
    if cost is None:
        return None
    est = dict(cost)
    est.update(roofline(cost['flops'], cost['bytes_accessed'],
                        platform=platform, peak_flops=peak_flops,
                        peak_bandwidth=peak_bandwidth))
    if step_seconds and step_seconds > 0:
        est['measured_step_s'] = float(step_seconds)
        est['mfu_est'] = cost['flops'] / step_seconds / est['peak_flops']
        ideal = est['ideal_step_s']
        est['roofline_frac'] = (ideal / step_seconds) if ideal else 0.0
    return est


def record(est, registry=None):
    """Publish an estimate onto the perf gauges (mfu_est, arithmetic
    intensity, roofline bound as 0=bandwidth/1=compute) so telemetry
    snapshots carry the cost-model block."""
    from ..registry import default_registry
    from ..telemetry import record_perf_schema
    if not est:
        return None
    reg = registry if registry is not None else default_registry()
    fams = record_perf_schema(reg)
    if 'mfu_est' in est:
        fams['perf_mfu_est'].set(est['mfu_est'])
    intensity = est.get('arithmetic_intensity')
    if intensity is not None and intensity != float('inf'):
        fams['perf_arithmetic_intensity'].set(intensity)
    fams['perf_roofline_bound'].set(
        1.0 if est.get('roofline_bound') == 'compute' else 0.0)
    return reg
