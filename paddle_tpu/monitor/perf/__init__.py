"""Performance introspection: the third leg of the monitor subsystem.

Three components, each usable alone (stdlib at import; jax touched only
when live):

- ``watchdog``  — CompileWatchdog: jax.monitoring compile listeners,
  recompile attribution (callsite + abstract-shape signature), warmup
  barrier with flight-dump + optional strict hard-fail;
- ``timeline``  — StepTimeline: data-wait / host-dispatch /
  device-blocked phase split with rolling percentiles and straggler
  detection;
- ``costmodel`` — XLA cost-analysis -> arithmetic intensity, roofline
  bound, ideal step time, and MFU estimates.

All metric families are single-sourced in
``monitor.telemetry.PERF_FAMILIES`` (registered via
``record_perf_schema``) so the dryrun schema gate covers them without a
perf run. See docs/observability.md for the family/label inventory.
"""
from . import costmodel
from .timeline import PHASES, StepTimeline
from .watchdog import COMPILE_EVENTS, CompileWatchdog, RecompileError

__all__ = ['CompileWatchdog', 'RecompileError', 'COMPILE_EVENTS',
           'StepTimeline', 'PHASES', 'costmodel']
