"""CompileWatchdog: count, time, and attribute every jit compile.

Hooks ``jax.monitoring``'s event-duration listeners (graceful no-op on
a jaxlib without them): each jit compilation fires three duration
events — jaxpr trace, MLIR lowering, backend compile — which land in
the ``perf_compiles_total`` counter and ``perf_compile_seconds``
histogram, labeled by stage.

The steady-state contract is the interesting part. After the owner
declares a warmup barrier (``declare_warmup``), ANY further backend
compile is a recompile: the watchdog walks the live stack to attribute
it to the triggering callsite and the abstract-shape signature that
forced the retrace (the pjit frame's ClosedJaxpr ``in_avals``), bumps
``perf_recompiles_total``, pushes a ``perf.recompile`` record into the
tracer's flight ring and fires a throttled flight dump — and, under
``PADDLE_TPU_COMPILE_STRICT=1`` (or ``strict=True``), raises
:class:`RecompileError` straight out of the offending dispatch.

Listeners are process-global: every watchdog sees every compile in the
process. The optional ``owner`` filter keeps multi-engine processes
honest — a recompile is only charged to a watchdog whose owner object
appears on the compiling stack (so replica A's warm barrier is not
tripped by replica B's first compile). With no owner, every post-warmup
compile counts.

Persistent-cache composition: jax fires the backend-compile duration
event even when ``compiler.compile_or_get_cached`` was served from the
persistent compilation cache (the event wraps the whole call), so a
cache-hit *reload* after ``declare_warmup()`` used to count as a
recompile. The watchdog now diffs ``framework.compile_cache``'s
per-thread hit/miss tallies around every compile event: a fresh hit is
exported as ``perf_persistent_cache_hits_total`` and exempted from the
recompile path; a miss (or a cache-less compile) stays a violation.
Each watchdog keeps its own per-thread marks, so several watchdogs on
one registry classify every compile independently and identically.
"""
import contextlib
import os
import sys
import threading
import time

from ..registry import default_registry
from ..telemetry import record_perf_schema
from .. import tracing as _tracing

__all__ = ['CompileWatchdog', 'RecompileError', 'COMPILE_EVENTS']

# jax.monitoring event -> stage label (closed set; docs/observability.md)
COMPILE_EVENTS = {
    '/jax/core/compile/jaxpr_trace_duration': 'trace',
    '/jax/core/compile/jaxpr_to_mlir_module_duration': 'lower',
    '/jax/core/compile/backend_compile_duration': 'compile',
}

_KINDS = ('trace', 'lower', 'compile')


class RecompileError(RuntimeError):
    """A jit recompile happened after a declared warmup barrier while
    the watchdog ran in strict mode."""


def _is_internal_frame(filename):
    """Frames that can never be the *triggering* callsite: jax's own
    machinery, contextlib plumbing, and this package."""
    f = filename.replace('\\', '/')
    return ('/jax/' in f or '/jaxlib/' in f or f.endswith('contextlib.py')
            or '/monitor/perf/' in f or f.endswith('threading.py'))


def _walk_attribution(max_depth=120):
    """(callsite, signature, owner_candidates) from the live stack.

    Called inside jax's compile path, so the stack below us holds the
    pjit frame whose local ``jaxpr`` (a ClosedJaxpr) carries the
    abstract input shapes that keyed this compilation, and further down
    the first non-jax frame is the dispatch that triggered it.
    ``owner_candidates`` collects every ``self`` seen on non-jax frames
    so a watchdog bound to an engine can tell its own dispatches from a
    sibling replica's.
    """
    callsite = signature = None
    owners = []
    try:
        f = sys._getframe(2)
    except Exception:
        return callsite, signature, owners
    depth = 0
    while f is not None and depth < max_depth:
        code = f.f_code
        if signature is None:
            jaxpr = f.f_locals.get('jaxpr')
            avals = getattr(jaxpr, 'in_avals', None)
            if avals is not None:
                try:
                    signature = ', '.join(a.str_short() for a in avals)
                except Exception:
                    signature = repr(avals)
                signature = signature[:400]
        if not _is_internal_frame(code.co_filename):
            if callsite is None:
                callsite = '%s:%d:%s' % (code.co_filename, f.f_lineno,
                                         code.co_name)
            slf = f.f_locals.get('self')
            if slf is not None:
                owners.append(slf)
        f = f.f_back
        depth += 1
    return callsite, signature, owners


class CompileWatchdog:
    """Per-registry jit-compilation accountant with a warmup barrier.

        wd = CompileWatchdog()           # default registry + tracer
        ... compile everything once ...
        wd.declare_warmup('serving steady state')
        # any compile from here on is a counted, attributed recompile

    ``enabled`` is a plain attribute checked first in the listener (the
    registry's one-load+branch discipline); ``close()`` unregisters the
    listener — always pair construction with close() in tests. When
    jax.monitoring is unavailable the watchdog constructs fine and
    ``active`` stays False.
    """

    def __init__(self, registry=None, tracer=None, strict=None,
                 owner=None, name='', clock=None, max_records=64):
        self.registry = registry if registry is not None \
            else default_registry()
        fams = record_perf_schema(self.registry)
        self._m_compiles = {k: fams['perf_compiles_total'].labels(k)
                            for k in _KINDS}
        self._h_seconds = {k: fams['perf_compile_seconds'].labels(k)
                           for k in _KINDS}
        self._m_recompiles = fams['perf_recompiles_total']
        self._m_cache_hits = fams['perf_persistent_cache_hits_total']
        self._m_cache_misses = fams['perf_persistent_cache_misses_total']
        try:
            from ...framework import compile_cache as _cc
        except Exception:
            _cc = None
        self._cc = _cc
        self._cc_marks = threading.local()  # this watchdog's own marks
        self.enabled = True
        self.armed = False
        self.warmup_label = None
        self.name = name
        self.owner = owner
        if strict is None:
            strict = os.environ.get('PADDLE_TPU_COMPILE_STRICT') == '1'
        self.strict = bool(strict)
        self.max_records = int(max_records)
        self.counts = {k: 0 for k in _KINDS}
        self.recompile_count = 0    # this watchdog's own violations
        self.records = []           # recompile attributions, oldest first
        self._tracer = tracer       # None -> default_tracer() at use
        self._clock = clock or time.time
        self._lock = threading.Lock()
        self._listener = None
        self._install()

    # ---- listener lifecycle -------------------------------------------

    def _install(self):
        try:
            from jax._src import monitoring as _mon
            register = _mon.register_event_duration_secs_listener
        except Exception:
            return              # jaxlib without jax.monitoring: no-op

        def _listen(event, duration, **kw):
            if self.enabled:
                self._on_event(event, duration)

        try:
            register(_listen)
            self._listener = _listen
        except Exception:
            self._listener = None

    @property
    def active(self):
        """True while the jax.monitoring listener is registered."""
        return self._listener is not None

    def close(self):
        """Stop counting and unregister the listener (idempotent)."""
        self.enabled = False
        listener, self._listener = self._listener, None
        if listener is None:
            return
        try:
            from jax._src import monitoring as _mon
            _mon._unregister_event_duration_listener_by_callback(listener)
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ---- warmup barrier -----------------------------------------------

    def declare_warmup(self, label='warmup'):
        """Arm recompile accounting: every backend compile from now on
        is a steady-state violation."""
        self.warmup_label = label
        self.armed = True
        return self

    def disarm(self):
        self.armed = False

    @contextlib.contextmanager
    def suspended(self):
        """Temporarily disarm — for deliberate compiles (cost-model
        lowering, bench warm-compile timing) inside a warm window."""
        was = self.armed
        self.armed = False
        try:
            yield self
        finally:
            self.armed = was

    # ---- event path ---------------------------------------------------

    def _on_event(self, event, duration):
        kind = COMPILE_EVENTS.get(event)
        if kind is None:
            return
        try:
            with self._lock:
                self.counts[kind] += 1
            self._m_compiles[kind].inc()
            self._h_seconds[kind].observe(float(duration))
        except Exception:
            return              # accounting must never break a compile
        if kind != 'compile':
            return
        cache_hit = False
        try:
            cache_hit = self._classify_cache()
        except Exception:
            pass                # classification must never break a compile
        if self.armed and not cache_hit:
            self._on_recompile(float(duration))

    def _classify_cache(self):
        """Diff compile_cache's per-thread lookup tallies against this
        watchdog's marks: returns True when the compile event being
        handled was a persistent-cache HIT (exempt from the recompile
        rule), publishing the hit/miss counters along the way. The
        lookup event fires on the compiling thread before the duration
        event does, so the fresh delta belongs to this compile."""
        if self._cc is None:
            return False
        hits, misses, last = self._cc.thread_state()
        marks = self._cc_marks
        prev = getattr(marks, 'state', None)
        marks.state = (hits, misses)
        if prev is None:
            # first compile event this watchdog sees on this thread:
            # only the lookup belonging to THIS compile is fresh —
            # earlier lookups predate the watchdog (or its thread) and
            # must not be charged to it
            dh = 1 if last == 'hit' else 0
            dm = 1 if last == 'miss' else 0
        else:
            dh = hits - prev[0]
            dm = misses - prev[1]
        if dh > 0:
            self._m_cache_hits.inc(dh)
        if dm > 0:
            self._m_cache_misses.inc(dm)
        return dh > 0 and dm == 0

    def _on_recompile(self, duration):
        callsite, signature, owners = _walk_attribution()
        if self.owner is not None and not any(o is self.owner
                                              for o in owners):
            return              # someone else's compile, not a violation
        rec = {'time': self._clock(), 'duration_s': duration,
               'after_warmup': self.warmup_label, 'callsite': callsite,
               'signature': signature, 'watchdog': self.name}
        with self._lock:
            self.recompile_count += 1
            self.records.append(rec)
            del self.records[:-self.max_records]
        self._m_recompiles.inc()
        tracer = self._tracer if self._tracer is not None \
            else _tracing.default_tracer()
        try:
            # drop the attribution into the flight ring so the dump
            # that follows carries WHO retraced, not just that one did
            tracer.recorder.record({'name': 'perf.recompile',
                                    'start': rec['time'],
                                    'duration': duration,
                                    'tags': dict(rec)})
            tracer.recorder.maybe_dump('recompile')
        except Exception:
            pass
        if self.strict:
            raise RecompileError(
                'recompile after warmup barrier %r: callsite=%s '
                'signature=%s (set PADDLE_TPU_COMPILE_STRICT=0 or fix '
                'the retrace)' % (self.warmup_label, callsite, signature))

    # ---- inspection ---------------------------------------------------

    @property
    def recompiles(self):
        """Violations charged to THIS watchdog (the registry counter is
        shared when several watchdogs publish to one registry)."""
        return self.recompile_count

    def report(self):
        """Plain-dict summary for logs / bench rows."""
        with self._lock:
            return {'counts': dict(self.counts),
                    'recompiles': self.recompiles,
                    'armed': self.armed,
                    'warmup_label': self.warmup_label,
                    'records': [dict(r) for r in self.records]}
