"""StepTimeline: split train/serve steps into host-visible phases.

A step's wall time decomposes into what the host can measure without a
profiler:

  data_wait      — blocked on the input pipeline (loader ``next()``);
  host_dispatch  — Python + tracing-cache lookup + async enqueue of the
                   jitted computation (returns before the device runs);
  device_block   — blocked on device results (``device_get`` /
                   ``.numpy()`` — the dispatch-to-block-until-ready gap,
                   which IS the device time once dispatch is async);
  other          — the remainder when an explicit wall time is given.

Each phase lands in the ``perf_step_phase_seconds`` histogram (labeled,
with trace-exemplar links into the active tracer span) and a rolling
window that serves percentiles and straggler detection: a step slower
than ``straggler_factor`` x the rolling median bumps
``perf_stragglers_total`` and drops a ``perf.straggler`` span into the
flight ring.

The clock is injectable (tests drive a fake), and ``enabled=False``
reduces every call to one attribute load + branch — the registry's
disabled-path discipline.
"""
import collections
import contextlib
import time

from ..registry import default_registry
from ..telemetry import record_perf_schema
from .. import tracing as _tracing

__all__ = ['StepTimeline', 'PHASES', 'percentile']

PHASES = ('data_wait', 'host_dispatch', 'device_block', 'other')


def percentile(sorted_vals, p):
    """Linear-interpolation percentile over an ascending list (the
    serving metrics convention); None on empty input."""
    if not sorted_vals:
        return None
    if len(sorted_vals) == 1:
        return float(sorted_vals[0])
    rank = (p / 100.0) * (len(sorted_vals) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = rank - lo
    return float(sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac)


class StepTimeline:
    """Per-step phase profiler over one registry.

        tl = StepTimeline()
        with tl.phase('data_wait'):
            batch = next(loader)
        with tl.phase('host_dispatch'):
            out = step(batch)           # async dispatch
        with tl.phase('device_block'):
            loss = out.numpy()          # block until ready
        tl.end_step()                   # finalize + histograms

    ``record(phase, seconds)`` is the low-level door for callers with
    their own timing. Phases accumulate until ``end_step``, which
    observes the histograms, updates the rolling window, and runs
    straggler detection against the median of the PREVIOUS steps.
    """

    def __init__(self, registry=None, tracer=None, clock=None,
                 window=128, straggler_factor=2.0, min_history=8):
        self.registry = registry if registry is not None \
            else default_registry()
        fams = record_perf_schema(self.registry)
        hist = fams['perf_step_phase_seconds']
        self._h = {p: hist.labels(p) for p in PHASES}
        self._m_steps = fams['perf_steps_total']
        self._m_stragglers = fams['perf_stragglers_total']
        self._clock = clock or time.monotonic
        self._tracer = tracer       # None -> default_tracer() at use
        self.window = int(window)
        self.straggler_factor = float(straggler_factor)
        self.min_history = int(min_history)
        self.enabled = True
        self.steps = 0
        self.stragglers = 0
        self._cur = {}
        self._win = {p: collections.deque(maxlen=self.window)
                     for p in PHASES}
        self._totals = collections.deque(maxlen=self.window)

    # ---- recording ----------------------------------------------------

    @contextlib.contextmanager
    def phase(self, name):
        """Time a with-block into phase `name` of the current step."""
        if not self.enabled:
            yield self
            return
        t0 = self._clock()
        try:
            yield self
        finally:
            self.record(name, self._clock() - t0)

    def record(self, phase, seconds):
        """Add `seconds` to `phase` of the step being assembled."""
        if not self.enabled:
            return
        if phase not in self._h:
            raise ValueError('unknown phase %r (one of %s)'
                             % (phase, ', '.join(PHASES)))
        self._cur[phase] = self._cur.get(phase, 0.0) + float(seconds)

    def discard(self):
        """Drop the partially-assembled step without observing it —
        e.g. the loader's final StopIteration data_wait at epoch end,
        which belongs to no step."""
        self._cur = {}

    def end_step(self, wall_seconds=None, exemplar=None):
        """Finalize the step. With `wall_seconds`, the gap between the
        recorded phases and the wall lands in 'other'. Returns the
        per-phase dict (plus 'total'/'straggler') or None when nothing
        was recorded."""
        if not self.enabled:
            return None
        cur, self._cur = self._cur, {}
        if not cur and wall_seconds is None:
            return None
        total = sum(cur.values())
        if wall_seconds is not None and wall_seconds > total:
            cur['other'] = cur.get('other', 0.0) + (wall_seconds - total)
            total = float(wall_seconds)
        # straggler check against the PREVIOUS steps' median, before
        # this step pollutes the window
        straggler = False
        median = None
        if len(self._totals) >= self.min_history:
            median = percentile(sorted(self._totals), 50)
            straggler = bool(median) and \
                total > self.straggler_factor * median
        tracer = self._tracer if self._tracer is not None \
            else _tracing.default_tracer()
        if exemplar is None and tracer.enabled:
            span = tracer.current()
            if span is not None:
                exemplar = getattr(span, 'trace_id', None)
        for p, s in cur.items():
            self._win[p].append(s)
            self._h[p].observe(s, exemplar=exemplar)
        self._totals.append(total)
        self.steps += 1
        self._m_steps.inc()
        if straggler:
            self.stragglers += 1
            self._m_stragglers.inc()
            if tracer.enabled:
                tracer.start_span('perf.straggler',
                                  tags={'total_s': round(total, 6),
                                        'median_s': round(median, 6),
                                        'step': self.steps}).finish()
        out = dict(cur)
        out['total'] = total
        out['straggler'] = straggler
        return out

    # ---- rolling statistics -------------------------------------------

    def percentile(self, p, phase=None):
        """Rolling percentile of step totals (or one phase) over the
        window; None with no history."""
        data = self._totals if phase is None else self._win[phase]
        return percentile(sorted(data), p)

    def summary(self):
        """{phase: {count, mean, p50, p90}} over the rolling window,
        plus step/straggler totals."""
        out = {'steps': self.steps, 'stragglers': self.stragglers}
        for p in PHASES:
            vals = sorted(self._win[p])
            if not vals:
                continue
            out[p] = {'count': len(vals),
                      'mean': sum(vals) / len(vals),
                      'p50': percentile(vals, 50),
                      'p90': percentile(vals, 90)}
        if self._totals:
            tot = sorted(self._totals)
            out['total'] = {'count': len(tot),
                            'mean': sum(tot) / len(tot),
                            'p50': percentile(tot, 50),
                            'p90': percentile(tot, 90)}
        return out
