"""Exporters: Prometheus text exposition (format 0.0.4) and JSON
snapshots of a MetricRegistry.

The text format is what `curl :port/metrics` and every Prometheus scraper
consume; the JSON snapshot is the machine-diffable form the dryrun
telemetry line and tools/check_metrics_snapshot.py work from (schema =
metric names + label keys, the part a silent de-instrumentation breaks).
"""
import json
import math

__all__ = ['to_prometheus', 'to_dict', 'to_json', 'schema_of',
           'snapshot_to_prometheus']


def _esc_help(s):
    return s.replace('\\', '\\\\').replace('\n', '\\n')


def _esc_label(s):
    return (s.replace('\\', '\\\\').replace('\n', '\\n')
            .replace('"', '\\"'))


def _fmt_value(v):
    if isinstance(v, float) and math.isinf(v):
        return '+Inf' if v > 0 else '-Inf'
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return repr(int(v))
    return repr(float(v))


def _labels_text(names, values, extra=()):
    pairs = ['%s="%s"' % (n, _esc_label(v))
             for n, v in zip(names, values)]
    pairs.extend('%s="%s"' % (n, _esc_label(str(v))) for n, v in extra)
    return '{%s}' % ','.join(pairs) if pairs else ''


def to_prometheus(registry):
    """The registry as Prometheus text exposition (one scrape body)."""
    out = []
    for fam in registry.collect():
        out.append('# HELP %s %s' % (fam.name, _esc_help(fam.help)))
        out.append('# TYPE %s %s' % (fam.name, fam.kind))
        for values, child in fam.samples():
            if fam.kind == 'histogram':
                # the child's mergeable cumulative view IS the `le`
                # semantics of the _bucket lines: one shared source for
                # scrapes and federation merges
                cum = child.cumulative()
                for bound, n in zip(cum['bounds'], cum['cumulative']):
                    out.append('%s_bucket%s %s' % (
                        fam.name,
                        _labels_text(fam.labelnames, values,
                                     [('le', _fmt_value(float(bound)))]),
                        n))
                lbl = _labels_text(fam.labelnames, values)
                out.append('%s_sum%s %s' % (fam.name, lbl,
                                            _fmt_value(cum['sum'])))
                out.append('%s_count%s %d' % (fam.name, lbl, cum['count']))
            else:
                out.append('%s%s %s' % (
                    fam.name, _labels_text(fam.labelnames, values),
                    _fmt_value(child.value())))
    return '\n'.join(out) + '\n'


def to_dict(registry, buckets=True):
    """JSON-able snapshot: {name: {type, labels, samples: [...]}}.

    Each sample is {'labels': {...}} plus either {'value': v} (counter /
    gauge) or {'count': n, 'sum': s[, 'buckets': {...}]} (histogram).
    `buckets=False` trims per-bucket counts — what the one-line dryrun
    telemetry snapshot wants.
    """
    out = {}
    for fam in registry.collect():
        samples = []
        for values, child in fam.samples():
            s = {'labels': dict(zip(fam.labelnames, values))}
            if fam.kind == 'histogram':
                snap = child.snapshot()
                s['count'] = snap['count']
                s['sum'] = snap['sum']
                if buckets:
                    s['buckets'] = {
                        _fmt_value(float(b)): n
                        for b, n in zip(fam.buckets, snap['buckets'])}
                    s['buckets']['+Inf'] = snap['buckets'][-1]
                    ex = snap.get('exemplars')
                    if ex:
                        # text exposition 0.0.4 has no exemplar syntax,
                        # so trace links ride the JSON snapshot only
                        def _bound(i):
                            return (_fmt_value(float(fam.buckets[i]))
                                    if i < len(fam.buckets) else '+Inf')
                        s['exemplars'] = {
                            _bound(i): {'trace_id': t, 'value': v,
                                        'ts': ts}
                            for i, (t, v, ts) in sorted(ex.items())}
            else:
                s['value'] = child.value()
            samples.append(s)
        out[fam.name] = {'type': fam.kind,
                         'labels': list(fam.labelnames),
                         'samples': samples}
    return out


def to_json(registry, **kw):
    return json.dumps(to_dict(registry, **kw), sort_keys=True,
                      separators=(',', ':'))


def _bound_key(text):
    """Sort key for formatted bucket bounds ('+Inf' sorts last)."""
    return math.inf if text == '+Inf' else float(text)


def snapshot_to_prometheus(snapshot):
    """Render a to_dict()-shaped snapshot dict as text exposition.

    Registries render through to_prometheus directly; this path exists
    for snapshots that no longer have a live registry behind them — the
    federation merge (monitor/federation.py) and archived dryrun lines —
    so `/fleet?format=prom` can serve the merged fleet view to a
    standard scraper. Histogram samples without per-bucket detail
    (buckets=False snapshots) emit sum/count only.
    """
    out = []
    for name in sorted(snapshot):
        fam = snapshot[name]
        kind = fam.get('type', 'gauge')
        names = list(fam.get('labels') or ())
        out.append('# TYPE %s %s' % (name, kind))
        for s in fam.get('samples', ()):
            labels = dict(s.get('labels') or {})
            ordered = [n for n in names if n in labels] + \
                [n for n in sorted(labels) if n not in names]
            pairs = [(n, labels[n]) for n in ordered]
            if kind == 'histogram':
                lbl = _labels_text((), (), pairs)
                buckets = s.get('buckets')
                if buckets:
                    acc = 0
                    for b in sorted(buckets, key=_bound_key):
                        acc += int(buckets[b])
                        out.append('%s_bucket%s %d' % (
                            name, _labels_text((), (),
                                               pairs + [('le', b)]), acc))
                out.append('%s_sum%s %s'
                           % (name, lbl, _fmt_value(float(s.get('sum')
                                                          or 0.0))))
                out.append('%s_count%s %d' % (name, lbl,
                                              int(s.get('count') or 0)))
            else:
                out.append('%s%s %s' % (
                    name, _labels_text((), (), pairs),
                    _fmt_value(s.get('value') or 0.0)))
    return '\n'.join(out) + '\n'


def schema_of(snapshot):
    """{metric name: {'type': kind, 'labels': sorted label keys}} from a
    to_dict() snapshot — the identity the regression gate diffs; values
    and label VALUES are deliberately excluded."""
    return {name: {'type': fam['type'],
                   'labels': sorted(fam['labels'])}
            for name, fam in snapshot.items()}
