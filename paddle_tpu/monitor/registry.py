"""Thread-safe metric registry (reference: paddle/fluid/platform/monitor.h
StatRegistry + STAT_ADD, grown into the three Prometheus metric kinds).

Design constraints, in order:

1. **Near-zero cost when disabled.** Every hot-path instrumentation site
   in the repo (ResilientChannel.call, the serving decode loop) goes
   through a bound child whose update is ONE attribute load + branch
   when the owning registry is disabled — no lock, no dict lookup, no
   allocation. The guard test pins this.
2. **Exact under concurrency.** Python's ``+=`` on an int is a
   read-modify-write across bytecodes; a per-child lock keeps totals
   exact so the chaos harness can use counters as a correctness oracle
   (N injected faults == N recorded failures, not ~N).
3. **Get-or-create families.** Two engines (or a re-imported module)
   asking for the same (name, type, labelnames) share one family; a
   conflicting redeclaration raises instead of silently forking series.

Label values are positional-or-keyword; children are interned per value
tuple so call sites can cache them once (``self._m = fam.labels(ep)``)
and pay only the child update per event.
"""
import bisect
import threading
import time

__all__ = ['MetricRegistry', 'Counter', 'Gauge', 'Histogram',
           'exponential_buckets', 'default_registry', 'set_default_registry']

# Prometheus-conventional default histogram buckets (seconds)
DEFAULT_BUCKETS = (.005, .01, .025, .05, .1, .25, .5, 1.0, 2.5, 5.0, 10.0)


def exponential_buckets(start, factor, count):
    """`count` bucket upper bounds: start, start*factor, ... (the
    reference monitor.h stats are plain sums; exponential bounds are what
    latency distributions need)."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError('need start > 0, factor > 1, count >= 1')
    out = []
    b = float(start)
    for _ in range(int(count)):
        out.append(b)
        b *= factor
    return tuple(out)


def _check_name(name):
    if not name or not all(c.isalnum() or c in '_:' for c in name):
        raise ValueError('invalid metric name %r' % (name,))


class _Child:
    """One labeled series. Updates check the registry's enabled flag
    FIRST (the disabled fast path), then mutate under the family lock."""

    __slots__ = ('_reg', '_lock', '_value')

    def __init__(self, reg, lock):
        self._reg = reg
        self._lock = lock
        self._value = 0.0

    def value(self):
        with self._lock:
            return self._value


class _CounterChild(_Child):
    __slots__ = ()

    def inc(self, amount=1.0):
        if not self._reg._enabled:
            return
        if amount < 0:
            raise ValueError('counters only go up')
        with self._lock:
            self._value += amount


class _GaugeChild(_Child):
    __slots__ = ()

    def set(self, value):
        if not self._reg._enabled:
            return
        with self._lock:
            self._value = float(value)

    def inc(self, amount=1.0):
        if not self._reg._enabled:
            return
        with self._lock:
            self._value += amount

    def dec(self, amount=1.0):
        self.inc(-amount)


class _HistogramChild:
    __slots__ = ('_reg', '_lock', '_bounds', '_counts', '_sum', '_count',
                 '_exemplars')

    def __init__(self, reg, lock, bounds):
        self._reg = reg
        self._lock = lock
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)   # trailing +Inf bucket
        self._sum = 0.0
        self._count = 0
        # bucket index -> (exemplar id, value, t): the LAST annotated
        # observation per bucket, so an outlier bucket links back to a
        # concrete trace (monitor/tracing.py exemplars)
        self._exemplars = {}

    def observe(self, value, exemplar=None):
        if not self._reg._enabled:
            return
        i = bisect.bisect_left(self._bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            if exemplar is not None:
                self._exemplars[i] = (str(exemplar), float(value),
                                      self._reg.clock())

    def value(self):
        """(count, sum) — the scalar view used by tests/snapshots."""
        with self._lock:
            return self._count, self._sum

    def snapshot(self):
        with self._lock:
            return {'count': self._count, 'sum': self._sum,
                    'buckets': list(self._counts),
                    'exemplars': dict(self._exemplars)}

    def cumulative(self):
        """Mergeable fixed-boundary view: Prometheus `le` semantics,
        one cumulative count per upper bound with +Inf last, so
        cumulative[-1] == count always. Two children with the same
        bounds merge by element-wise sum (monitor/federation.py); the
        `_bucket` exposition lines print exactly these numbers."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum, acc = [], 0
        for n in counts:
            acc += n
            cum.append(acc)
        return {'bounds': list(self._bounds) + [float('inf')],
                'cumulative': cum, 'count': total, 'sum': s}


class _Family:
    """One metric family: a name, a type, label names, and children."""

    kind = None

    def __init__(self, reg, name, help, labelnames):
        _check_name(name)
        self.name = name
        self.help = help or ''
        self.labelnames = tuple(labelnames or ())
        self._reg = reg
        self._lock = threading.Lock()
        self._children = {}
        if not self.labelnames:
            self._children[()] = self._make_child()

    def _make_child(self):
        raise NotImplementedError

    def labels(self, *values, **kwvalues):
        if kwvalues:
            if values:
                raise ValueError('pass labels positionally OR by name')
            try:
                values = tuple(kwvalues[k] for k in self.labelnames)
            except KeyError as e:
                raise ValueError('missing label %s' % e)
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError('%s expects labels %r, got %r'
                             % (self.name, self.labelnames, values))
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = self._make_child()
            return child

    # unlabeled convenience: fam.inc() == fam.labels().inc()
    def __getattr__(self, attr):
        if attr in ('inc', 'dec', 'set', 'observe', 'value',
                    'cumulative') and not self.labelnames:
            return getattr(self._children[()], attr)
        raise AttributeError(attr)

    def samples(self):
        """[(label_values_tuple, child)] — a consistent point-in-time
        listing for exporters."""
        with self._lock:
            return sorted(self._children.items())


class Counter(_Family):
    kind = 'counter'

    def _make_child(self):
        return _CounterChild(self._reg, self._lock)


class Gauge(_Family):
    kind = 'gauge'

    def _make_child(self):
        return _GaugeChild(self._reg, self._lock)


class Histogram(_Family):
    kind = 'histogram'

    def __init__(self, reg, name, help, labelnames, buckets=None):
        bounds = tuple(sorted(float(b) for b in (buckets or
                                                 DEFAULT_BUCKETS)))
        if not bounds:
            raise ValueError('need at least one bucket bound')
        self.buckets = bounds
        super().__init__(reg, name, help, labelnames)

    def _make_child(self):
        return _HistogramChild(self._reg, self._lock, self.buckets)


class MetricRegistry:
    """Get-or-create home for metric families, with a global on/off
    switch (monitor.h's StatRegistry::Instance() analog is
    ``default_registry()``)."""

    def __init__(self, enabled=True, clock=None):
        self._enabled = bool(enabled)
        self.clock = clock or time.monotonic
        self._families = {}
        self._lock = threading.Lock()

    # -- enable/disable ------------------------------------------------------
    @property
    def enabled(self):
        return self._enabled

    def enable(self):
        self._enabled = True

    def disable(self):
        """Freeze all instrumentation fed by this registry: every child
        update becomes a flag check and nothing else."""
        self._enabled = False

    # -- family constructors -------------------------------------------------
    def _get_or_create(self, cls, name, help, labelnames, **kw):
        labelnames = tuple(labelnames or ())
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != cls.kind or fam.labelnames != labelnames:
                    raise ValueError(
                        'metric %r already registered as %s%r, requested '
                        '%s%r' % (name, fam.kind, fam.labelnames,
                                  cls.kind, labelnames))
                return fam
            fam = cls(self, name, help, labelnames, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name, help='', labelnames=()):
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help='', labelnames=()):
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help='', labelnames=(), buckets=None):
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    # -- introspection -------------------------------------------------------
    def collect(self):
        """Families sorted by name (stable exporter order)."""
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    def get(self, name):
        with self._lock:
            return self._families.get(name)

    def unregister(self, name):
        with self._lock:
            self._families.pop(name, None)


_default = MetricRegistry(enabled=True)
_default_lock = threading.Lock()


def default_registry():
    """The process-wide registry every built-in instrumentation site
    feeds unless handed an explicit one."""
    return _default


def set_default_registry(reg):
    """Swap the process default (tests); returns the previous one.

    Already-bound children keep feeding the registry they were created
    from — swap BEFORE constructing the objects under test.
    """
    global _default
    with _default_lock:
        prev, _default = _default, reg
        return prev
