"""Distributed request tracing (reference: platform/profiler.h
RecordEvent spans + tools/CrossStackProfiler's cross-trainer timeline,
rebuilt as a stdlib-only tracer the whole repo shares).

The metrics registry (registry.py) says HOW MUCH; this module says WHERE
TIME WENT for one request. Three consumers ride on it:

- **Cross-process propagation** — ``ResilientChannel.call`` opens a span
  per attempt and injects ``span.ctx()`` into the message under
  ``TRACE_KEY``; the graph/PS servers pop it and continue the trace, so
  one embedding pull or GNN sampling request is a single causally-linked
  tree across processes.
- **Serving lifecycle** — the slot/paged engines emit
  queued→admit→prefill→decode→retire spans with prefix-cache-hit and
  spec-accept events, and TTFT/inter-token histogram observations carry
  trace_id exemplars (registry.py) so an outlier bucket links back to
  its trace.
- **Flight recorder + export** — every finished span lands in a bounded
  ring; circuit-open, deadline-expiry and chaos faults trigger JSON
  dumps; ``/debug/traces`` on MetricsServer serves the ring live; and
  ``spans_to_chrome`` emits Chrome-trace JSON that
  ``profiler.merge_traces`` folds into one Perfetto timeline next to
  jax.profiler device traces.

Cost discipline matches the registry: a disabled tracer's
``start_span`` is one attribute load + branch returning the shared
``NULL_SPAN`` — no allocation, no clock read, no contextvar touch.
Span timestamps use ``time.time`` (epoch) by default so spans from
different processes align on one timeline without clock negotiation.
"""
import collections
import contextvars
import json
import os
import random
import threading
import time

from .registry import default_registry

__all__ = ['Span', 'Tracer', 'FlightRecorder', 'TraceRetention',
           'NULL_SPAN', 'TRACE_KEY',
           'default_tracer', 'set_default_tracer', 'current_span',
           'register_metrics', 'spans_to_chrome', 'note_fault',
           'TRACING_FAMILIES']

# message-metadata key carrying {'trace_id', 'span_id'} across processes
# (a str->str dict, representable by the ps/wire typed codec)
TRACE_KEY = '_trace'

# the tracer's own health families — unlabeled counters except the dump
# counter, whose 'reason' label is a closed vocabulary (circuit_open /
# deadline_expired / chaos_fault / manual). Single-source rule: the
# telemetry schema baseline and every tracer register through here.
TRACING_FAMILIES = (
    ('counter', 'trace_spans_started_total', 'spans begun'),
    ('counter', 'trace_spans_finished_total',
     'spans finished and offered to the flight recorder'),
    ('counter', 'trace_spans_dropped_total',
     'finished spans evicted from the flight-recorder ring'),
    ('counter', 'trace_exemplars_total',
     'histogram observations annotated with a trace_id exemplar'),
    ('counter', 'trace_retention_discarded_total',
     'completed span trees the tail sampler decided not to keep'),
    ('counter', 'trace_retention_evicted_total',
     'kept or pending span trees evicted at the retention caps'),
)


def register_metrics(registry):
    """Get-or-create the tracing metric families on `registry`;
    returns {name: family} (plus the reason-labeled dump counter)."""
    out = {}
    for kind, name, doc in TRACING_FAMILIES:
        out[name] = getattr(registry, kind)(name, doc)
    out['trace_flight_dumps_total'] = registry.counter(
        'trace_flight_dumps_total',
        'flight-recorder dumps written, by trigger reason', ('reason',))
    out['trace_retained_total'] = registry.counter(
        'trace_retained_total',
        'complete span trees kept by tail-based retention, by reason',
        ('reason',))
    return out


_current = contextvars.ContextVar('paddle_tpu_trace_span', default=None)


def _new_id(bits):
    return '%0*x' % (bits // 4, random.getrandbits(bits))


class _NullSpan:
    """Shared do-nothing span: the disabled tracer's return value.
    Falsy, so call sites can guard optional work with ``if span:``."""

    __slots__ = ()
    trace_id = None
    span_id = None
    parent_id = None
    name = None

    def __bool__(self):
        return False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_tag(self, key, value):
        return self

    def add_event(self, name, **attrs):
        return self

    def set_error(self, exc):
        return self

    def ctx(self):
        return None

    def finish(self):
        pass

    def to_dict(self):
        return {}

    def __repr__(self):
        return 'NULL_SPAN'


NULL_SPAN = _NullSpan()


class Span:
    """One timed operation in a trace tree.

    Mutations (set_tag / add_event / set_error) are expected from the
    span's owning thread; use as a context manager to also publish the
    span to the thread's contextvar so children (and cross-process
    injection) pick it up as parent. ``finish()`` is idempotent."""

    __slots__ = ('name', 'trace_id', 'span_id', 'parent_id', 'start',
                 'end', 'tags', 'events', 'status', 'error', 'tid',
                 '_tracer', '_token')

    def __init__(self, tracer, name, trace_id, parent_id, tags):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id(64)
        self.parent_id = parent_id
        self.start = tracer.clock()
        self.end = None
        self.tags = dict(tags) if tags else {}
        self.events = []          # [(ts, name, attrs)]
        self.status = 'ok'
        self.error = None
        self.tid = threading.get_ident()
        self._token = None

    def __bool__(self):
        return True

    def set_tag(self, key, value):
        self.tags[key] = value
        return self

    def add_event(self, name, **attrs):
        self.events.append((self._tracer.clock(), name, attrs))
        return self

    def set_error(self, exc):
        self.status = 'error'
        self.error = repr(exc)
        return self

    def ctx(self):
        """The wire form: what a client injects under TRACE_KEY."""
        return {'trace_id': self.trace_id, 'span_id': self.span_id}

    def finish(self):
        if self.end is not None:
            return
        self.end = self._tracer.clock()
        self._tracer._on_finish(self)

    def __enter__(self):
        self._token = _current.set(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if exc_type is not None and self.status == 'ok':
            self.set_error(exc if exc is not None else exc_type)
        self.finish()
        return False

    def to_dict(self):
        return {'name': self.name, 'trace_id': self.trace_id,
                'span_id': self.span_id, 'parent_id': self.parent_id,
                'start': self.start,
                'end': self.end if self.end is not None else self.start,
                'tid': self.tid, 'status': self.status,
                'error': self.error, 'tags': dict(self.tags),
                'events': [{'ts': ts, 'name': n, 'args': dict(a)}
                           for ts, n, a in self.events]}

    def __repr__(self):
        return ('Span(%s, trace=%s, span=%s, parent=%s, status=%s)'
                % (self.name, self.trace_id, self.span_id,
                   self.parent_id, self.status))


class FlightRecorder:
    """Bounded ring of completed spans + throttled crash-dump writer.

    ``record`` keeps the newest `capacity` span dicts (evictions are
    counted, never silent). ``maybe_dump(reason)`` writes the ring to
    ``dump_dir/flight_<reason>_<seq>.json`` at most once per `cooldown`
    seconds per reason — the automatic triggers (circuit-open, deadline
    expiry, chaos faults) can fire in bursts and must not grind the hot
    path into disk I/O. With no dump_dir (the default, unless
    PADDLE_TPU_FLIGHT_DIR is set) maybe_dump is a no-op and the ring is
    inspection-only (``/debug/traces``, ``dump(path=...)``).
    """

    def __init__(self, capacity=4096, dump_dir=None, cooldown=60.0,
                 registry=None, clock=None):
        if capacity < 1:
            raise ValueError('capacity must be >= 1')
        self.capacity = int(capacity)
        self.dump_dir = (dump_dir if dump_dir is not None
                         else os.environ.get('PADDLE_TPU_FLIGHT_DIR'))
        self.cooldown = float(cooldown)
        self._clock = clock or time.time
        self._ring = collections.deque()
        self._lock = threading.Lock()
        self._dropped = 0
        self._seq = 0
        self._last_dump = {}      # reason -> last dump time
        reg = registry if registry is not None else default_registry()
        fams = register_metrics(reg)
        self._m_dropped = fams['trace_spans_dropped_total']
        self._m_dumps = fams['trace_flight_dumps_total']

    def __len__(self):
        with self._lock:
            return len(self._ring)

    @property
    def dropped(self):
        with self._lock:
            return self._dropped

    def record(self, span_dict):
        with self._lock:
            if len(self._ring) >= self.capacity:
                self._ring.popleft()
                self._dropped += 1
                self._m_dropped.inc()
            self._ring.append(span_dict)

    def spans(self):
        """Oldest-first copy of the ring."""
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()

    def dump(self, reason='manual', path=None):
        """Write the ring as JSON and return the path. With path=None a
        sequenced file lands under dump_dir (which must be set)."""
        spans = self.spans()
        if path is None:
            if not self.dump_dir:
                raise ValueError('FlightRecorder has no dump_dir; pass '
                                 'an explicit path')
            with self._lock:
                self._seq += 1
                seq = self._seq
            os.makedirs(self.dump_dir, exist_ok=True)
            path = os.path.join(self.dump_dir,
                                'flight_%s_%04d.json' % (reason, seq))
        payload = {'reason': reason, 'time': self._clock(),
                   'dropped': self.dropped, 'span_count': len(spans),
                   'spans': spans}
        with open(path, 'w') as fh:
            json.dump(payload, fh)
        self._m_dumps.labels(reason).inc()
        return path

    def maybe_dump(self, reason):
        """Throttled automatic dump: None when no dump_dir is configured
        or the reason is still inside its cooldown window."""
        if not self.dump_dir:
            return None
        now = self._clock()
        with self._lock:
            last = self._last_dump.get(reason)
            if last is not None and now - last < self.cooldown:
                return None
            self._last_dump[reason] = now
        return self.dump(reason)

    def to_chrome(self, process_name=None):
        return spans_to_chrome(self.spans(), process_name=process_name)

    def export_chrome(self, path, process_name=None):
        """Write the ring in Chrome-trace format; drop the file in a
        directory handed to profiler.merge_traces and host spans join
        the per-rank device traces on one Perfetto timeline."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, 'w') as fh:
            json.dump(self.to_chrome(process_name=process_name), fh)
        return path


def spans_to_chrome(spans, pid=None, process_name=None):
    """Span dicts -> Chrome-trace JSON dict ({'traceEvents': [...]}).

    Spans become 'X' complete events (ts/dur in microseconds — epoch-
    based, so traces from different processes align without offset
    bookkeeping), span events become 'i' instants, and a process_name
    metadata record labels the lane (merge_traces prefixes it with
    'rank N:')."""
    pid = os.getpid() if pid is None else int(pid)
    events = [{'ph': 'M', 'name': 'process_name', 'pid': pid, 'tid': 0,
               'args': {'name': process_name
                        or 'paddle_tpu host %d' % pid}}]
    for s in spans:
        tid = s.get('tid') or 0
        start = float(s.get('start') or 0.0)
        end = float(s.get('end') or start)
        args = dict(s.get('tags') or {})
        args['trace_id'] = s.get('trace_id')
        args['span_id'] = s.get('span_id')
        if s.get('parent_id'):
            args['parent_id'] = s['parent_id']
        if s.get('status') not in (None, 'ok'):
            args['status'] = s['status']
            if s.get('error'):
                args['error'] = s['error']
        events.append({'ph': 'X', 'cat': 'span',
                       'name': s.get('name') or '?', 'pid': pid,
                       'tid': tid, 'ts': start * 1e6,
                       'dur': max(end - start, 0.0) * 1e6, 'args': args})
        for ev in s.get('events') or ():
            events.append({'ph': 'i', 's': 't', 'cat': 'span',
                           'name': ev.get('name') or 'event', 'pid': pid,
                           'tid': tid,
                           'ts': float(ev.get('ts') or start) * 1e6,
                           'args': dict(ev.get('args') or {})})
    return {'traceEvents': events, 'displayTimeUnit': 'ms'}


class TraceRetention:
    """Tail-based trace retention: decide AFTER a trace completes.

    Head sampling (keep 1%) throws away exactly the traces worth
    reading; tail sampling buffers each trace's finished spans until its
    ROOT span (parent_id None) completes, then keeps the whole tree when
    the request was interesting — errored (any span with status
    'error'), slow (root duration over `slow_threshold_s`, typically the
    serving SLO), or force-marked by an outside observer (the gateway
    marks failed-over requests via ``mark``) — plus a probabilistic
    `keep_probability` sample of healthy traffic as a baseline. Wide
    events (monitor/events.py) carry the trace_id, so ``get(trace_id)``
    closes the event → full-span-tree join.

    Everything is bounded: at most `capacity` kept trees (FIFO
    eviction), at most `pending_capacity` incomplete trees, and a
    bounded memory of recent decisions/marks; evictions and discards
    are counted, never silent. Attach to a tracer via
    ``Tracer(retention=...)`` or ``tracer.retention = ...``; a detached
    store costs the hot path nothing (one load + branch in
    ``_on_finish``, which only runs when tracing is enabled anyway)."""

    def __init__(self, capacity=256, slow_threshold_s=None,
                 keep_probability=0.0, pending_capacity=1024,
                 registry=None, rng=None):
        if capacity < 1 or pending_capacity < 1:
            raise ValueError('capacities must be >= 1')
        self.capacity = int(capacity)
        self.slow_threshold_s = slow_threshold_s
        self.keep_probability = float(keep_probability)
        self.pending_capacity = int(pending_capacity)
        self._rng = rng or random.random
        self._lock = threading.Lock()
        self._pending = collections.OrderedDict()   # trace_id -> [span]
        self._kept = collections.OrderedDict()      # trace_id -> entry
        self._marked = collections.OrderedDict()    # trace_id -> reason
        self._decided = collections.OrderedDict()   # trace_id -> True
        reg = registry if registry is not None else default_registry()
        fams = register_metrics(reg)
        self._m_retained = fams['trace_retained_total']
        self._m_discarded = fams['trace_retention_discarded_total']
        self._m_evicted = fams['trace_retention_evicted_total']

    def mark(self, trace_id, reason='forced'):
        """Force-keep `trace_id` when its tree completes (or
        immediately, if it already has). The gateway calls this with
        reason 'failover' for every re-placed request."""
        if not trace_id:
            return
        with self._lock:
            entry = self._kept.get(trace_id)
            if entry is not None:
                if reason not in entry['reasons']:
                    entry['reasons'].append(reason)
                return
            self._marked[trace_id] = reason
            while len(self._marked) > self.pending_capacity:
                self._marked.popitem(last=False)

    def offer(self, span_dict):
        """Feed one finished span (called by Tracer._on_finish)."""
        tid = span_dict.get('trace_id')
        if not tid:
            return
        with self._lock:
            entry = self._kept.get(tid)
            if entry is not None:
                # straggler span of an already-kept tree
                entry['spans'].append(span_dict)
                return
            if tid in self._decided:
                return
            spans = self._pending.get(tid)
            if spans is None:
                while len(self._pending) >= self.pending_capacity:
                    self._pending.popitem(last=False)
                    self._m_evicted.inc()
                spans = self._pending[tid] = []
            spans.append(span_dict)
            if span_dict.get('parent_id') is None:
                self._decide_locked(tid, span_dict)

    def _decide_locked(self, tid, root):
        spans = self._pending.pop(tid, [])
        reasons = []
        forced = self._marked.pop(tid, None)
        if forced is not None:
            reasons.append(forced)
        if any(s.get('status') == 'error' for s in spans):
            reasons.append('error')
        duration = float(root.get('end') or 0.0) \
            - float(root.get('start') or 0.0)
        if self.slow_threshold_s is not None \
                and duration > self.slow_threshold_s:
            reasons.append('slow')
        if not reasons and self.keep_probability > 0.0 \
                and self._rng() < self.keep_probability:
            reasons.append('sampled')
        self._decided[tid] = True
        while len(self._decided) > 4 * self.pending_capacity:
            self._decided.popitem(last=False)
        if not reasons:
            self._m_discarded.inc()
            return
        while len(self._kept) >= self.capacity:
            self._kept.popitem(last=False)
            self._m_evicted.inc()
        self._kept[tid] = {'trace_id': tid, 'reasons': reasons,
                           'root': root.get('name'),
                           'duration_s': duration,
                           'end': root.get('end'), 'spans': spans}
        self._m_retained.labels(reasons[0]).inc()

    def get(self, trace_id):
        """The full retained span tree for `trace_id` (list of span
        dicts, finish order), or None if it was not kept."""
        with self._lock:
            entry = self._kept.get(trace_id)
            return list(entry['spans']) if entry is not None else None

    def traces(self, reason=None):
        """Summaries of the kept trees, oldest first: trace_id, reasons,
        root span name, duration."""
        with self._lock:
            entries = list(self._kept.values())
        out = []
        for e in entries:
            if reason is not None and reason not in e['reasons']:
                continue
            out.append({'trace_id': e['trace_id'],
                        'reasons': list(e['reasons']),
                        'root': e['root'],
                        'duration_s': e['duration_s'],
                        'end': e['end'],
                        'span_count': len(e['spans'])})
        return out

    def __len__(self):
        with self._lock:
            return len(self._kept)

    def clear(self):
        with self._lock:
            self._pending.clear()
            self._kept.clear()
            self._marked.clear()
            self._decided.clear()


class Tracer:
    """Span factory + the enabled/disabled switch.

    ``enabled`` is a plain attribute so hot paths pay one load + branch
    when tracing is off (the registry's ~90 ns discipline); disabled
    ``start_span`` returns the shared NULL_SPAN. The injectable clock
    stamps span start/end/events — keep it epoch-based (time.time) in
    production so cross-process spans share a timeline."""

    def __init__(self, enabled=True, clock=None, recorder=None,
                 registry=None, retention=None):
        self.enabled = bool(enabled)
        self.clock = clock or time.time
        self.registry = registry if registry is not None \
            else default_registry()
        fams = register_metrics(self.registry)
        self._m_started = fams['trace_spans_started_total']
        self._m_finished = fams['trace_spans_finished_total']
        self.recorder = recorder if recorder is not None else \
            FlightRecorder(registry=self.registry, clock=self.clock)
        # tail-based retention is opt-in: None costs one load + branch
        # per finished span (attach with tracer.retention = TraceRetention())
        self.retention = retention

    def enable(self):
        self.enabled = True

    def disable(self):
        """Freeze tracing: start_span becomes a branch returning
        NULL_SPAN; in-flight real spans still finish and record."""
        self.enabled = False

    def current(self):
        """The calling thread/context's innermost entered span."""
        return _current.get()

    def start_span(self, name, parent=None, ctx=None, tags=None,
                   root=False):
        """Begin a span. Parent resolution: explicit `ctx` (a wire dict
        from a remote client) > explicit `parent` span > the contextvar
        current span > a fresh root. `root=True` skips the contextvar
        lookup and always opens a NEW trace — request-identity spans
        (the engines' serving.request) use it so a request re-submitted
        inside a gateway routing/failover span still owns its own trace
        (tail retention decides per request, and the wide event's
        trace_id resolves to exactly that request's tree). The returned
        span is NOT current until entered (``with``) — lifecycle spans
        held across calls (a serving request) just ``finish()``
        manually."""
        if not self.enabled:
            return NULL_SPAN
        if root:
            trace_id, parent_id = _new_id(128), None
        elif ctx is not None:
            trace_id = str(ctx.get('trace_id') or _new_id(128))
            parent_id = ctx.get('span_id')
        elif parent:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        else:
            cur = _current.get()
            if cur is not None:
                trace_id, parent_id = cur.trace_id, cur.span_id
            else:
                trace_id, parent_id = _new_id(128), None
        self._m_started.inc()
        return Span(self, name, trace_id, parent_id, tags)

    def server_span(self, msg, prefix):
        """Server-side continuation: pop TRACE_KEY from an incoming
        message dict and open a span parented on the remote caller.
        ALWAYS pops (even disabled / untraced) so op handlers never see
        transport metadata; returns NULL_SPAN when there is nothing to
        continue."""
        ctx = msg.pop(TRACE_KEY, None) if isinstance(msg, dict) else None
        if not self.enabled or not isinstance(ctx, dict):
            return NULL_SPAN
        name = prefix
        if isinstance(msg, dict) and 'op' in msg:
            name = '%s.%s' % (prefix, msg['op'])
        return self.start_span(name, ctx=ctx)

    def _on_finish(self, span):
        self._m_finished.inc()
        d = span.to_dict()
        self.recorder.record(d)
        ret = self.retention
        if ret is not None:
            ret.offer(d)


def _env_enabled():
    v = os.environ.get('PADDLE_TPU_TRACING', '1').strip().lower()
    return v not in ('0', 'false', 'off', 'no', '')


_default = Tracer(enabled=_env_enabled())
_default_lock = threading.Lock()


def default_tracer():
    """The process-wide tracer every built-in instrumentation site uses
    unless handed an explicit one."""
    return _default


def set_default_tracer(tracer):
    """Swap the process default (tests); returns the previous one.
    Objects that cached the old tracer at construction keep it — swap
    BEFORE constructing the engines/channels under test."""
    global _default
    with _default_lock:
        prev, _default = _default, tracer
        return prev


def current_span():
    """Module-level convenience for the calling context's span."""
    return _current.get()


def note_fault(point, endpoint):
    """Chaos hook (testing/chaos.py): annotate the current span with the
    injected fault and request a throttled flight dump. No-op when
    tracing is disabled."""
    tr = _default
    if not tr.enabled:
        return
    sp = _current.get()
    if sp is not None:
        sp.add_event('chaos.fault', point=point, endpoint=endpoint)
    tr.recorder.maybe_dump('chaos_fault')
