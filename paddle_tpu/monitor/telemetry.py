"""Dryrun telemetry snapshot lines (the sharding_audit pattern applied
to metrics): one `telemetry_snapshot(N)[tag]: {json}` line per driver
config, parsed back by tools/check_metrics_snapshot.py and diffed
against a committed schema baseline so an instrumented metric cannot
silently disappear.
"""
import json
import re

from . import export
from .registry import MetricRegistry
from .runtime import RuntimeSampler

__all__ = ['record_dryrun_step', 'record_serving_schema',
           'record_serving_request_schema', 'record_gateway_schema',
           'record_tracing_schema', 'record_perf_schema',
           'record_rpc_schema', 'record_client_op_schema',
           'record_train_loop_schema', 'record_fleet_schema',
           'record_alert_schema', 'record_supervisor_schema',
           'record_request_event_schema', 'record_tenant_schema',
           'record_qos_schema', 'record_capacity_schema',
           'record_ingest_schema', 'record_registry_schema',
           'snapshot_line',
           'parse_snapshot_lines', 'LINE_RE']

LINE_RE = re.compile(r'telemetry_snapshot\((?P<n>\d+)\)'
                     r'\[(?P<tag>[^\]]*)\]:\s*(?P<json>\{.*\})\s*$')


def record_dryrun_step(registry, step_seconds, loss, batch=None):
    """The per-config training gauges the dryrun embeds. Kept in one
    place so the driver and the schema-baseline test register the exact
    same families."""
    registry.gauge('train_step_seconds',
                   'wall time of the measured train step').set(step_seconds)
    registry.gauge('train_loss', 'loss of the measured step').set(loss)
    registry.counter('train_steps_total', 'train steps run').inc()
    if batch:
        registry.counter('train_examples_total',
                         'examples consumed').inc(batch)
        if step_seconds > 0:
            registry.gauge('train_examples_per_second',
                           'examples/s of the measured step').set(
                               batch / step_seconds)


# the paged serving engine's capacity/efficiency families. Declared here
# (not in serving/metrics.py) so the schema-baseline gate and the engine
# register the exact same names/types — same single-source rule as
# record_dryrun_step. (kind, name, help) with no labels: registration
# alone creates the unlabeled child, so these appear in every snapshot.
SERVING_PAGED_FAMILIES = (
    ('gauge', 'serving_kv_pages_in_use',
     'physical KV pages currently referenced (sequences + prefix cache)'),
    ('counter', 'serving_prefix_cache_hits_total',
     'full prompt blocks served from the prefix cache'),
    ('counter', 'serving_prefix_cache_misses_total',
     'full prompt blocks that had to prefill'),
    ('counter', 'serving_spec_tokens_proposed_total',
     'draft tokens proposed for speculative verification'),
    ('counter', 'serving_spec_tokens_accepted_total',
     'draft tokens accepted by the verify pass'),
)


def record_serving_schema(registry):
    """Register the paged-serving metric families on `registry` and
    return {name: family}. Used by ServingMetrics at engine construction
    and by dryrun_registry so the committed schema baseline covers
    serving without a serving run."""
    out = {}
    for kind, name, doc in SERVING_PAGED_FAMILIES:
        out[name] = getattr(registry, kind)(name, doc)
    return out


# the multi-replica gateway's families (serving/gateway/). Same
# single-source rule: the ServingGateway and the schema baseline both
# register through record_gateway_schema. (kind, name, help, labels) —
# labeled families appear in snapshots on registration alone (schema_of
# lists the family even with zero children), so the gate covers them
# without a gateway run. Label budgets (docs/observability.md): replica
# is bounded by max_replicas (<= 8 by default), direction is {up, down}.
GATEWAY_FAMILIES = (
    ('counter', 'gateway_requests_total',
     'requests accepted at the gateway front door', ()),
    ('counter', 'gateway_requests_completed_total',
     'requests fully delivered to the caller', ()),
    ('counter', 'gateway_tokens_total',
     'tokens delivered to callers across all replicas', ()),
    ('counter', 'gateway_route_total',
     'routing decisions per replica', ('replica',)),
    ('counter', 'gateway_retries_total',
     'submissions retried on another replica after a transport error',
     ()),
    ('counter', 'gateway_failover_total',
     'in-flight requests re-admitted after a replica loss', ()),
    ('counter', 'gateway_scale_events_total',
     'autoscaler actions taken', ('direction',)),
    ('gauge', 'gateway_replicas',
     'replicas currently alive (ready or draining)', ()),
    ('gauge', 'gateway_replica_state',
     'per-replica state (0=ready 1=draining 2=dead 3=stopped)',
     ('replica',)),
    ('gauge', 'gateway_queue_depth',
     'requests parked at the gateway awaiting a routable replica', ()),
    ('gauge', 'gateway_slo_burn_rate',
     'fraction of windowed TTFT samples over the SLO', ()),
    ('histogram', 'gateway_ttft_seconds',
     'time from gateway submission to first delivered token', ()),
)


def record_gateway_schema(registry):
    """Register the gateway metric families on `registry` and return
    {name: family}. Used by ServingGateway at construction and by
    dryrun_registry so the committed baseline covers the gateway."""
    from .registry import exponential_buckets
    out = {}
    for kind, name, doc, labels in GATEWAY_FAMILIES:
        kw = {}
        if kind == 'histogram':
            kw['buckets'] = exponential_buckets(0.002, 2.0, 16)
        out[name] = getattr(registry, kind)(name, doc, labels, **kw)
    return out


# the performance-introspection families (monitor/perf/). Same
# single-source rule: CompileWatchdog, StepTimeline, the cost-model
# gauges and the schema baseline all register through
# record_perf_schema. Label budgets: kind is the three jax compile
# stages, phase the four step-timeline phases — both closed sets.
PERF_FAMILIES = (
    ('counter', 'perf_compiles_total',
     'jit compilation events seen by the CompileWatchdog', ('kind',)),
    ('histogram', 'perf_compile_seconds',
     'duration of jit trace/lower/compile events', ('kind',)),
    ('counter', 'perf_recompiles_total',
     'compiles after a declared warmup barrier '
     '(steady state must stay 0)', ()),
    ('histogram', 'perf_step_phase_seconds',
     'per-step phase durations '
     '(data_wait/host_dispatch/device_block/other)', ('phase',)),
    ('counter', 'perf_steps_total',
     'steps finalized by a StepTimeline', ()),
    ('counter', 'perf_stragglers_total',
     'steps slower than straggler_factor x the rolling median', ()),
    ('gauge', 'perf_mfu_est',
     'cost-model MFU estimate of the measured step', ()),
    ('gauge', 'perf_arithmetic_intensity',
     'analytic flops per byte accessed of the compiled step', ()),
    ('gauge', 'perf_roofline_bound',
     'roofline classification of the compiled step '
     '(0=bandwidth 1=compute)', ()),
    ('counter', 'perf_persistent_cache_hits_total',
     'backend compiles served from the persistent compile cache '
     '(framework/compile_cache.py)', ()),
    ('counter', 'perf_persistent_cache_misses_total',
     'backend compiles that missed the persistent compile cache', ()),
)


def record_perf_schema(registry):
    """Register the perf-introspection families on `registry` and return
    {name: family}. Used by CompileWatchdog/StepTimeline at construction
    and by dryrun_registry so the committed baseline covers perf."""
    from .registry import exponential_buckets
    buckets = {
        # trace/lower/compile stages span ~1ms (CPU toy) to minutes
        'perf_compile_seconds': exponential_buckets(0.001, 2.0, 18),
        # step phases span ~0.1ms (decode dispatch) to tens of seconds
        'perf_step_phase_seconds': exponential_buckets(1e-4, 2.0, 20),
    }
    out = {}
    for kind, name, doc, labels in PERF_FAMILIES:
        kw = {}
        if kind == 'histogram':
            kw['buckets'] = buckets[name]
        out[name] = getattr(registry, kind)(name, doc, labels, **kw)
    return out


def record_tracing_schema(registry):
    """Register the span-tracer health families (spans started /
    finished / dropped, flight dumps, exemplar count) on `registry` —
    the tracing block of the dryrun snapshot. Same single-source rule:
    tracers and the schema baseline both go through
    tracing.register_metrics."""
    from . import tracing
    return tracing.register_metrics(registry)


# the per-request serving families (serving/metrics.py + the engines'
# retrace canary). Same single-source rule: ServingMetrics and the
# schema baseline both register through record_serving_request_schema.
# Label budget: program is the engine's closed program set (prefill/
# decode/verify).
SERVING_REQUEST_FAMILIES = (
    ('counter', 'serving_requests_total',
     'requests submitted to the engine', ()),
    ('counter', 'serving_requests_admitted_total',
     'requests bound to a KV slot', ()),
    ('counter', 'serving_requests_retired_total',
     'requests finished and released', ()),
    ('counter', 'serving_tokens_total',
     'tokens emitted to consumers', ()),
    ('histogram', 'serving_ttft_seconds',
     'arrival to first visible token', ()),
    ('histogram', 'serving_inter_token_seconds',
     'per-token gap (burst spread over its tokens)', ()),
    ('gauge', 'serving_queue_depth',
     'requests waiting for a slot', ()),
    ('gauge', 'serving_occupancy',
     'occupied-slot fraction, last step', ()),
    ('counter', 'serving_prefill_tokens_total',
     'prompt tokens actually prefilled (prefix-cache hits excluded)', ()),
    ('gauge', 'serving_trace_count',
     'times each serving program has been traced '
     '(flat == zero retrace)', ('program',)),
)


def record_serving_request_schema(registry):
    """Register the per-request serving families on `registry` and
    return {name: family}. Used by ServingMetrics at construction and by
    dryrun_registry so the committed baseline covers the request path."""
    from .registry import exponential_buckets
    buckets = {
        # inter-token gaps live around 1-100 ms on hardware, seconds on
        # CPU CI; TTFT adds prefill, so its ladder starts higher
        'serving_ttft_seconds': exponential_buckets(0.002, 2.0, 16),
        'serving_inter_token_seconds': exponential_buckets(0.0005, 2.0,
                                                           16),
    }
    out = {}
    for kind, name, doc, labels in SERVING_REQUEST_FAMILIES:
        kw = {}
        if kind == 'histogram':
            kw['buckets'] = buckets[name]
        out[name] = getattr(registry, kind)(name, doc, labels, **kw)
    return out


# the RPC resilience families (distributed/resilience.py). Single-source
# rule again: ResilientChannel/CircuitBreaker and the schema baseline
# both register through record_rpc_schema. Label budgets: endpoint is
# the bounded server set; `to` is the three breaker states.
RPC_FAMILIES = (
    ('counter', 'rpc_attempts_total',
     'RPC attempts begun (first tries + retries)', ('endpoint',)),
    ('counter', 'rpc_attempt_failures_total',
     'retryable transport failures (each feeds the circuit breaker)',
     ('endpoint',)),
    ('counter', 'rpc_backoff_seconds_total',
     'seconds slept between retries', ('endpoint',)),
    ('counter', 'rpc_deadline_expired_total',
     'calls that died on their deadline', ('endpoint',)),
    ('counter', 'rpc_circuit_open_total',
     'calls fast-failed by an open breaker', ('endpoint',)),
    ('counter', 'rpc_breaker_transitions_total',
     'circuit-breaker state transitions', ('endpoint', 'to')),
    ('gauge', 'rpc_breaker_state',
     'current breaker state: 0 closed, 1 open, 2 half-open',
     ('endpoint',)),
)


def record_rpc_schema(registry):
    """Register the RPC resilience families on `registry` and return
    {name: family}."""
    out = {}
    for kind, name, doc, labels in RPC_FAMILIES:
        out[name] = getattr(registry, kind)(name, doc, labels)
    return out


# the per-op client counters of the two socket services. Label budget:
# op is each service's closed OP_SEMANTICS vocabulary.
CLIENT_OP_FAMILIES = (
    ('counter', 'ps_client_calls_total',
     'embedding-service client RPCs by op', ('op',)),
    ('counter', 'ps_client_call_errors_total',
     'embedding-service client RPCs that raised', ('op',)),
    ('counter', 'graph_client_calls_total',
     'graph-service client RPCs by op', ('op',)),
    ('counter', 'graph_client_call_errors_total',
     'graph-service client RPCs that raised', ('op',)),
)


def record_client_op_schema(registry):
    """Register the service-client per-op counters on `registry` and
    return {name: family}."""
    out = {}
    for kind, name, doc, labels in CLIENT_OP_FAMILIES:
        out[name] = getattr(registry, kind)(name, doc, labels)
    return out


# the training-loop families hapi.callbacks adds beyond the dryrun step
# gauges (record_dryrun_step covers the shared names via get-or-create).
TRAIN_LOOP_FAMILIES = (
    ('histogram', 'train_step_duration_seconds',
     'train step wall time', ()),
    ('gauge', 'train_epoch', 'current epoch index', ()),
)


def record_train_loop_schema(registry):
    """Register the TelemetryCallback-only training families on
    `registry` and return {name: family}."""
    from .registry import exponential_buckets
    out = {}
    for kind, name, doc, labels in TRAIN_LOOP_FAMILIES:
        kw = {}
        if kind == 'histogram':
            kw['buckets'] = exponential_buckets(0.001, 2.0, 16)
        out[name] = getattr(registry, kind)(name, doc, labels, **kw)
    return out


# the fleet-federation collector's health families (monitor/
# federation.py). Single-source rule: FleetCollector and the schema
# baseline both register through record_fleet_schema. Label budget
# (docs/observability.md): instance is the bounded set of registered
# scrape targets (replica indices / shard endpoints) — never
# per-request, never per-scrape.
FLEET_FAMILIES = (
    ('gauge', 'fleet_target_up',
     '1 when the last scrape of the target succeeded, else 0',
     ('instance',)),
    ('gauge', 'fleet_target_staleness_seconds',
     'seconds since the target last scraped successfully '
     '(-1 = never scraped)', ('instance',)),
    ('gauge', 'fleet_targets',
     'scrape targets registered with the collector', ()),
    ('counter', 'fleet_scrapes_total',
     'federation scrape cycles completed', ()),
    ('counter', 'fleet_scrape_errors_total',
     'failed target scrapes (target kept stale, never dropped)',
     ('instance',)),
    ('histogram', 'fleet_scrape_seconds',
     'wall time of one federation scrape cycle', ()),
    ('counter', 'fleet_merge_conflicts_total',
     'families dropped from a merge for type/label/bucket mismatch',
     ()),
)


def record_fleet_schema(registry):
    """Register the federation families on `registry` and return
    {name: family}. Used by FleetCollector at construction and by
    dryrun_registry so the committed baseline covers federation."""
    from .registry import exponential_buckets
    out = {}
    for kind, name, doc, labels in FLEET_FAMILIES:
        kw = {}
        if kind == 'histogram':
            # a cycle spans sub-ms (in-proc) to seconds (slow HTTP peer)
            kw['buckets'] = exponential_buckets(0.0005, 2.0, 16)
        out[name] = getattr(registry, kind)(name, doc, labels, **kw)
    return out


# the SLO alerting families (monitor/alerts.py). Single-source rule:
# AlertManager and the schema baseline both register through
# record_alert_schema. Label budgets: rule is the declared rule set;
# `to` is the closed lifecycle vocabulary {pending, firing, resolved,
# inactive}.
ALERT_FAMILIES = (
    ('gauge', 'alerts_firing',
     '1 while the rule is firing', ('rule',)),
    ('gauge', 'alerts_pending',
     '1 while the rule is pending (condition true, for_duration not '
     'yet met)', ('rule',)),
    ('counter', 'alerts_transitions_total',
     'alert lifecycle transitions taken', ('rule', 'to')),
    ('counter', 'alerts_evaluations_total',
     'alert evaluation passes', ()),
)


def record_alert_schema(registry):
    """Register the alerting families on `registry` and return
    {name: family}. Used by AlertManager at construction and by
    dryrun_registry so the committed baseline covers alerting."""
    out = {}
    for kind, name, doc, labels in ALERT_FAMILIES:
        out[name] = getattr(registry, kind)(name, doc, labels)
    return out


# the elastic training supervisor's families (distributed/supervisor.py).
# Single-source rule: TrainingSupervisor/ShardSupervisor and the schema
# baseline both register through record_supervisor_schema. Label
# budgets: role is the closed shard vocabulary {trainer, ps, graph};
# kind is {periodic, urgent}; stage is the escalation ladder
# {restart, restore, abort}.
SUPERVISOR_FAMILIES = (
    ('counter', 'supervisor_restarts_total',
     'shard restarts driven by the supervisor', ('role',)),
    ('histogram', 'supervisor_recover_seconds',
     'MTTR: liveness-miss detection to shard recovered', ()),
    ('counter', 'supervisor_checkpoints_total',
     'training checkpoints written by the supervisor', ('kind',)),
    ('counter', 'supervisor_preemptions_total',
     'preemption notices honored with an urgent checkpoint', ()),
    ('counter', 'supervisor_journal_replays_total',
     'journaled push entries replayed after a shard recovery', ()),
    ('counter', 'supervisor_journal_dedup_hits_total',
     'replayed/retried journaled pushes the server deduplicated', ()),
    ('counter', 'supervisor_escalations_total',
     'recovery escalation stages entered', ('stage',)),
    ('gauge', 'supervisor_shards_alive',
     'shards passing liveness at the last heartbeat round', ()),
)


def record_supervisor_schema(registry):
    """Register the elastic-supervisor families on `registry` and return
    {name: family}. Used by the supervisor at construction and by
    dryrun_registry so the committed baseline covers recovery."""
    from .registry import exponential_buckets
    out = {}
    for kind, name, doc, labels in SUPERVISOR_FAMILIES:
        kw = {}
        if kind == 'histogram':
            # recovery spans ~10ms (in-proc restart) to minutes (pod
            # reschedule + snapshot restore + journal replay)
            kw['buckets'] = exponential_buckets(0.01, 2.0, 16)
        out[name] = getattr(registry, kind)(name, doc, labels, **kw)
    return out


# the wide-event request log's health families (monitor/events.py).
# Single-source rule: RequestLog and the schema baseline both register
# through record_request_event_schema. Unlabeled — the log is a
# process-level object, per-request detail lives in the events
# themselves, never in labels.
REQUEST_EVENT_FAMILIES = (
    ('counter', 'request_events_total',
     'wide request events emitted (one per completed serving request)'),
    ('counter', 'request_events_dropped_total',
     'wide events evicted from the bounded in-memory ring'),
    ('counter', 'request_sink_rotations_total',
     'request-log JSONL sink files rotated at the size cap'),
)


def record_request_event_schema(registry):
    """Register the wide-event request-log families on `registry` and
    return {name: family}. Used by RequestLog at construction and by
    dryrun_registry so the committed baseline covers the event log."""
    out = {}
    for kind, name, doc in REQUEST_EVENT_FAMILIES:
        out[name] = getattr(registry, kind)(name, doc)
    return out


# the per-tenant attribution families. Single-source rule: the engines'
# ServingMetrics, the gateway and the schema baseline all register
# through record_tenant_schema. Label budget (docs/observability.md):
# tenant is BOUNDED by construction — events.TenantLabeler interns the
# first cap (default 16) distinct tenants and folds the rest into a
# fixed set of hashed overflow_<n> buckets, so worst-case cardinality is
# cap + overflow buckets + the 'default' label, independent of traffic.
TENANT_FAMILIES = (
    ('counter', 'tenant_requests_total',
     'requests completed per tenant', ('tenant',)),
    ('counter', 'tenant_tokens_total',
     'generated tokens delivered per tenant', ('tenant',)),
    ('histogram', 'tenant_ttft_seconds',
     'time to first token per tenant', ('tenant',)),
    ('counter', 'tenant_kv_byte_seconds_total',
     'KV-cache bytes held x seconds, attributed per tenant', ('tenant',)),
)


def record_tenant_schema(registry):
    """Register the per-tenant attribution families on `registry` and
    return {name: family}. Used by ServingMetrics / ServingGateway at
    construction and by dryrun_registry so the committed baseline covers
    tenant attribution."""
    from .registry import exponential_buckets
    out = {}
    for kind, name, doc, labels in TENANT_FAMILIES:
        kw = {}
        if kind == 'histogram':
            # same ladder as the unlabeled TTFT families
            kw['buckets'] = exponential_buckets(0.002, 2.0, 16)
        out[name] = getattr(registry, kind)(name, doc, labels, **kw)
    return out


# the QoS enforcement families (serving/gateway/admission.py +
# capacity/qos.py): admission decisions, preempt/resume traffic and the
# token-bucket levels the admission layer runs on. Single-source rule:
# the gateway's admission hooks, the engines' preemption path and the
# schema baseline all register through record_qos_schema. Label budgets
# (docs/observability.md): tenant is bounded by TenantLabeler exactly
# like TENANT_FAMILIES; reason is the closed rejection vocabulary
# {rate, quota, queue_full, deadline}; priority is the closed set of
# priorities declared in the configured QosPolicy classes (stringified
# ints — config-bounded, never per-request).
QOS_FAMILIES = (
    ('counter', 'qos_admitted_total',
     'requests passed by the admission layer per tenant', ('tenant',)),
    ('counter', 'qos_rejected_total',
     'requests shed by the admission layer per reason and tenant',
     ('reason', 'tenant')),
    ('counter', 'qos_preempted_total',
     'KV-page preemptions of low-priority residents per tenant',
     ('tenant',)),
    ('counter', 'qos_resumed_total',
     'previously preempted requests re-admitted per tenant', ('tenant',)),
    ('gauge', 'qos_token_bucket_level',
     'remaining token-bucket credit per tenant at the last admission '
     'decision', ('tenant',)),
    ('histogram', 'qos_ttft_seconds',
     'time to first token per priority class (premium vs background)',
     ('priority',)),
)


def record_qos_schema(registry):
    """Register the QoS enforcement families on `registry` and return
    {name: family}. Used by the gateway admission layer / ServingMetrics
    at construction and by dryrun_registry so the committed baseline
    covers QoS."""
    from .registry import exponential_buckets
    out = {}
    for kind, name, doc, labels in QOS_FAMILIES:
        kw = {}
        if kind == 'histogram':
            # same ladder as the unlabeled TTFT families
            kw['buckets'] = exponential_buckets(0.002, 2.0, 16)
        out[name] = getattr(registry, kind)(name, doc, labels, **kw)
    return out


# the capacity-planning families (paddle_tpu/capacity/): trace replay
# against the real gateway plus the discrete-event fleet simulator.
# Single-source rule: replay.replay/simulator.simulate and the schema
# baseline all register through record_capacity_schema. Unlabeled —
# per-request and per-tenant detail lives in the wide events the runs
# emit, never in labels.
CAPACITY_FAMILIES = (
    ('counter', 'capacity_requests_replayed_total',
     'trace requests submitted by the open-loop replay harness'),
    ('counter', 'capacity_replay_runs_total',
     'completed open-loop trace replays'),
    ('histogram', 'capacity_replay_lag_seconds',
     'worst submit-behind-schedule lag per replay run'),
    ('counter', 'sim_requests_total',
     'requests pushed through the discrete-event fleet simulator'),
    ('counter', 'sim_runs_total',
     'completed fleet-simulator runs'),
    ('gauge', 'sim_last_p99_ttft_seconds',
     'p99 simulated TTFT of the most recent simulator run'),
)


def record_capacity_schema(registry):
    """Register the capacity-planning families on `registry` and return
    {name: family}. Used by capacity.replay / capacity.simulate when
    handed a registry and by dryrun_registry so the committed baseline
    covers capacity planning."""
    from .registry import exponential_buckets
    out = {}
    for kind, name, doc in CAPACITY_FAMILIES:
        kw = {}
        if kind == 'histogram':
            # replay lag spans scheduler jitter (~ms) to a saturated
            # submitter falling a full trace behind (~minutes)
            kw['buckets'] = exponential_buckets(0.001, 2.0, 18)
        out[name] = getattr(registry, kind)(name, doc, **kw)
    return out


# the streaming ingestion plane's families (paddle_tpu/data/). Single-
# source rule: IngestPipeline and the schema baseline both register
# through record_ingest_schema. Unlabeled — a pipeline is a per-process
# object; per-shard and per-epoch detail lives in bench rows and the
# cursor, never in labels.
INGEST_FAMILIES = (
    ('counter', 'ingest_records_total',
     'records emitted downstream by the ingestion pipeline'),
    ('counter', 'ingest_batches_total',
     'collated batches delivered to the consumer'),
    ('counter', 'ingest_bytes_read_total',
     'shard payload bytes read off disk'),
    ('gauge', 'ingest_queue_depth',
     'prefetched batches parked in the bounded hand-off queue'),
    ('counter', 'ingest_backpressure_seconds_total',
     'producer seconds blocked on a full prefetch queue '
     '(consumer is the bottleneck)'),
    ('counter', 'ingest_wait_seconds_total',
     'consumer seconds blocked waiting for a batch '
     '(the data_wait the StepTimeline charges to input)'),
    ('gauge', 'ingest_examples_per_second',
     'examples/s over the last completed epoch'),
    ('counter', 'ingest_epochs_total',
     'epochs fully streamed by the pipeline'),
    ('counter', 'ingest_resumes_total',
     'mid-epoch cursor restores (seek, not drain)'),
)


def record_ingest_schema(registry):
    """Register the streaming-ingestion families on `registry` and
    return {name: family}. Used by IngestPipeline at construction and by
    dryrun_registry so the committed baseline covers ingestion."""
    out = {}
    for kind, name, doc in INGEST_FAMILIES:
        out[name] = getattr(registry, kind)(name, doc)
    return out


# the multi-model serving registry/weight-paging families
# (paddle_tpu/serving/registry/). Single-source rule: ModelHost and the
# schema baseline both register through record_registry_schema. Label
# budget (docs/observability.md): `model` is bounded by ModelLabeler —
# the TenantLabeler discipline applied to model names, so a caller
# spraying model ids can never explode cardinality.
REGISTRY_FAMILIES = (
    ('gauge', 'registry_resident_bytes',
     'artifact bytes of models currently paged in on this host', ()),
    ('gauge', 'registry_models_resident',
     'model versions currently resident on this host', ()),
    ('counter', 'registry_loads_total',
     'model loads (weight page-ins) per model', ('model',)),
    ('counter', 'registry_evictions_total',
     'model evictions (weight page-outs) per model', ('model',)),
    ('counter', 'registry_evictions_deferred_total',
     'evictions deferred because in-flight requests still referenced '
     'the weights', ()),
    ('histogram', 'registry_load_seconds',
     'wall seconds to bring a model resident (artifact load + engine '
     'build, warmup included when performed)', ()),
    ('counter', 'registry_warm_load_cache_hits_total',
     'persistent-compile-cache hits observed during warm model '
     'bring-ups (rollout warmups)', ()),
    ('counter', 'registry_warm_load_cache_misses_total',
     'persistent-compile-cache misses observed during warm model '
     'bring-ups (a rollout that recompiled)', ()),
    ('counter', 'registry_rollouts_total',
     'version rollouts completed per model', ('model',)),
)


def record_registry_schema(registry):
    """Register the model-registry/weight-paging families on `registry`
    and return {name: family}. Used by ModelHost at construction and by
    dryrun_registry so the committed baseline covers multi-model
    serving."""
    from .registry import exponential_buckets
    out = {}
    for kind, name, doc, labels in REGISTRY_FAMILIES:
        kw = {}
        if kind == 'histogram':
            # spans a stub-engine reload (~ms) through a cold multi-GB
            # artifact load + compile (~minutes)
            kw['buckets'] = exponential_buckets(0.001, 2.0, 18)
        out[name] = getattr(registry, kind)(name, doc, labels, **kw) \
            if labels else getattr(registry, kind)(name, doc, **kw)
    return out


def dryrun_registry(step_seconds, loss, batch=None, registry=None):
    """Fresh per-config registry holding the full dryrun telemetry
    schema: training gauges + serving + tracing + perf families + one
    runtime sample. Pass `registry` to fold live instrumentation into
    the snapshot (the dryrun hands in the registry its CompileWatchdog /
    StepTimeline populated around the measured step); families already
    present are reused via get-or-create."""
    reg = registry if registry is not None else MetricRegistry()
    record_dryrun_step(reg, step_seconds, loss, batch=batch)
    record_serving_schema(reg)
    record_serving_request_schema(reg)
    record_gateway_schema(reg)
    record_tracing_schema(reg)
    record_perf_schema(reg)
    record_rpc_schema(reg)
    record_client_op_schema(reg)
    record_train_loop_schema(reg)
    record_fleet_schema(reg)
    record_alert_schema(reg)
    record_supervisor_schema(reg)
    record_request_event_schema(reg)
    record_tenant_schema(reg)
    record_qos_schema(reg)
    record_capacity_schema(reg)
    record_ingest_schema(reg)
    record_registry_schema(reg)
    RuntimeSampler(registry=reg, jax_metrics=True).sample_once()
    return reg


def snapshot_line(registry, n_devices, tag):
    """One parseable line embedding the registry snapshot (no per-bucket
    detail — schema + scalar values only, keeps the line short).

    `tag` follows the sharding_audit convention: the driver's config
    label INCLUDING its brackets (e.g. '[dp/mp/sharding fused-ce]')."""
    snap = export.to_dict(registry, buckets=False)
    return 'telemetry_snapshot(%d)%s: %s' % (
        n_devices, tag, json.dumps(snap, sort_keys=True,
                                   separators=(',', ':')))


def parse_snapshot_lines(text):
    """{tag: snapshot dict} from captured driver output (tolerates
    interleaved non-telemetry lines; later duplicates of a tag win)."""
    out = {}
    for line in (text or '').splitlines():
        m = LINE_RE.search(line)
        if not m:
            continue
        try:
            out[m.group('tag')] = json.loads(m.group('json'))
        except ValueError:
            continue
    return out
