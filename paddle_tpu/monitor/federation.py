"""Cross-process metric federation: one merged view of a fleet.

Every process in the reference deployment — PS shards, graph shards,
gateway replicas (SURVEY.md §3.5/§3.6) — owns a private MetricRegistry;
until now nothing could answer "how many tokens did the FLEET serve".
The FleetCollector closes that gap Prometheus-style: **pull-based**
scraping of registered targets, either

- **in-process registries** (gateway replicas, in-proc PS shards) read
  directly through ``export.to_dict``, or
- **HTTP targets** — any peer running a MetricsServer — fetched from
  its ``/metrics.json`` endpoint,

then merged into one snapshot with fixed semantics per metric kind:

==========  ===========================================================
counter     summed across targets per label set (totals stay EXACT:
            every target's last-known value participates, so a dead
            target's already-counted work is never lost and the merged
            total is monotone)
gauge       kept per target with an added ``instance`` label (summing
            occupancies across replicas would manufacture nonsense;
            pass-through when the family already carries ``instance`` —
            federation-of-federations)
histogram   merged bucket-wise per label set — requires the fixed
            shared boundaries ``registry.Histogram`` guarantees; sums
            and counts add, so fleet-level percentiles come from the
            merged buckets
==========  ===========================================================

Failure is data, not absence: a target whose scrape fails keeps its
last-known snapshot (marked **stale**) in the merge and flips
``fleet_target_up{instance}`` to 0 — consumers see exact totals plus an
explicit liveness signal, never silently shrinking sums. Each scrape
cycle runs under a ``fleet.scrape`` span with one child span per target
riding the existing tracer, so a slow shard shows up in the flight
ring like any other laggard.

Transport ops fire the resilience fault hooks ('send'/'recv' scoped to
the target's endpoint), so ``chaos.partition`` black-holes a scrape
target exactly as it does a socket peer — the chaos federation test
kills a target mid-cycle this way.

Everything here is stdlib-only and import-safe without jax (the
check_metrics_snapshot loading rule for monitor/): the resilience hook
import is lazy and optional.
"""
import json
import re
import threading
import time
import urllib.request

from . import export
from .registry import default_registry

__all__ = ['ScrapeTarget', 'FleetCollector', 'merge_snapshots',
           'fleet_snapshot_line', 'FLEET_LINE_RE']

FLEET_LINE_RE = re.compile(r'fleet_snapshot\((?P<n>\d+)\)'
                           r'\[(?P<tag>[^\]]*)\]:\s*(?P<json>\{.*\})\s*$')

_fire_fault_points = None


def _fire(point, endpoint):
    """Resilience chaos hooks, imported lazily so monitor/ stays loadable
    without the distributed package (and without jax)."""
    global _fire_fault_points
    if _fire_fault_points is None:
        try:
            from ..distributed.resilience import fire_fault_points
        except Exception:
            def fire_fault_points(point, endpoint):
                return None
        _fire_fault_points = fire_fault_points
    _fire_fault_points(point, endpoint)


class ScrapeTarget:
    """One scrapeable peer: an in-process registry OR a /metrics.json
    URL. `instance` is the merge label value — keep it bounded and
    stable (replica index, shard endpoint), never per-request."""

    def __init__(self, instance, registry=None, url=None, timeout=2.0):
        if (registry is None) == (url is None):
            raise ValueError('pass exactly one of registry= or url=')
        self.instance = str(instance)
        self.registry = registry
        self.url = None
        if url is not None:
            url = url.rstrip('/')
            self.url = url if url.endswith('.json') \
                else url + '/metrics.json'
        self.timeout = float(timeout)
        self.endpoint = self.url or ('inproc://%s' % self.instance)

    def fetch(self):
        """One scrape: the target's full to_dict snapshot. Raises on an
        unreachable/dead target; the chaos hooks fire around the fetch
        so an injected partition surfaces as the same failure."""
        _fire('send', self.endpoint)
        if self.registry is not None:
            snap = export.to_dict(self.registry)
        else:
            with urllib.request.urlopen(self.url,
                                        timeout=self.timeout) as resp:
                snap = json.loads(resp.read().decode('utf-8'))
        _fire('recv', self.endpoint)
        if not isinstance(snap, dict):
            raise ValueError('target %s returned non-dict snapshot'
                             % self.instance)
        return snap

    def __repr__(self):
        return 'ScrapeTarget(%s, %s)' % (self.instance, self.endpoint)


def _sample_key(labels):
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def merge_snapshots(named_snaps, conflicts=None):
    """Merge [(instance, to_dict snapshot)] into one snapshot dict.

    Pure function — the collector calls it with last-known snapshots,
    tests call it directly. Families that disagree across targets on
    type, label keys, or histogram bucket boundaries are dropped from
    the merge and reported in `conflicts` (a list, appended in place):
    a wrong answer is worse than a missing one.
    """
    merged = {}                  # name -> {'type','labels',samples dict}
    skipped = set()
    for instance, snap in named_snaps:
        for name, fam in snap.items():
            if name in skipped:
                continue
            kind = fam.get('type')
            labels = list(fam.get('labels') or ())
            gauge_passthrough = kind == 'gauge' and 'instance' in labels
            out_labels = labels if kind != 'gauge' or gauge_passthrough \
                else labels + ['instance']
            cur = merged.get(name)
            if cur is None:
                cur = merged[name] = {'type': kind,
                                      'labels': out_labels,
                                      '_samples': {}}
            elif cur['type'] != kind or cur['labels'] != out_labels:
                if conflicts is not None:
                    conflicts.append({'family': name,
                                      'instance': instance,
                                      'problem': 'type_or_labels'})
                skipped.add(name)
                del merged[name]
                continue
            for s in fam.get('samples', ()):
                slabels = dict(s.get('labels') or {})
                if kind == 'gauge':
                    if not gauge_passthrough:
                        slabels['instance'] = instance
                    cur['_samples'][_sample_key(slabels)] = {
                        'labels': slabels,
                        'value': float(s.get('value') or 0.0)}
                elif kind == 'histogram':
                    key = _sample_key(slabels)
                    acc = cur['_samples'].get(key)
                    if acc is None:
                        acc = cur['_samples'][key] = {
                            'labels': slabels, 'count': 0, 'sum': 0.0}
                    acc['count'] += int(s.get('count') or 0)
                    acc['sum'] += float(s.get('sum') or 0.0)
                    buckets = s.get('buckets')
                    if buckets is not None:
                        mine = acc.setdefault('buckets', {})
                        if mine and set(mine) != set(buckets):
                            if conflicts is not None:
                                conflicts.append({
                                    'family': name,
                                    'instance': instance,
                                    'problem': 'bucket_bounds'})
                            skipped.add(name)
                            del merged[name]
                            break
                        for b, n in buckets.items():
                            mine[b] = mine.get(b, 0) + int(n)
                else:                     # counter
                    key = _sample_key(slabels)
                    acc = cur['_samples'].get(key)
                    if acc is None:
                        acc = cur['_samples'][key] = {
                            'labels': slabels, 'value': 0.0}
                    acc['value'] += float(s.get('value') or 0.0)
    out = {}
    for name, fam in merged.items():
        out[name] = {'type': fam['type'], 'labels': fam['labels'],
                     'samples': [fam['_samples'][k]
                                 for k in sorted(fam['_samples'])]}
    return out


class _TargetState:
    """Per-target scrape bookkeeping (guarded by the collector lock)."""

    __slots__ = ('target', 'snapshot', 'up', 'stale', 'last_ok',
                 'last_error', 'scrapes', 'errors')

    def __init__(self, target):
        self.target = target
        self.snapshot = None
        self.up = False
        self.stale = False
        self.last_ok = None
        self.last_error = None
        self.scrapes = 0
        self.errors = 0

    def status(self, now):
        return {
            'endpoint': self.target.endpoint,
            'up': bool(self.up),
            'stale': bool(self.stale),
            'scrapes': self.scrapes,
            'errors': self.errors,
            'staleness_s': (None if self.last_ok is None
                            else round(now - self.last_ok, 3)),
            'last_error': self.last_error,
        }


class FleetCollector:
    """Pull-based scraper + merger over a set of ScrapeTargets.

        fc = FleetCollector()
        fc.add_target('replica-0', registry=rep0.registry)
        fc.add_target('ps-shard-1', url=shard_metrics_server.url)
        fc.scrape()                     # one cycle (or start(interval))
        fc.merged()                     # fleet-wide snapshot
        fc.fleet_status()               # /fleet body: targets + merged

    The collector's own health families (fleet_target_up,
    fleet_scrapes_total, ...) live on `registry` — single-sourced in
    telemetry.FLEET_FAMILIES like every other subsystem. Disabled
    (`enabled=False` or disable()) the collector scrapes nothing and
    merged() serves the last view: zero cost outside scrape calls, and
    nothing here ever touches the RPC or decode hot paths.
    """

    def __init__(self, registry=None, tracer=None, clock=None,
                 timeout=2.0, enabled=True):
        from .telemetry import record_fleet_schema
        self.registry = registry if registry is not None \
            else default_registry()
        if tracer is None:
            from .tracing import default_tracer
            tracer = default_tracer()
        self.tracer = tracer
        self.clock = clock or time.time
        self.timeout = float(timeout)
        self.enabled = bool(enabled)
        fams = record_fleet_schema(self.registry)
        self._m_up = fams['fleet_target_up']
        self._m_staleness = fams['fleet_target_staleness_seconds']
        self._m_targets = fams['fleet_targets']
        self._m_scrapes = fams['fleet_scrapes_total']
        self._m_errors = fams['fleet_scrape_errors_total']
        self._m_cycle = fams['fleet_scrape_seconds']
        self._m_conflicts = fams['fleet_merge_conflicts_total']
        self._lock = threading.Lock()
        self._targets = {}            # instance -> _TargetState
        self._thread = None
        self._stop = threading.Event()

    # ---- membership ----------------------------------------------------

    def add_target(self, instance, registry=None, url=None, timeout=None):
        """Register a scrape target; returns it. Re-registering an
        instance name replaces the target but keeps no old data."""
        t = ScrapeTarget(instance, registry=registry, url=url,
                         timeout=self.timeout if timeout is None
                         else timeout)
        with self._lock:
            self._targets[t.instance] = _TargetState(t)
            self._m_targets.set(len(self._targets))
        return t

    def remove_target(self, instance):
        with self._lock:
            st = self._targets.pop(str(instance), None)
            self._m_targets.set(len(self._targets))
        return None if st is None else st.target

    def targets(self):
        with self._lock:
            return [st.target for st in self._targets.values()]

    # ---- scraping ------------------------------------------------------

    def enable(self):
        self.enabled = True

    def disable(self):
        self.enabled = False

    def scrape(self):
        """One federation cycle: fetch every target, update liveness and
        staleness, keep last-known snapshots for the merge. Returns
        {'ok': n, 'down': n}. A failing target never fails the cycle."""
        if not self.enabled:
            return {'ok': 0, 'down': 0, 'skipped': True}
        t0 = self.clock()
        with self._lock:
            states = list(self._targets.values())
        ok = down = 0
        with self.tracer.start_span('fleet.scrape',
                                    tags={'targets': len(states)}) as cyc:
            for st in states:
                with self.tracer.start_span(
                        'fleet.scrape.target',
                        tags={'instance': st.target.instance}) as span:
                    try:
                        snap = st.target.fetch()
                    except Exception as exc:  # noqa: BLE001 — transport
                        span.set_error(exc)
                        with self._lock:
                            st.up = False
                            st.stale = st.snapshot is not None
                            st.last_error = repr(exc)
                            st.errors += 1
                        self._m_errors.labels(st.target.instance).inc()
                        down += 1
                        continue
                    with self._lock:
                        st.snapshot = snap
                        st.up = True
                        st.stale = False
                        st.last_ok = self.clock()
                        st.last_error = None
                        st.scrapes += 1
                    ok += 1
            now = self.clock()
            for st in states:
                inst = st.target.instance
                self._m_up.labels(inst).set(1.0 if st.up else 0.0)
                self._m_staleness.labels(inst).set(
                    -1.0 if st.last_ok is None else now - st.last_ok)
            cyc.set_tag('ok', ok)
            cyc.set_tag('down', down)
        self._m_scrapes.inc()
        self._m_cycle.observe(self.clock() - t0)
        return {'ok': ok, 'down': down}

    # ---- the merged view -----------------------------------------------

    def merged(self, buckets=True):
        """The fleet-wide snapshot (to_dict shape) over every target's
        last-known data — stale targets included, so counter totals are
        monotone across target death. `buckets=False` trims per-bucket
        histogram detail (the fleet_snapshot dryrun line)."""
        with self._lock:
            named = [(st.target.instance, st.snapshot)
                     for st in self._targets.values()
                     if st.snapshot is not None]
        conflicts = []
        out = merge_snapshots(named, conflicts=conflicts)
        if conflicts:
            self._m_conflicts.inc(len(conflicts))
        if not buckets:
            for fam in out.values():
                if fam['type'] == 'histogram':
                    for s in fam['samples']:
                        s.pop('buckets', None)
        return out

    def fleet_status(self):
        """The /fleet body: per-target liveness + the merged snapshot."""
        now = self.clock()
        with self._lock:
            targets = {inst: st.status(now)
                       for inst, st in self._targets.items()}
        return {'ts': now, 'targets': targets,
                'up': sum(1 for t in targets.values() if t['up']),
                'merged': self.merged()}

    # ---- background loop (production cadence) --------------------------

    def start(self, interval_s=10.0):
        """Scrape on a daemon thread every `interval_s` until stop()."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def _run():
            while not self._stop.wait(interval_s):
                try:
                    self.scrape()
                except Exception:   # noqa: BLE001 — keep the loop alive
                    pass

        self._thread = threading.Thread(target=_run,
                                        name='fleet-collector',
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def fleet_snapshot_line(collector, n_devices, tag):
    """One parseable dryrun line embedding the fleet status (bucket
    detail trimmed — same discipline as telemetry.snapshot_line).
    Parsed back by tools/fleet_status.py via FLEET_LINE_RE."""
    status = collector.fleet_status()
    status['merged'] = collector.merged(buckets=False)
    return 'fleet_snapshot(%d)%s: %s' % (
        n_devices, tag, json.dumps(status, sort_keys=True,
                                   separators=(',', ':')))
