"""RuntimeSampler: background capture of host/runtime health gauges.

What an operator needs on a dashboard BEFORE anything is wrong: host
RSS (is the host-side sparse table / dataloader leaking?), live jax
array bytes (is the device heap creeping toward the 13B-class OOM the
sharding tests gate?), device count (did a chip drop out of the mesh?),
and compiled-program cache sizes (is something retracing per step? —
the serving engine's whole design is that these stay flat).

Every probe is individually guarded: a jax internals rename degrades one
gauge to absent instead of killing the sampler thread. `sample_once()`
is the deterministic test surface; the thread just calls it on an
interval.
"""
import os
import threading

from .registry import default_registry

__all__ = ['RuntimeSampler', 'read_rss_bytes', 'jax_cache_entries']


def read_rss_bytes():
    """Resident set size in bytes from /proc (no psutil in the image);
    None where /proc is unavailable (macOS CI)."""
    try:
        with open('/proc/self/status') as f:
            for line in f:
                if line.startswith('VmRSS:'):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        # ru_maxrss is the PEAK, not current — still monotone-useful
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return None


def jax_cache_entries():
    """Total entries across jax's weakref-LRU tracing caches plus the
    C++ pjit executable caches — a flat number means no retrace churn.

    The infer-params cache is already a member of the weakref-LRU list,
    so it must not be added again; the C++ fast-path caches
    (PjitFunctionCache) are NOT in that list, and without them this
    probe under-reports jax.jit churn on current jaxlib — every
    steady-state jit call resolves through them."""
    total = 0
    try:
        import jax._src.util as _u
        for c in list(_u._weakref_lru_caches):
            try:
                total += c.cache_info().currsize
            except Exception:
                continue
    except Exception:
        return None
    try:
        import jax._src.pjit as _pjit
        for cache in (_pjit._cpp_pjit_cache_fun_only,
                      _pjit._cpp_pjit_cache_explicit_attributes):
            total += cache.size()
    except Exception:
        pass
    return total


class RuntimeSampler:
    """Periodic gauges over one registry.

        sampler = RuntimeSampler(interval=10.0)
        sampler.start()          # daemon thread; stop() to quit
        sampler.sample_once()    # or: one deterministic capture

    Extra probes register via ``add_source(fn)`` where fn(registry) is
    called per sample (the serving engine wires its trace counts this
    way).
    """

    def __init__(self, registry=None, interval=10.0, jax_metrics=True):
        self.registry = registry if registry is not None \
            else default_registry()
        self.interval = float(interval)
        self._jax = bool(jax_metrics)
        self._stop = threading.Event()
        self._thread = None
        self._sources = []
        r = self.registry
        self._rss = r.gauge('process_resident_bytes',
                            'host RSS of this process')
        self._live_bytes = r.gauge('jax_live_array_bytes',
                                   'bytes held by live jax arrays')
        self._live_count = r.gauge('jax_live_array_count',
                                   'number of live jax arrays')
        self._devices = r.gauge('jax_device_count',
                                'devices visible to this process')
        self._caches = r.gauge('jax_trace_cache_entries',
                               'entries across jax tracing caches '
                               '(flat == no retrace churn)')
        self._samples = r.counter('runtime_samples_total',
                                  'runtime sampler iterations')

    def add_source(self, fn):
        """Register an extra probe fn(registry), run every sample."""
        self._sources.append(fn)
        return fn

    def sample_once(self):
        rss = read_rss_bytes()
        if rss is not None:
            self._rss.set(rss)
        if self._jax:
            try:
                import jax
                arrays = jax.live_arrays()
                self._live_bytes.set(
                    sum(getattr(a, 'nbytes', 0) for a in arrays))
                self._live_count.set(len(arrays))
                self._devices.set(len(jax.devices()))
            except Exception:
                pass
            entries = jax_cache_entries()
            if entries is not None:
                self._caches.set(entries)
        for fn in list(self._sources):
            try:
                fn(self.registry)
            except Exception:
                pass  # a broken probe must not take the sampler down
        self._samples.inc()

    def _run(self):
        while not self._stop.wait(self.interval):
            self.sample_once()

    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name='runtime-sampler', daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=self.interval + 1.0)
        self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
