"""Scrape endpoint: a stdlib http.server serving /metrics and /healthz.

Prometheus-compatible without the prometheus_client dependency (the
image bakes nothing in): text exposition 0.0.4 on /metrics, a tiny JSON
liveness body on /healthz, the tracer's flight-recorder ring on
/debug/traces (?format=chrome for a Perfetto-loadable body), the
federated fleet view on /fleet (?scrape=1 to force a cycle, ?format=prom
for text exposition of the merge), alert state on /alerts when a
FleetCollector / AlertManager is attached, and the wide-event request
log on /requests (?tenant= / ?model= / ?outcome= / ?min_failovers= /
?since_ts= / ?until_ts= / ?limit= filters) when a RequestLog is
attached, 404 elsewhere. HEAD is
answered on every route (load-balancer probes use it and must not see
http.server's default 501). Ephemeral-port by default so tests and
multi-engine processes never collide; `.port`/`.url` report the bound
address.
"""
import http.server
import json
import threading
import time

from . import export
from .registry import default_registry

__all__ = ['MetricsServer']

CONTENT_TYPE = 'text/plain; version=0.0.4; charset=utf-8'


class _Handler(http.server.BaseHTTPRequestHandler):
    # one scrape per connection is fine; keep-alive complicates shutdown
    protocol_version = 'HTTP/1.0'

    def _route(self):
        """(code, content-type, body) for the request path — shared by
        GET and HEAD so probe responses carry the real headers."""
        path, _, query = self.path.partition('?')
        if path == '/metrics':
            return (200, CONTENT_TYPE,
                    export.to_prometheus(self.server.registry).encode())
        if path in ('/healthz', '/health'):
            # liveness only: the process is up and serving scrapes. A
            # draining replica stays live (kubelet must not restart it)
            # even though /readyz says to stop routing to it.
            body = json.dumps({
                'status': 'ok',
                'uptime_s': round(time.monotonic() - self.server.started,
                                  3)}).encode()
            return 200, 'application/json', body
        if path == '/readyz':
            check = getattr(self.server, 'readiness', None)
            ready = True if check is None else bool(check())
            body = json.dumps({
                'status': 'ready' if ready else 'draining'}).encode()
            return (200 if ready else 503), 'application/json', body
        if path == '/metrics.json':
            return (200, 'application/json',
                    export.to_json(self.server.registry).encode())
        if path == '/fleet':
            coll = getattr(self.server, 'collector', None)
            if coll is None:
                return (404, 'text/plain; charset=utf-8',
                        b'no fleet collector attached\n')
            # pull-based federation: ?scrape=1 forces a cycle before
            # answering (the offline CLI's freshness knob); the default
            # serves the collector's last merged view
            if 'scrape=1' in query:
                coll.scrape()
            if 'format=prom' in query:
                return (200, CONTENT_TYPE,
                        export.snapshot_to_prometheus(
                            coll.merged()).encode())
            return (200, 'application/json',
                    json.dumps(coll.fleet_status()).encode())
        if path == '/alerts':
            mgr = getattr(self.server, 'alerts', None)
            if mgr is None:
                return (404, 'text/plain; charset=utf-8',
                        b'no alert manager attached\n')
            if 'evaluate=1' in query:
                mgr.evaluate()
            return (200, 'application/json',
                    json.dumps({'firing': mgr.firing(),
                                'alerts': mgr.state()}).encode())
        if path == '/requests':
            log = getattr(self.server, 'events', None)
            if log is None:
                return (404, 'text/plain; charset=utf-8',
                        b'no request log attached\n')
            import urllib.parse
            q = urllib.parse.parse_qs(query)

            def _one(name, conv=str):
                vals = q.get(name)
                return None if not vals else conv(vals[0])

            try:
                evs = log.events(tenant=_one('tenant'),
                                 model=_one('model'),
                                 outcome=_one('outcome'),
                                 min_failovers=_one('min_failovers', int),
                                 since_ts=_one('since_ts', float),
                                 until_ts=_one('until_ts', float),
                                 limit=_one('limit', int))
            except ValueError:
                return (400, 'text/plain; charset=utf-8',
                        b'min_failovers/limit must be integers and '
                        b'since_ts/until_ts floats\n')
            body = json.dumps({'count': len(evs),
                               'dropped': log.dropped,
                               'events': evs}).encode()
            return 200, 'application/json', body
        if path == '/debug/traces':
            tracer = getattr(self.server, 'tracer', None)
            if tracer is None:
                return (404, 'text/plain; charset=utf-8',
                        b'no tracer attached\n')
            rec = tracer.recorder
            if 'format=chrome' in query:
                body = json.dumps(rec.to_chrome()).encode()
            else:
                body = json.dumps({'enabled': tracer.enabled,
                                   'capacity': rec.capacity,
                                   'dropped': rec.dropped,
                                   'spans': rec.spans()}).encode()
            return 200, 'application/json', body
        return 404, 'text/plain; charset=utf-8', b'not found\n'

    def do_GET(self):
        self._reply(*self._route())

    def do_HEAD(self):
        self._reply(*self._route(), head=True)

    def _reply(self, code, ctype, body, head=False):
        self.send_response(code)
        self.send_header('Content-Type', ctype)
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        if not head:
            self.wfile.write(body)

    def log_message(self, fmt, *args):
        pass  # scrapes every few seconds must not spam stderr


class _HTTPServer(http.server.ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


class MetricsServer:
    """Background scrape server over one registry.

        srv = MetricsServer()            # default registry, ephemeral port
        srv.start()
        ... curl http://127.0.0.1:<srv.port>/metrics ...
        srv.stop()

    Also a context manager. Serving runs on a daemon thread, so a process
    exit never hangs on an open scrape socket.
    """

    def __init__(self, registry=None, host='127.0.0.1', port=0,
                 tracer=None, readiness=None, collector=None,
                 alerts=None, events=None):
        self.registry = registry if registry is not None \
            else default_registry()
        if tracer is None:
            from .tracing import default_tracer
            tracer = default_tracer()
        self.tracer = tracer
        # /readyz: liveness (/healthz) says "don't restart me", readiness
        # says "route to me". None = always ready; otherwise a zero-arg
        # callable — e.g. a gateway replica's `.ready` — evaluated per
        # probe so a drain flips the route to 503 without a restart.
        self.readiness = readiness
        # /fleet: a monitor.federation.FleetCollector (merged fleet
        # snapshot + per-target liveness); /alerts: a
        # monitor.alerts.AlertManager. Both optional — unattached
        # routes answer 404 like any unknown path.
        self.collector = collector
        self.alerts = alerts
        # /requests: a monitor.events.RequestLog (the wide-event ring).
        # Optional like the collector — unattached answers 404.
        self.events = events
        self._host = host
        self._port = int(port)
        self._srv = None
        self._thread = None

    def start(self):
        if self._srv is not None:
            return self
        self._srv = _HTTPServer((self._host, self._port), _Handler)
        self._srv.registry = self.registry
        self._srv.tracer = self.tracer
        self._srv.readiness = self.readiness
        self._srv.collector = self.collector
        self._srv.alerts = self.alerts
        self._srv.events = self.events
        self._srv.started = time.monotonic()
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        name='metrics-server', daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._srv is None:
            return
        self._srv.shutdown()
        self._srv.server_close()
        self._srv = None
        self._thread = None

    @property
    def port(self):
        if self._srv is None:
            raise RuntimeError('server not started')
        return self._srv.server_address[1]

    @property
    def url(self):
        return 'http://%s:%d' % (self._host, self.port)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
