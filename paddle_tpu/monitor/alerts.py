"""Declarative SLO alerting over (federated) metric snapshots.

The collector (monitor/federation.py) answers "what is the fleet
doing"; this module answers "is that OK" — without a human watching a
dashboard. Rules are pure declarations evaluated against a snapshot
under an **injectable clock**, so every lifecycle edge is unit-testable
at analytically exact ticks (the autoscaler discipline):

- ``ThresholdRule`` — scalar comparison (counter/gauge sample vs a
  threshold) that must hold for ``for_duration`` seconds before firing;
- ``BurnRateRule`` — multi-window SLO burn over a latency histogram
  (the Google-SRE pattern): from cumulative bucket counts it derives
  the fraction of observations over the SLO bound per trailing window,
  divides by the error budget (1 - objective), and fires when BOTH the
  long and the short window of any (long_s, short_s, factor) pair
  exceed `factor` — the long window proves the burn is real, the short
  window proves it is still happening, so recovered incidents resolve
  fast and blips never page.

Lifecycle per rule: inactive → pending (condition true, waiting out
``for_duration``) → firing → resolved (condition false for
``resolve_after`` — the hysteresis that stops a sawtoothing signal
from flapping). Every firing edge writes EXACTLY ONE flight-recorder
dump (reason ``alert_firing``) so the spans around the regression are
preserved the moment it is detected, and state is exported three ways:
``alerts_firing{rule}`` / ``alerts_pending{rule}`` gauges, an
``alerts_transitions_total{rule,to}`` counter, and the ``/alerts``
endpoint (monitor/server.py).

Stdlib-only, import-safe without jax, zero cost off the evaluate()
path: nothing here hooks the RPC or decode loops.
"""
import bisect
import collections
import math
import threading
import time

from .registry import default_registry

__all__ = ['AlertRule', 'ThresholdRule', 'BurnRateRule', 'AlertManager',
           'HistogramWindow', 'find_sample', 'federated_burn_source',
           'INACTIVE', 'PENDING', 'FIRING']

INACTIVE = 'inactive'
PENDING = 'pending'
FIRING = 'firing'

_OPS = {
    '>': lambda a, b: a > b,
    '>=': lambda a, b: a >= b,
    '<': lambda a, b: a < b,
    '<=': lambda a, b: a <= b,
    '==': lambda a, b: a == b,
}


def find_sample(snapshot, metric, labels=None):
    """The first sample of `metric` whose labels are a superset of
    `labels` (None/{} matches the first sample); None when absent."""
    fam = snapshot.get(metric)
    if not fam:
        return None
    want = dict(labels or {})
    for s in fam.get('samples', ()):
        have = s.get('labels') or {}
        if all(have.get(k) == str(v) for k, v in want.items()):
            return s
    return None


class AlertRule:
    """Base rule: a name plus lifecycle timings. Subclasses implement
    ``condition(snapshot, now) -> (active, value)``; value is whatever
    scalar best explains the decision (shown in /alerts)."""

    def __init__(self, name, for_duration=0.0, resolve_after=0.0):
        if not name:
            raise ValueError('rules need a name')
        self.name = str(name)
        self.for_duration = float(for_duration)
        self.resolve_after = float(resolve_after)

    def condition(self, snapshot, now):
        raise NotImplementedError

    def describe(self):
        return {'name': self.name, 'kind': type(self).__name__,
                'for_duration': self.for_duration,
                'resolve_after': self.resolve_after}


class ThresholdRule(AlertRule):
    """`metric <op> threshold`, sustained for `for_duration` seconds.

    The metric sample is a counter/gauge value (or a histogram's count
    when `field='count'`). A missing metric or sample is NOT active —
    absence alerts belong to `fleet_target_up` threshold rules, which
    this composes with: ThresholdRule('ps-down', 'fleet_target_up',
    0.5, op='<', labels={'instance': 'ps:0'}).
    """

    def __init__(self, name, metric, threshold, op='>', labels=None,
                 field='value', **kw):
        super().__init__(name, **kw)
        if op not in _OPS:
            raise ValueError('op must be one of %s' % sorted(_OPS))
        self.metric = str(metric)
        self.threshold = float(threshold)
        self.op = op
        self.labels = dict(labels or {})
        self.field = field

    def condition(self, snapshot, now):
        s = find_sample(snapshot, self.metric, self.labels)
        if s is None:
            return False, None
        value = s.get(self.field)
        if value is None:
            return False, None
        value = float(value)
        return _OPS[self.op](value, self.threshold), value

    def describe(self):
        d = super().describe()
        d.update(metric=self.metric, op=self.op,
                 threshold=self.threshold, labels=self.labels)
        return d


class HistogramWindow:
    """Windowed rates from a cumulative histogram sample.

    Histograms are cumulative-since-birth; SLO burn needs trailing
    windows. This ring keeps (t, count, over_count) at each update and
    answers `fraction(window_s, now)` = share of observations over the
    SLO bound within the window, by differencing against the newest
    sample at or before the window start (partial windows fall back to
    the oldest retained sample — conservative, never fabricated).

    `slo_le` must be one of the histogram's fixed bucket bounds: the
    over-count is then exact (count - cumulative count at le=slo_le),
    not interpolated. A mismatched bound raises at update time — an
    alert that silently measured the wrong SLO is the worst outcome.
    """

    def __init__(self, slo_le, horizon_s=3600.0):
        self.slo_le = float(slo_le)
        self.horizon_s = float(horizon_s)
        self._ring = collections.deque()      # (t, count, over)

    def update(self, sample, now):
        """Fold one histogram sample (to_dict shape with buckets)."""
        count = int(sample.get('count') or 0)
        buckets = sample.get('buckets')
        if buckets is None:
            raise ValueError('histogram sample carries no buckets '
                             '(snapshot taken with buckets=False?)')
        good = 0
        matched = False
        for b, n in buckets.items():
            bound = math.inf if b == '+Inf' else float(b)
            if bound <= self.slo_le:
                good += int(n)
                if bound == self.slo_le:
                    matched = True
        if not matched:
            raise ValueError('slo_le=%g is not a bucket bound of the '
                             'histogram (bounds must be fixed and '
                             'shared)' % self.slo_le)
        over = count - good
        self._ring.append((float(now), count, over))
        while self._ring and now - self._ring[0][0] > self.horizon_s:
            self._ring.popleft()

    def _at(self, t):
        """Newest ring entry with timestamp <= t (oldest as fallback)."""
        times = [e[0] for e in self._ring]
        i = bisect.bisect_right(times, t) - 1
        return self._ring[max(i, 0)]

    def fraction(self, window_s, now):
        """Over-SLO fraction of observations inside the window; 0.0 on
        no evidence (empty ring or no new observations)."""
        if not self._ring:
            return 0.0
        t0, c0, o0 = self._at(now - window_s)
        _, c1, o1 = self._ring[-1]
        dc = c1 - c0
        if dc <= 0:
            return 0.0
        return (o1 - o0) / float(dc)


class BurnRateRule(AlertRule):
    """Multi-window error-budget burn over a latency histogram.

    objective: the SLO (e.g. 0.95 = 95% of requests under slo_le
    seconds); budget = 1 - objective. windows: ((long_s, short_s,
    factor), ...) — active when, for ANY pair, burn(long) >= factor AND
    burn(short) >= factor, where burn(w) = over-fraction(w) / budget.
    Defaults follow the SRE workbook two-pair setup scaled to minutes
    (the injectable clock makes the absolute scale a test choice).
    """

    def __init__(self, name, metric, slo_le, objective=0.95,
                 windows=((300.0, 60.0, 14.4), (3600.0, 300.0, 6.0)),
                 labels=None, horizon_s=None, **kw):
        super().__init__(name, **kw)
        if not 0.0 < objective < 1.0:
            raise ValueError('objective must be in (0, 1)')
        self.metric = str(metric)
        self.slo_le = float(slo_le)
        self.objective = float(objective)
        self.budget = 1.0 - self.objective
        self.windows = tuple((float(l), float(s), float(f))
                             for l, s, f in windows)
        if not self.windows:
            raise ValueError('need at least one (long, short, factor)')
        self.labels = dict(labels or {})
        horizon = horizon_s if horizon_s is not None \
            else 2.0 * max(l for l, _, _ in self.windows)
        self._window = HistogramWindow(self.slo_le, horizon_s=horizon)

    def condition(self, snapshot, now):
        s = find_sample(snapshot, self.metric, self.labels)
        if s is not None:
            self._window.update(s, now)
        burns = [(self._window.fraction(l, now) / self.budget,
                  self._window.fraction(sh, now) / self.budget, f)
                 for l, sh, f in self.windows]
        active = any(bl >= f and bs >= f for bl, bs, f in burns)
        worst = max((min(bl, bs) for bl, bs, _ in burns), default=0.0)
        return active, worst

    def describe(self):
        d = super().describe()
        d.update(metric=self.metric, slo_le=self.slo_le,
                 objective=self.objective,
                 windows=[list(w) for w in self.windows],
                 labels=self.labels)
        return d


class _RuleState:
    __slots__ = ('state', 'pending_since', 'firing_since', 'clear_since',
                 'fired_count', 'resolved_count', 'last_value',
                 'last_transition_t')

    def __init__(self):
        self.state = INACTIVE
        self.pending_since = None
        self.firing_since = None
        self.clear_since = None
        self.fired_count = 0
        self.resolved_count = 0
        self.last_value = None
        self.last_transition_t = None


class AlertManager:
    """Evaluates rules against a snapshot source on demand.

        mgr = AlertManager([rule, ...], source=collector.merged)
        mgr.evaluate()        # call on the scrape cadence / fake clock
        mgr.state()           # /alerts body
        mgr.firing()          # rule names currently firing

    `source` is any zero-arg callable returning a to_dict-shaped
    snapshot — a FleetCollector's merged(), a bare registry via
    ``lambda: export.to_dict(reg)``, or a parsed fleet_snapshot line.
    The flight `recorder` (default: the tracer's) receives exactly one
    dump per pending→firing edge, bypassing the cooldown throttle — the
    rule's own for_duration/resolve_after hysteresis IS the throttle.
    """

    def __init__(self, rules, source, registry=None, recorder=None,
                 clock=None):
        from .telemetry import record_alert_schema
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError('duplicate rule names: %r' % (names,))
        self.rules = list(rules)
        self._source = source
        self.clock = clock or time.time
        self.registry = registry if registry is not None \
            else default_registry()
        if recorder is None:
            from .tracing import default_tracer
            recorder = default_tracer().recorder
        self.recorder = recorder
        fams = record_alert_schema(self.registry)
        self._m_firing = fams['alerts_firing']
        self._m_pending = fams['alerts_pending']
        self._m_transitions = fams['alerts_transitions_total']
        self._m_evals = fams['alerts_evaluations_total']
        self._lock = threading.Lock()
        self._states = {r.name: _RuleState() for r in self.rules}
        for r in self.rules:          # zero-init so /metrics shows all
            self._m_firing.labels(r.name).set(0)
            self._m_pending.labels(r.name).set(0)

    # ---- lifecycle -----------------------------------------------------

    def _transition(self, rule, st, to, now):
        st.state = to if to in (PENDING, FIRING) else INACTIVE
        st.last_transition_t = now
        self._m_transitions.labels(rule.name, to).inc()
        self._m_pending.labels(rule.name).set(
            1 if st.state == PENDING else 0)
        self._m_firing.labels(rule.name).set(
            1 if st.state == FIRING else 0)

    def _on_firing_edge(self, rule):
        """Exactly one flight dump per edge (when a dump dir exists)."""
        rec = self.recorder
        if rec is None or not getattr(rec, 'dump_dir', None):
            return None
        try:
            return rec.dump('alert_firing')
        except OSError:
            return None

    def evaluate(self, now=None):
        """One pass over every rule; returns [(rule_name, transition)]
        for the edges taken this pass ('pending', 'firing', 'resolved',
        'inactive')."""
        now = self.clock() if now is None else now
        snapshot = self._source()
        edges = []
        with self._lock:
            self._m_evals.inc()
            for rule in self.rules:
                st = self._states[rule.name]
                active, value = rule.condition(snapshot, now)
                st.last_value = value
                if st.state == INACTIVE:
                    if active:
                        st.pending_since = now
                        if rule.for_duration <= 0.0:
                            st.firing_since = now
                            st.fired_count += 1
                            self._transition(rule, st, FIRING, now)
                            self._on_firing_edge(rule)
                            edges.append((rule.name, FIRING))
                        else:
                            self._transition(rule, st, PENDING, now)
                            edges.append((rule.name, PENDING))
                elif st.state == PENDING:
                    if not active:
                        st.pending_since = None
                        self._transition(rule, st, INACTIVE, now)
                        edges.append((rule.name, INACTIVE))
                    elif now - st.pending_since >= rule.for_duration:
                        st.firing_since = now
                        st.fired_count += 1
                        self._transition(rule, st, FIRING, now)
                        self._on_firing_edge(rule)
                        edges.append((rule.name, FIRING))
                elif st.state == FIRING:
                    if active:
                        st.clear_since = None       # hysteresis reset
                    else:
                        if st.clear_since is None:
                            st.clear_since = now
                        if now - st.clear_since >= rule.resolve_after:
                            st.clear_since = None
                            st.firing_since = None
                            st.pending_since = None
                            st.resolved_count += 1
                            self._transition(rule, st, 'resolved', now)
                            edges.append((rule.name, 'resolved'))
        return edges

    # ---- export --------------------------------------------------------

    def state(self):
        """The /alerts body: one entry per rule, JSON-able."""
        with self._lock:
            out = []
            for rule in self.rules:
                st = self._states[rule.name]
                out.append({
                    'rule': rule.describe(),
                    'state': st.state,
                    'value': st.last_value,
                    'pending_since': st.pending_since,
                    'firing_since': st.firing_since,
                    'fired_count': st.fired_count,
                    'resolved_count': st.resolved_count,
                    'last_transition_t': st.last_transition_t,
                })
            return out

    def firing(self):
        with self._lock:
            return sorted(name for name, st in self._states.items()
                          if st.state == FIRING)


def federated_burn_source(collector, slo_ttft_s,
                          metric='gateway_ttft_seconds', window_s=30.0,
                          labels=None):
    """A burn-rate reader over the FEDERATED view, shaped for
    ServingGateway.autoscale_tick's burn override: `fn(now) -> fraction
    of windowed observations over the SLO`. Lets one autoscaler act on
    TTFT aggregated across every gateway process in the fleet instead
    of only its own in-process samples. `slo_ttft_s` must be a bucket
    bound of the TTFT histogram (it is: the gateway buckets are fixed
    exponential)."""
    window = HistogramWindow(slo_ttft_s, horizon_s=4.0 * window_s)

    def burn(now):
        s = find_sample(collector.merged(), metric, labels)
        if s is not None:
            window.update(s, now)
        return window.fraction(window_s, now)
    return burn
