"""paddle.jit: to_static + save/load (reference: python/paddle/fluid/dygraph/
jit.py:161 declarative, dygraph_to_static/program_translator.py:759).

TPU-native: no AST transpiler — jax.jit traces python control flow directly
(loops unroll; data-dependent branches need lax helpers, same contract as the
reference's control-flow ops). A "ConcreteProgram" is a cached jitted
callable keyed by input signature. jit.save exports StableHLO + weights;
jit.load returns a TranslatedLayer running the compiled artifact.
"""
import functools
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, run_op, no_grad_guard
from ..framework import functional as func_mod
from ..static.input_spec import InputSpec

__all__ = ['to_static', 'save', 'load', 'TranslatedLayer', 'not_to_static',
           'ignore_module']

# bump the MAJOR on breaking artifact-layout changes; loads refuse a
# newer major and warn on an older one (forward-compat contract)
_FORMAT_VERSION = (1, 0)


class StaticFunction:
    """Wraps a function/method: first call traces+compiles, later calls hit
    the jit cache (ConcreteProgram.from_func_spec parity)."""

    def __init__(self, fn, input_spec=None, layer=None):
        self._fn = fn
        self._input_spec = input_spec
        self._layer = layer
        self._jitted = {}
        functools.update_wrapper(self, fn)

    def __get__(self, instance, owner):
        if instance is None:
            return self
        return StaticFunction(self._fn.__get__(instance, owner),
                              self._input_spec, layer=instance)

    @property
    def _bound_layer(self):
        if self._layer is not None:
            return self._layer
        return getattr(self._fn, '__self__', None)

    def _sig(self, arrays, training):
        return tuple((tuple(a.shape), str(a.dtype)) for a in arrays) + (training,)

    def __call__(self, *args, **kwargs):
        # JAX trace errors re-frame to the user's source line
        # (dygraph_to_static/error.py capability; jit/error.py)
        from .error import trace_error_scope
        with trace_error_scope(self._fn):
            return self._call_impl(*args, **kwargs)

    def _call_impl(self, *args, **kwargs):
        layer = self._bound_layer
        in_arrays = []
        struct = []
        for a in args:
            if isinstance(a, Tensor):
                in_arrays.append(a._data)
                struct.append(None)
            else:
                struct.append(a)
        in_arrays = tuple(in_arrays)

        if layer is None:
            # plain function: closed-over Parameters discovered on the first
            # (eager, recorded) call, then lifted to jit inputs so grads flow
            from ..framework import core as core_mod
            key = self._sig(in_arrays, True)
            if key not in self._jitted:
                recorder = {}
                core_mod._param_recorder[0] = recorder
                try:
                    first_out = self._fn(*args, **kwargs)
                finally:
                    core_mod._param_recorder[0] = None
                captured = [t for t in recorder.values()]
                fn = self._fn

                def pure(*arrays):
                    n_cap = len(captured)
                    saved = [(t, t._data) for t in captured]
                    try:
                        for t, arr in zip(captured, arrays[:n_cap]):
                            t._data = arr
                        it = iter(arrays[n_cap:])
                        call_args = [Tensor(next(it), stop_gradient=False)
                                     if s is None else s for s in struct]
                        out = fn(*call_args, **kwargs)
                    finally:
                        for t, arr in saved:
                            t._data = arr
                    if isinstance(out, Tensor):
                        return out._data
                    if isinstance(out, (list, tuple)):
                        return tuple(o._data if isinstance(o, Tensor) else o
                                     for o in out)
                    return out
                self._jitted[key] = (jax.jit(pure), captured)
                return first_out
            jitted, captured = self._jitted[key]
            tensor_args = [a if isinstance(a, Tensor) else Tensor(a)
                           for a, s in zip(args, struct) if s is None]
            return run_op('to_static_fn', jitted, *captured, *tensor_args)

        # bound method on a Layer: functionalize params/buffers
        training = layer.training
        key = self._sig(in_arrays, training)
        if key not in self._jitted:
            model = layer
            method_fn = self._fn

            def pure(params, buffers, *arrays):
                def fwd(*ts):
                    it = iter(ts)
                    call_args = [next(it) if s is None else s for s in struct]
                    return method_fn(*call_args, **kwargs)
                saved, bmap = func_mod._bind(model, params, buffers)
                try:
                    t_args = [Tensor(a, stop_gradient=False) for a in arrays]
                    out = fwd(*t_args)
                    new_buf = {n: t._data for n, t in bmap.items()
                               if t is not None}
                finally:
                    for t, arr in saved:
                        t._data = arr
                if isinstance(out, (list, tuple)):
                    return tuple(o._data if isinstance(o, Tensor) else o
                                 for o in out), new_buf
                return (out._data if isinstance(out, Tensor) else out), new_buf
            self._jitted[key] = jax.jit(pure)

        params = func_mod.extract_params(layer)
        buffers = func_mod.extract_buffers(layer)
        jitted = self._jitted[key]

        # route through the tape as one op over (params..., inputs...) so
        # loss.backward() differentiates through the compiled program
        names = list(params.keys())
        pmap = dict(layer.named_parameters())
        param_tensors = [pmap[n] for n in names]
        tensor_args = [a for a, s in zip(args, struct) if s is None]
        new_buf_box = {}

        def op_fn(*arrays):
            p = dict(zip(names, arrays[:len(names)]))
            out, new_buf = jitted(p, buffers, *arrays[len(names):])
            new_buf_box.update(new_buf)
            return out

        out = run_op('to_static', op_fn, *param_tensors, *tensor_args)
        concrete = {k: v for k, v in new_buf_box.items()
                    if not isinstance(v, jax.core.Tracer)}
        if concrete:
            func_mod.write_back_buffers(layer, concrete)
        return out

    @property
    def concrete_program(self):
        return self

    def rollback(self):
        return self._fn


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    def decorate(fn):
        from ..nn.layer.layers import Layer
        if isinstance(fn, Layer):
            fn.forward = StaticFunction(fn.forward, input_spec, layer=fn)
            return fn
        return StaticFunction(fn, input_spec)
    if function is not None:
        return decorate(function)
    return decorate


declarative = to_static


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


# ---------------------------------------------------------------------------
# save / load (reference: jit.py:515 jit.save -> pdmodel+pdiparams;
# dygraph/io.py TranslatedLayer)
# ---------------------------------------------------------------------------

def save(layer, path, input_spec=None, **configs):
    """Export: weights + buffers + StableHLO of the eval-mode forward."""
    from ..nn.layer.layers import Layer
    os.makedirs(os.path.dirname(path) or '.', exist_ok=True)

    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer")
    params = func_mod.extract_params(layer)
    buffers = func_mod.extract_buffers(layer)

    state = {'params': {k: np.asarray(v) for k, v in params.items()},
             'buffers': {k: np.asarray(v) for k, v in buffers.items()}}
    with open(path + '.pdiparams', 'wb') as f:
        pickle.dump(state, f, protocol=4)

    # artifact versioning (reference: framework/op_version_registry.h +
    # framework/version.cc — saved programs carry versions and loads check
    # compatibility)
    import jax as _jax
    from .. import __version__ as _fw_version
    from ..framework import op_version as _opv
    meta = {'input_spec': None, 'stablehlo': None,
            'format_version': _FORMAT_VERSION,
            'framework_version': _fw_version,
            'jax_version': _jax.__version__,
            'op_versions': _opv.snapshot()}
    if input_spec:
        specs = [s if isinstance(s, InputSpec) else InputSpec.from_tensor(s)
                 for s in input_spec]
        meta['input_spec'] = [(tuple(s.shape), s.dtype,
                               s.name or 'input_%d' % i)
                              for i, s in enumerate(specs)]
        was_training = layer.training
        layer.eval()
        try:
            def pure(params, buffers, *arrays):
                out, _ = func_mod.functional_call(layer, params, buffers,
                                                  args=arrays, training=False)
                return out
            shaped = [jax.ShapeDtypeStruct(
                tuple(d if d and d > 0 else 1 for d in s.shape),
                np.dtype(s.dtype)) for s in specs]
            lowered = jax.jit(pure).lower(
                {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                 for k, v in params.items()},
                {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                 for k, v in buffers.items()}, *shaped)
            meta['stablehlo'] = lowered.as_text()
        finally:
            if was_training:
                layer.train()

    # architecture payload: pickled layer (class-importable contract, same
    # as paddle.save of a whole Layer)
    try:
        arch = pickle.dumps(_strip_for_pickle(layer), protocol=4)
    except Exception:
        arch = None
    with open(path + '.pdmodel', 'wb') as f:
        pickle.dump({'meta': meta, 'arch': arch}, f, protocol=4)


def _strip_for_pickle(layer):
    import copy
    # compiled-executable caches (GPTForCausalLM.generate's prefill/
    # decode FIFO caches) are unpicklable AND undeepcopyable — map them
    # to empty dicts in the memo so a model that already served traffic
    # still saves with its architecture payload intact
    memo = {}
    for l in layer.sublayers(include_self=True):
        for name in ('_prefill_cache', '_decode_cache'):
            c = getattr(l, name, None)
            if isinstance(c, dict):
                memo[id(c)] = {}
    layer2 = copy.deepcopy(layer, memo)
    for l in layer2.sublayers(include_self=True):
        l._forward_pre_hooks.clear()
        l._forward_post_hooks.clear()
        for d in (l._parameters, l._buffers):
            for k, t in list(d.items()):
                if t is not None:
                    arr = np.asarray(t._data)
                    t._data = arr  # numpy is picklable; rewrapped on load
                    t._grad = None
                    t._grad_node = None
    return layer2


class TranslatedLayer:
    """Runs a loaded program (reference: dygraph/io.py:1082)."""

    def __init__(self, layer, params, buffers, meta=None):
        self._layer = layer
        self._meta = meta or {}
        if layer is not None:
            pmap = dict(layer.named_parameters())
            for k, v in params.items():
                if k in pmap:
                    pmap[k]._data = jnp.asarray(v)
            bmap = dict(layer.named_buffers())
            for k, v in buffers.items():
                if k in bmap and bmap[k] is not None:
                    bmap[k]._data = jnp.asarray(v)

    def __call__(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    def eval(self):
        self._layer.eval()
        return self

    def train(self):
        self._layer.train()
        return self

    def parameters(self, *a, **k):
        return self._layer.parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._layer.state_dict(*a, **k)

    def forward(self, *args, **kwargs):
        return self.__call__(*args, **kwargs)


def load(path, **configs):
    with open(path + '.pdiparams', 'rb') as f:
        state = pickle.load(f)
    with open(path + '.pdmodel', 'rb') as f:
        model_payload = pickle.load(f)
    fmt = (model_payload.get('meta') or {}).get('format_version')
    if fmt is not None and tuple(fmt)[0] > _FORMAT_VERSION[0]:
        raise RuntimeError(
            'artifact %s was saved by a NEWER framework (format %s, this '
            'build reads %s) — upgrade paddle_tpu to load it'
            % (path, tuple(fmt), _FORMAT_VERSION))
    if fmt is not None and tuple(fmt)[0] < _FORMAT_VERSION[0]:
        import warnings
        warnings.warn('artifact %s uses the older format %s (current %s); '
                      'loading with best-effort compatibility'
                      % (path, tuple(fmt), _FORMAT_VERSION))
    # per-op semantic versions (framework/op_version.py; reference
    # op_version_registry.h) — refuse ops saved at newer semantics
    from ..framework import op_version as _opv
    _opv.check_compatible(
        (model_payload.get('meta') or {}).get('op_versions'), artifact=path)
    layer = None
    if model_payload.get('arch') is not None:
        layer = pickle.loads(model_payload['arch'])
        for l in layer.sublayers(include_self=True):
            for d in (l._parameters, l._buffers):
                for k, t in list(d.items()):
                    if t is not None and isinstance(t._data, np.ndarray):
                        t._data = jnp.asarray(t._data)
    return TranslatedLayer(layer, state['params'], state['buffers'],
                           meta=model_payload.get('meta'))


class ProgramTranslator:
    """Singleton facade (reference program_translator.py:759): jit tracing
    replaces the AST transpiler, so enable/disable toggles a global
    passthrough flag consumed by to_static wrappers."""
    _instance = None
    _enabled = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static=True):
        ProgramTranslator._enabled = bool(enable_to_static)


def set_verbosity(level=0, also_to_stdout=False):
    pass  # transpiler diagnostics have no analog: tracing IS the program


def set_code_level(level=100, also_to_stdout=False):
    pass


class TracedLayer:
    """reference dygraph/jit.py TracedLayer: trace once, replay compiled.
    Static-shape jit trace over a Layer call."""

    def __init__(self, layer, inputs):
        self._layer = layer
        self._fn = to_static(layer.forward)
        self._example = inputs

    @staticmethod
    def trace(layer, inputs):
        t = TracedLayer(layer, inputs)
        return t._fn(*inputs), t

    def __call__(self, *args):
        return self._fn(*args)

    def save_inference_model(self, path, feed=None, fetch=None, **kw):
        save(self._layer, path, input_spec=self._example)
