"""to_static error source-mapping (VERDICT r3 item 5; reference:
python/paddle/fluid/dygraph/dygraph_to_static/error.py + origin_info.py).

A tracing failure inside @to_static otherwise surfaces as a raw JAX stack
of framework internals. This module re-frames JAX trace-time errors to
point at the USER's model source line (JAX/framework frames filtered),
with the matching lax-helper suggestion — the reference maps translated-
program errors back to user source the same way.
"""
import contextlib
import linecache
import os

import jax

__all__ = ['ToStaticError', 'trace_error_scope']

_SKIP_DIRS = (
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),  # paddle_tpu
    os.path.dirname(os.path.abspath(jax.__file__)),               # jax
)


class ToStaticError(Exception):
    """Trace-time failure inside @to_static, re-framed to user source."""


def _user_frames(tb):
    frames = []
    while tb is not None:
        f = tb.tb_frame
        fname = os.path.abspath(f.f_code.co_filename)
        if not fname.startswith(_SKIP_DIRS) and os.path.exists(fname):
            frames.append((fname, tb.tb_lineno, f.f_code.co_name))
        tb = tb.tb_next
    return frames


def _hint_for(exc):
    name = type(exc).__name__
    if 'TracerBool' in name or 'ConcretizationType' in name:
        return ('data-dependent Python control flow cannot be traced: '
                'branch with paddle.static.nn.cond / case / switch_case '
                '(lax.cond) and loop with paddle.static.nn.while_loop '
                '(lax.while_loop) instead of if/while on Tensor values')
    if 'TracerInteger' in name:
        return ('a traced Tensor was used as a Python int (e.g. range(n) '
                'or list index): use paddle.static.nn.while_loop, or keep '
                'the value a static Python int')
    if 'TracerArray' in name:
        return ('a traced Tensor was converted to a concrete value '
                'mid-trace (bool/numpy conversion): if this is an '
                'if/while on a Tensor, use paddle.static.nn.cond / '
                'while_loop (lax.cond / lax.while_loop); otherwise keep '
                'the computation in paddle ops or pull it out of the '
                '@to_static region')
    return ('the operation is incompatible with tracing; see the chained '
            'JAX error for details')


def _is_trace_error(exc):
    """True only for genuine TRACE-time failures (JAXTypeError family:
    TracerBool/Integer/ArrayConversionError, ConcretizationTypeError).
    Runtime errors (e.g. jaxlib XlaRuntimeError — device OOM on an
    already-compiled function) must propagate untouched: re-framing them
    as tracing problems would send the user debugging the wrong thing."""
    try:
        return isinstance(exc, jax.errors.JAXTypeError)
    except AttributeError:
        return type(exc).__name__ in (
            'TracerBoolConversionError', 'TracerIntegerConversionError',
            'TracerArrayConversionError', 'ConcretizationTypeError')


@contextlib.contextmanager
def trace_error_scope(user_fn):
    """Re-raise JAX trace errors as ToStaticError pointing at user code."""
    try:
        yield
    except Exception as e:
        if not _is_trace_error(e):
            raise
        frames = _user_frames(e.__traceback__)
        target = None
        try:
            target_file = os.path.abspath(user_fn.__code__.co_filename)
            for fr in frames:
                if fr[0] == target_file:
                    target = fr  # last frame inside the user's source file
        except AttributeError:
            pass
        if target is None and frames:
            target = frames[-1]
        if target is None:
            raise
        fname, lineno, func = target
        src = linecache.getline(fname, lineno).strip()
        raise ToStaticError(
            'error while tracing @to_static function %r:\n'
            '  File "%s", line %d, in %s\n'
            '    %s\n'
            'Hint: %s\n'
            '(original JAX error chained below)'
            % (getattr(user_fn, '__name__', user_fn), fname, lineno, func,
               src, _hint_for(e))) from e
