"""Random sampling ops (reference: python/paddle/tensor/random.py).

Eager path draws keys from the global Generator (framework/random.py). Under a
jit trace these appear as constants of the trace — the train-step compiler
threads a live key instead (framework/functional.py).
"""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, wrap_out, run_op
from ..framework import random as rng
from ..framework import dtype as dtype_mod
from ._helpers import ensure_tensor, shape_arg, jdt

__all__ = [
    'check_shape',
    'rand', 'randn', 'randint', 'randint_like', 'randperm', 'uniform',
    'normal', 'standard_normal', 'bernoulli', 'multinomial', 'poisson',
    'uniform_', 'normal_', 'exponential_',
]


def _default(dtype):
    return jdt(dtype) if dtype else jdt(dtype_mod.get_default_dtype())


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.PRNGKey(seed) if seed else rng.next_key()
    return wrap_out(jax.random.uniform(key, shape_arg(shape), _default(dtype),
                                       minval=min, maxval=max))


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def randn(shape, dtype=None, name=None):
    return wrap_out(jax.random.normal(rng.next_key(), shape_arg(shape),
                                      _default(dtype)))


standard_normal = randn


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = ensure_tensor(mean)._data if isinstance(mean, Tensor) else mean
        s = ensure_tensor(std)._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(
            m.shape if hasattr(m, 'shape') else (),
            s.shape if hasattr(s, 'shape') else ())
        return wrap_out(m + s * jax.random.normal(rng.next_key(), shp, jnp.float32))
    shp = shape_arg(shape) if shape is not None else ()
    return wrap_out(mean + std * jax.random.normal(rng.next_key(), shp,
                                                   _default(None)))


def randint(low=0, high=None, shape=(1,), dtype='int64', name=None):
    if high is None:
        low, high = 0, low
    return wrap_out(jax.random.randint(rng.next_key(), shape_arg(shape),
                                       low, high, jdt(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = ensure_tensor(x)
    return randint(low, high, x.shape, dtype or x.dtype)


def randperm(n, dtype='int64', name=None):
    return wrap_out(jax.random.permutation(rng.next_key(), n).astype(jdt(dtype)))


def bernoulli(x, name=None):
    x = ensure_tensor(x)
    return wrap_out(jax.random.bernoulli(rng.next_key(), x._data).astype(x._data.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = ensure_tensor(x)
    logits = jnp.log(jnp.maximum(x._data, 1e-30))
    if replacement:
        out = jax.random.categorical(rng.next_key(), logits, axis=-1,
                                     shape=(num_samples,) + x._data.shape[:-1])
        out = jnp.moveaxis(out, 0, -1)
    else:
        g = jax.random.gumbel(rng.next_key(), x._data.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return wrap_out(out.astype(jnp.int64))


def poisson(x, name=None):
    x = ensure_tensor(x)
    return wrap_out(jax.random.poisson(rng.next_key(), x._data).astype(x._data.dtype))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x = ensure_tensor(x)
    # seed != 0 gives a reproducible draw independent of the global
    # stream (reference uniform_random_inplace semantics)
    key = jax.random.PRNGKey(seed) if seed else rng.next_key()
    x._data = jax.random.uniform(key, tuple(x._data.shape),
                                 x._data.dtype, minval=min, maxval=max)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x = ensure_tensor(x)
    x._data = mean + std * jax.random.normal(rng.next_key(), tuple(x._data.shape),
                                             x._data.dtype)
    return x


def exponential_(x, lam=1.0, name=None):
    x = ensure_tensor(x)
    x._data = jax.random.exponential(rng.next_key(),
                                     tuple(x._data.shape), x._data.dtype) / lam
    return x


def check_shape(shape, op_name='check_shape'):
    """Validate a shape ARGUMENT (reference fluid/data_feeder.py
    check_shape, re-exported at paddle.check_shape): list/tuple of ints
    (at most one -1) or an int Tensor."""
    from ..framework.core import Tensor
    if isinstance(shape, Tensor):
        if shape._data.dtype not in ('int32', 'int64') and \
                'int' not in str(shape._data.dtype):
            raise TypeError("%s: shape tensor must be int32/int64" % op_name)
        return
    if not isinstance(shape, (list, tuple)):
        raise TypeError("%s: shape must be a list/tuple/Tensor, got %r"
                        % (op_name, type(shape)))
    negs = 0
    for s in shape:
        if isinstance(s, Tensor):
            continue
        if int(s) < -1:
            raise ValueError("%s: shape dims must be >= -1" % op_name)
        if int(s) == -1:
            negs += 1
    if negs > 1:
        raise ValueError("%s: at most one dim may be -1" % op_name)
