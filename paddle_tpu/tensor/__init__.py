"""Tensor op library + Tensor method patching.

Mirrors the reference's math_op_patch.py / varbase_patch_methods.py: the wide
tensor API is defined as module functions and then attached to Tensor as
methods so `x.sum(...)`, `x + y`, `x[idx]` all work.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, run_op, wrap_out, to_tensor
from ..framework import dtype as dtype_mod

from .creation import *  # noqa: F401,F403
from .inplace import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .attribute import *  # noqa: F401,F403
from .einsum import einsum  # noqa: F401

from . import creation, math, manipulation, linalg, logic, search, stat, attribute
from . import random as random_ops
from ._helpers import ensure_tensor

# ---------------------------------------------------------------------------
# indexing
# ---------------------------------------------------------------------------

def _convert_index(item):
    """Convert paddle-style index (may contain Tensors) to jnp index."""
    def conv(i):
        if isinstance(i, Tensor):
            return i._data
        if isinstance(i, (list, tuple)) and any(isinstance(e, Tensor) for e in i):
            return jnp.asarray([e._data if isinstance(e, Tensor) else e for e in i])
        if isinstance(i, np.ndarray):
            return jnp.asarray(i)
        return i
    if isinstance(item, tuple):
        return tuple(conv(i) for i in item)
    return conv(item)


def _getitem(self, item):
    idx = _convert_index(item)
    return run_op('getitem', lambda a: a[idx], self)


def _setitem(self, item, value):
    idx = _convert_index(item)
    if isinstance(value, Tensor):
        out = run_op('setitem', lambda a, v: a.at[idx].set(v.astype(a.dtype)),
                     self, value)
    else:
        out = run_op('setitem', lambda a: a.at[idx].set(value), self)
    # version-bump semantics: this tensor becomes the op output in the graph
    self._data = out._data
    self._grad_node = out._grad_node
    self._node_out_idx = out._node_out_idx
    self.stop_gradient = out.stop_gradient


# ---------------------------------------------------------------------------
# operator overloads
# ---------------------------------------------------------------------------

def _binop(fn, reverse=False):
    def op(self, other):
        if reverse:
            return fn(other, self)
        return fn(self, other)
    return op


def _patch_operators():
    T = Tensor
    T.__getitem__ = _getitem
    T.__setitem__ = _setitem
    T.__add__ = _binop(math.add)
    T.__radd__ = _binop(math.add, True)
    T.__sub__ = _binop(math.subtract)
    T.__rsub__ = _binop(math.subtract, True)
    T.__mul__ = _binop(math.multiply)
    T.__rmul__ = _binop(math.multiply, True)
    T.__truediv__ = _binop(math.divide)
    T.__rtruediv__ = _binop(math.divide, True)
    T.__floordiv__ = _binop(math.floor_divide)
    T.__rfloordiv__ = _binop(math.floor_divide, True)
    T.__mod__ = _binop(math.mod)
    T.__rmod__ = _binop(math.mod, True)
    T.__pow__ = _binop(math.pow)
    T.__rpow__ = _binop(math.pow, True)
    T.__matmul__ = _binop(math.matmul)
    T.__rmatmul__ = _binop(math.matmul, True)
    T.__neg__ = lambda self: math.neg(self)
    T.__abs__ = lambda self: math.abs(self)
    T.__invert__ = lambda self: logic.logical_not(self)
    T.__eq__ = _binop(logic.equal)
    T.__ne__ = _binop(logic.not_equal)
    T.__lt__ = _binop(logic.less_than)
    T.__le__ = _binop(logic.less_equal)
    T.__gt__ = _binop(logic.greater_than)
    T.__ge__ = _binop(logic.greater_equal)
    T.__and__ = _binop(logic.logical_and)
    T.__or__ = _binop(logic.logical_or)
    T.__xor__ = _binop(logic.logical_xor)


_METHOD_SOURCES = [creation, math, manipulation, linalg, logic, search, stat,
                   attribute, random_ops]

_SKIP_METHODS = {'to_tensor', 'as_tensor', 'zeros', 'ones', 'full', 'arange',
                 'linspace', 'logspace', 'eye', 'empty', 'meshgrid', 'rand',
                 'randn', 'randint', 'randperm', 'uniform', 'normal',
                 'standard_normal', 'tril_indices', 'triu_indices',
                 'broadcast_shape', 'is_tensor', 'scatter_nd', 'einsum'}


def _patch_methods():
    import types
    for mod in _METHOD_SOURCES:
        for name in dir(mod):
            if name.startswith('_') or name in _SKIP_METHODS:
                continue
            fn = getattr(mod, name)
            if not isinstance(fn, types.FunctionType):
                continue
            if not hasattr(Tensor, name):
                setattr(Tensor, name, fn)
    Tensor.einsum = None  # not a method
    del Tensor.einsum

    # extra method aliases for paddle parity
    Tensor.astype = lambda self, dtype: manipulation.cast(self, dtype)
    Tensor.cast = Tensor.astype
    Tensor.numel = lambda self: creation.numel(self)
    Tensor.dim = lambda self: self.ndim
    Tensor.rank = lambda self: self.ndim
    Tensor.add_ = _inplace(math.add)
    Tensor.subtract_ = _inplace(math.subtract)
    Tensor.multiply_ = _inplace(math.multiply)
    Tensor.scale_ = _inplace(math.scale)
    Tensor.clip_ = _inplace(math.clip)
    Tensor.zero_ = lambda self: self.set_value(jnp.zeros_like(self._data)) or self
    Tensor.fill_ = lambda self, v: self.set_value(jnp.full_like(self._data, v)) or self
    Tensor.exp_ = _inplace(math.exp)
    Tensor.sqrt_ = _inplace(math.sqrt)
    Tensor.reshape_ = manipulation.reshape_
    Tensor.mean_all = lambda self: math.mean(self)


def _inplace(fn):
    def method(self, *args, **kwargs):
        out = fn(self, *args, **kwargs)
        self._data = out._data
        self._grad_node = out._grad_node
        self._node_out_idx = out._node_out_idx
        self.stop_gradient = out.stop_gradient
        return self
    return method


_patch_operators()
_patch_methods()


def set_printoptions(**kwargs):
    from .. import set_printoptions as _sp
    _sp(**kwargs)
