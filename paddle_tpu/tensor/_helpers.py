"""Shared helpers for the tensor op library.

The "op table" replacing the reference's operator registry
(paddle/fluid/framework/op_registry.h:278): every public tensor function is a
thin wrapper that closes attrs over a pure jax function and routes through
framework.core.run_op (which handles VJP recording). XLA is the kernel
library; there is no per-place dispatch.
"""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, run_op, as_jax, wrap_out
from ..framework import dtype as dtype_mod


def ensure_tensor(x, dtype=None):
    if isinstance(x, Tensor):
        return x
    return Tensor(x, dtype=dtype)


def jdt(dtype):
    return dtype_mod.to_jax_dtype(dtype)


def unary_op(name, fn):
    def op(x, name=None):
        return run_op(name or op.__name__, fn, ensure_tensor(x))
    op.__name__ = name
    op.__qualname__ = name
    return op


def _promote(x, y):
    """Paddle-ish binary promotion: python scalars follow tensor dtype."""
    xt = isinstance(x, Tensor)
    yt = isinstance(y, Tensor)
    if xt and not yt and not hasattr(y, 'shape'):
        y = Tensor(jnp.asarray(y, dtype=x._data.dtype))
    elif yt and not xt and not hasattr(x, 'shape'):
        x = Tensor(jnp.asarray(x, dtype=y._data.dtype))
    return ensure_tensor(x), ensure_tensor(y)


def binary_op(name, fn, int_to_float=False):
    def op(x, y, name=None):
        xt, yt = _promote(x, y)
        if int_to_float and not jnp.issubdtype(xt._data.dtype, jnp.inexact) \
                and not jnp.issubdtype(yt._data.dtype, jnp.inexact):
            xt = Tensor(xt._data.astype(jnp.float32))
        return run_op(name or op.__name__, fn, xt, yt)
    op.__name__ = name
    op.__qualname__ = name
    return op


def axes_arg(axis):
    """Normalize paddle axis arg (None | int | list | Tensor) to jnp form."""
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = axis.numpy()
        return int(a) if a.ndim == 0 else tuple(int(v) for v in a)
    if isinstance(axis, (list, tuple)):
        return tuple(int(as_static_int(v)) for v in axis)
    return int(axis)


def as_static_int(v):
    if isinstance(v, Tensor):
        return int(v.numpy())
    return int(v)


def shape_arg(shape):
    """Normalize paddle shape arg (list of int/Tensor, or Tensor) to tuple."""
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy().tolist())
    if isinstance(shape, (list, tuple)):
        return tuple(int(as_static_int(s)) for s in shape)
    return (int(shape),)
