"""Comparison / logical ops (reference: python/paddle/tensor/logic.py).

All comparison outputs are bool and non-differentiable — they bypass the tape.
"""
import jax.numpy as jnp

from ..framework.core import Tensor, wrap_out, run_op
from ._helpers import ensure_tensor, _promote

__all__ = [
    'equal', 'not_equal', 'less_than', 'less_equal', 'greater_than',
    'greater_equal', 'logical_and', 'logical_or', 'logical_xor', 'logical_not',
    'bitwise_and', 'bitwise_or', 'bitwise_xor', 'bitwise_not', 'is_empty',
    'is_tensor', 'allclose', 'isclose', 'equal_all',
]


def _cmp(name, fn):
    def op(x, y, name=None):
        xt, yt = _promote(x, y)
        return wrap_out(fn(xt._data, yt._data))
    op.__name__ = name
    return op


equal = _cmp('equal', jnp.equal)
not_equal = _cmp('not_equal', jnp.not_equal)
less_than = _cmp('less_than', jnp.less)
less_equal = _cmp('less_equal', jnp.less_equal)
greater_than = _cmp('greater_than', jnp.greater)
greater_equal = _cmp('greater_equal', jnp.greater_equal)
logical_and = _cmp('logical_and', jnp.logical_and)
logical_or = _cmp('logical_or', jnp.logical_or)
logical_xor = _cmp('logical_xor', jnp.logical_xor)
bitwise_and = _cmp('bitwise_and', jnp.bitwise_and)
bitwise_or = _cmp('bitwise_or', jnp.bitwise_or)
bitwise_xor = _cmp('bitwise_xor', jnp.bitwise_xor)


def logical_not(x, out=None, name=None):
    return wrap_out(jnp.logical_not(ensure_tensor(x)._data))


def bitwise_not(x, out=None, name=None):
    return wrap_out(jnp.bitwise_not(ensure_tensor(x)._data))


def is_empty(x, name=None):
    return wrap_out(jnp.asarray(ensure_tensor(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return wrap_out(jnp.allclose(x._data, y._data, rtol=float(rtol),
                                 atol=float(atol), equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return wrap_out(jnp.isclose(x._data, y._data, rtol=float(rtol),
                                atol=float(atol), equal_nan=equal_nan))


def equal_all(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    if tuple(x.shape) != tuple(y.shape):
        return wrap_out(jnp.asarray(False))
    return wrap_out(jnp.all(jnp.equal(x._data, y._data)))
