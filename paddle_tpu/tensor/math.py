"""Math op library (reference: python/paddle/tensor/math.py, ~150 fns)."""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, run_op, wrap_out
from ._helpers import ensure_tensor, unary_op, binary_op, axes_arg, jdt, _promote

# -- elementwise unary ------------------------------------------------------
exp = unary_op('exp', jnp.exp)
expm1 = unary_op('expm1', jnp.expm1)
log = unary_op('log', jnp.log)
log2 = unary_op('log2', jnp.log2)
log10 = unary_op('log10', jnp.log10)
log1p = unary_op('log1p', jnp.log1p)
sqrt = unary_op('sqrt', jnp.sqrt)
rsqrt = unary_op('rsqrt', jax.lax.rsqrt)
square = unary_op('square', jnp.square)
abs = unary_op('abs', jnp.abs)
sign = unary_op('sign', jnp.sign)
neg = unary_op('neg', jnp.negative)
reciprocal = unary_op('reciprocal', jnp.reciprocal)
sin = unary_op('sin', jnp.sin)
cos = unary_op('cos', jnp.cos)
tan = unary_op('tan', jnp.tan)
asin = unary_op('asin', jnp.arcsin)
acos = unary_op('acos', jnp.arccos)
atan = unary_op('atan', jnp.arctan)
sinh = unary_op('sinh', jnp.sinh)
cosh = unary_op('cosh', jnp.cosh)
tanh = unary_op('tanh', jnp.tanh)
asinh = unary_op('asinh', jnp.arcsinh)
acosh = unary_op('acosh', jnp.arccosh)
atanh = unary_op('atanh', jnp.arctanh)
erf = unary_op('erf', jax.scipy.special.erf)
erfinv = unary_op('erfinv', jax.scipy.special.erfinv)
floor = unary_op('floor', jnp.floor)
ceil = unary_op('ceil', jnp.ceil)
round = unary_op('round', jnp.round)
trunc = unary_op('trunc', jnp.trunc)
frac = unary_op('frac', lambda x: x - jnp.trunc(x))
angle = unary_op('angle', jnp.angle)
conj = unary_op('conj', jnp.conj)
digamma = unary_op('digamma', jax.scipy.special.digamma)
lgamma = unary_op('lgamma', jax.scipy.special.gammaln)
sigmoid = unary_op('sigmoid', jax.nn.sigmoid)
i0 = unary_op('i0', lambda x: jax.scipy.special.i0(x))

# -- elementwise binary -----------------------------------------------------
add = binary_op('add', jnp.add)
subtract = binary_op('subtract', jnp.subtract)
multiply = binary_op('multiply', jnp.multiply)
divide = binary_op('divide', jnp.divide, int_to_float=True)
floor_divide = binary_op('floor_divide', jnp.floor_divide)
mod = binary_op('mod', jnp.mod)
remainder = mod
floor_mod = mod
pow = binary_op('pow', jnp.power)
maximum = binary_op('maximum', jnp.maximum)
minimum = binary_op('minimum', jnp.minimum)
fmax = binary_op('fmax', jnp.fmax)
fmin = binary_op('fmin', jnp.fmin)
atan2 = binary_op('atan2', jnp.arctan2)
hypot = binary_op('hypot', jnp.hypot)
logaddexp = binary_op('logaddexp', jnp.logaddexp)
heaviside = binary_op('heaviside', jnp.heaviside)
nextafter = binary_op('nextafter', jnp.nextafter)
copysign = binary_op('copysign', jnp.copysign)
ldexp = binary_op('ldexp', jnp.ldexp)
gcd = binary_op('gcd', jnp.gcd)
lcm = binary_op('lcm', jnp.lcm)
inner = binary_op('inner', jnp.inner)
outer = binary_op('outer', jnp.outer)
kron = binary_op('kron', jnp.kron)

# legacy names
elementwise_add, elementwise_sub = add, subtract
elementwise_mul, elementwise_div = multiply, divide


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    x = ensure_tensor(x)
    s = scale.item() if isinstance(scale, Tensor) else scale

    def fn(a):
        out = a * s + bias if bias_after_scale else (a + bias) * s
        return out
    out = run_op('scale', fn, x)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def increment(x, value=1.0, name=None):
    x = ensure_tensor(x)
    out = run_op('increment', lambda a: a + value, x)
    x._data = out._data
    return x


def clip(x, min=None, max=None, name=None):
    x = ensure_tensor(x)
    mn = min.item() if isinstance(min, Tensor) else min
    mx = max.item() if isinstance(max, Tensor) else max
    return run_op('clip', lambda a: jnp.clip(a, mn, mx), x)


def lerp(x, y, weight, name=None):
    x, y = _promote(x, y)
    if isinstance(weight, Tensor):
        return run_op('lerp', lambda a, b, w: a + w * (b - a), x, y, weight)
    return run_op('lerp', lambda a, b: a + weight * (b - a), x, y)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return run_op('stanh', lambda a: scale_b * jnp.tanh(scale_a * a), ensure_tensor(x))


def multiplex(inputs, index, name=None):
    idx = ensure_tensor(index)
    ts = [ensure_tensor(t) for t in inputs]

    def fn(ix, *xs):
        stacked = jnp.stack(xs, axis=0)
        ix = ix.reshape(-1)
        rows = jnp.arange(stacked.shape[1])
        return stacked[ix, rows]
    return run_op('multiplex', fn, idx, *ts)


# -- reductions -------------------------------------------------------------
def _reduction(op_name, fn):
    def op(x, axis=None, keepdim=False, name=None, dtype=None):
        x = ensure_tensor(x)
        ax = axes_arg(axis)
        kw = {}
        if dtype is not None:
            kw['dtype'] = jdt(dtype)
        return run_op(op_name, lambda a: fn(a, axis=ax, keepdims=keepdim, **kw), x)
    op.__name__ = op_name
    return op


sum = _reduction('sum', jnp.sum)
prod = _reduction('prod', jnp.prod)
mean = _reduction('mean', jnp.mean)
max = _reduction('max', jnp.max)
min = _reduction('min', jnp.min)
amax = _reduction('amax', jnp.max)
amin = _reduction('amin', jnp.min)
nansum = _reduction('nansum', jnp.nansum)
nanmean = _reduction('nanmean', jnp.nanmean)
all = _reduction('all', jnp.all)
any = _reduction('any', jnp.any)


def logsumexp(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = axes_arg(axis)
    return run_op('logsumexp',
                  lambda a: jax.scipy.special.logsumexp(a, axis=ax, keepdims=keepdim), x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = axes_arg(axis)
    return wrap_out(jnp.count_nonzero(x._data, axis=ax, keepdims=keepdim))


def cumsum(x, axis=None, dtype=None, name=None):
    x = ensure_tensor(x)

    def fn(a):
        if axis is None:
            a = a.reshape(-1)
            return jnp.cumsum(a, dtype=jdt(dtype) if dtype else None)
        return jnp.cumsum(a, axis=int(axis), dtype=jdt(dtype) if dtype else None)
    return run_op('cumsum', fn, x)


def cumprod(x, dim=None, dtype=None, name=None):
    x = ensure_tensor(x)
    return run_op('cumprod',
                  lambda a: jnp.cumprod(a, axis=int(dim), dtype=jdt(dtype) if dtype else None), x)


def cummax(x, axis=None, dtype='int64', name=None):
    x = ensure_tensor(x)
    ax = 0 if axis is None else int(axis)
    vals = run_op('cummax',
                  lambda a: jax.lax.cummax(a.reshape(-1) if axis is None else a,
                                           axis=ax), x)
    # indices computed without grad
    a = x._data.reshape(-1) if axis is None else x._data
    eq = jnp.equal(jax.lax.cummax(a, axis=ax), a)
    ar = jnp.arange(a.shape[ax]).reshape(
        [-1 if i == ax else 1 for i in range(a.ndim)])
    indices = jax.lax.cummax(jnp.where(eq, ar, -1), axis=ax)
    return vals, wrap_out(indices.astype(jdt(dtype)))


def cummin(x, axis=None, dtype='int64', name=None):
    x = ensure_tensor(x)
    ax = 0 if axis is None else int(axis)
    a = x._data.reshape(-1) if axis is None else x._data
    vals = run_op('cummin', lambda v: jax.lax.cummin(v.reshape(-1) if axis is None else v,
                                                     axis=ax), x)
    eq = jnp.equal(jax.lax.cummin(a, axis=ax), a)
    ar = jnp.arange(a.shape[ax]).reshape([-1 if i == ax else 1 for i in range(a.ndim)])
    indices = jax.lax.cummax(jnp.where(eq, ar, -1), axis=ax)
    return vals, wrap_out(indices.astype(jdt(dtype)))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    x = ensure_tensor(x)
    pre = prepend._data if isinstance(prepend, Tensor) else prepend
    app = append._data if isinstance(append, Tensor) else append
    return run_op('diff', lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre, append=app), x)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return run_op('trace',
                  lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2),
                  ensure_tensor(x))


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return run_op('diagonal',
                  lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2),
                  ensure_tensor(x))


# -- predicates (no grad) ---------------------------------------------------
def isfinite(x, name=None):
    return wrap_out(jnp.isfinite(ensure_tensor(x)._data))


def isinf(x, name=None):
    return wrap_out(jnp.isinf(ensure_tensor(x)._data))


def isnan(x, name=None):
    return wrap_out(jnp.isnan(ensure_tensor(x)._data))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return run_op('nan_to_num',
                  lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
                  ensure_tensor(x))


# -- matmul-family (also exported via linalg) -------------------------------
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    x, y = _promote(x, y)

    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return run_op('matmul', fn, x, y)


def dot(x, y, name=None):
    x, y = _promote(x, y)

    def fn(a, b):
        return jnp.sum(a * b, axis=-1)
    return run_op('dot', fn, x, y)


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return matmul(x, y)


def mv(x, vec, name=None):
    return matmul(x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return run_op('addmm', lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
                  ensure_tensor(input), ensure_tensor(x), ensure_tensor(y))


def rad2deg(x, name=None):
    return run_op('rad2deg', jnp.rad2deg, ensure_tensor(x))


def deg2rad(x, name=None):
    return run_op('deg2rad', jnp.deg2rad, ensure_tensor(x))


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def take(x, index, mode='raise', name=None):
    x = ensure_tensor(x)
    idx = ensure_tensor(index)._data

    def fn(a):
        flat = a.reshape(-1)
        i = idx
        if mode == 'wrap':
            i = jnp.mod(i, flat.shape[0])
        elif mode == 'clip':
            i = jnp.clip(i, 0, flat.shape[0] - 1)
        return flat[i]
    return run_op('take', fn, x)


def add_n(inputs, name=None):
    """Sum a list of tensors elementwise (reference sum_op / add_n)."""
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    tensors = [ensure_tensor(t) for t in inputs]

    def fn(*arrays):
        out = arrays[0]
        for a in arrays[1:]:
            out = out + a
        return out
    return run_op('add_n', fn, *tensors)


def tanh_(x, name=None):
    """Inplace-alias (reference tanh_): rebinds x to tanh(x)."""
    out = tanh(x)
    if hasattr(x, '_data'):
        x._data = out._data
        x._grad_node = out._grad_node
        x._node_out_idx = getattr(out, '_node_out_idx', None)
        return x
    return out
