"""Einsum (reference: python/paddle/tensor/einsum.py) — jnp.einsum, which XLA
maps straight onto the MXU for contraction-heavy expressions."""
import jax.numpy as jnp

from ..framework.core import run_op
from ._helpers import ensure_tensor

__all__ = ['einsum']


def einsum(equation, *operands):
    ts = [ensure_tensor(t) for t in operands]
    return run_op('einsum', lambda *xs: jnp.einsum(equation, *xs), *ts)
