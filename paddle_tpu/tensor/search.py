"""Search/sort ops (reference: python/paddle/tensor/search.py).

Mixed-output ops (values+indices) follow the tape rule from framework/core:
indices are computed grad-free first, then differentiable values are gathered
with a recorded op, so VJPs never see integer cotangents.
"""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, run_op, wrap_out
from ._helpers import ensure_tensor, jdt

__all__ = [
    'argmax', 'argmin', 'argsort', 'sort', 'topk', 'where', 'nonzero',
    'index_select', 'masked_select', 'searchsorted', 'kthvalue', 'mode',
    'index_sample',
]

from .manipulation import index_select, masked_select, index_sample, take_along_axis


def argmax(x, axis=None, keepdim=False, dtype='int64', name=None):
    x = ensure_tensor(x)
    a = x._data
    if axis is None:
        out = jnp.argmax(a.reshape(-1))
        if keepdim:
            out = out.reshape((1,) * a.ndim)
        return wrap_out(out.astype(jdt(dtype)))
    out = jnp.argmax(a, axis=int(axis), keepdims=keepdim)
    return wrap_out(out.astype(jdt(dtype)))


def argmin(x, axis=None, keepdim=False, dtype='int64', name=None):
    x = ensure_tensor(x)
    a = x._data
    if axis is None:
        out = jnp.argmin(a.reshape(-1))
        if keepdim:
            out = out.reshape((1,) * a.ndim)
        return wrap_out(out.astype(jdt(dtype)))
    out = jnp.argmin(a, axis=int(axis), keepdims=keepdim)
    return wrap_out(out.astype(jdt(dtype)))


def argsort(x, axis=-1, descending=False, name=None):
    x = ensure_tensor(x)
    a = x._data
    idx = jnp.argsort(-a if descending else a, axis=axis)
    return wrap_out(idx.astype(jnp.int64))


def sort(x, axis=-1, descending=False, name=None):
    x = ensure_tensor(x)
    idx = argsort(x, axis=axis, descending=descending)
    return take_along_axis(x, idx, axis=axis)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    x = ensure_tensor(x)
    if isinstance(k, Tensor):
        k = int(k.numpy())
    ax = -1 if axis is None else int(axis)
    a = x._data
    moved = jnp.moveaxis(a, ax, -1)
    if largest:
        _, idx = jax.lax.top_k(moved, k)
    else:
        _, idx = jax.lax.top_k(-moved, k)
    idx = jnp.moveaxis(idx, -1, ax)
    vals = take_along_axis(x, wrap_out(idx), axis=ax)
    return vals, wrap_out(idx.astype(jnp.int64))


def where(condition, x=None, y=None, name=None):
    cond = ensure_tensor(condition)
    if x is None and y is None:
        return nonzero(cond, as_tuple=True)
    xt, yt = ensure_tensor(x), ensure_tensor(y)
    c = cond._data
    return run_op('where', lambda a, b: jnp.where(c, a, b), xt, yt)


def nonzero(x, as_tuple=False):
    import numpy as np
    a = np.asarray(ensure_tensor(x).numpy())
    nz = np.nonzero(a)
    if as_tuple:
        return tuple(wrap_out(jnp.asarray(i, dtype=jnp.int64)) for i in nz)
    return wrap_out(jnp.asarray(np.stack(nz, axis=1), dtype=jnp.int64))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    s = ensure_tensor(sorted_sequence)._data
    v = ensure_tensor(values)._data
    side = 'right' if right else 'left'
    if s.ndim == 1:
        out = jnp.searchsorted(s, v, side=side)
    else:
        out = jax.vmap(lambda a, b: jnp.searchsorted(a, b, side=side))(
            s.reshape(-1, s.shape[-1]), v.reshape(-1, v.shape[-1]))
        out = out.reshape(v.shape)
    return wrap_out(out.astype(jnp.int32 if out_int32 else jnp.int64))


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)
    a = x._data
    idx = jnp.argsort(a, axis=axis)
    kth_idx = jnp.take(idx, k - 1, axis=axis)
    kth_idx_e = jnp.expand_dims(kth_idx, axis)
    vals = take_along_axis(x, wrap_out(kth_idx_e), axis=axis)
    if not keepdim:
        from .manipulation import squeeze
        vals = squeeze(vals, axis=axis)
        return vals, wrap_out(kth_idx.astype(jnp.int64))
    return vals, wrap_out(kth_idx_e.astype(jnp.int64))


def mode(x, axis=-1, keepdim=False, name=None):
    import numpy as np
    a = ensure_tensor(x).numpy()
    moved = np.moveaxis(a, axis, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals = np.empty(flat.shape[0], dtype=a.dtype)
    for i, row in enumerate(flat):
        u, c = np.unique(row, return_counts=True)
        vals[i] = u[np.argmax(c)]
    vals = vals.reshape(moved.shape[:-1])
    idx = np.argmax(np.moveaxis(a, axis, -1) == vals[..., None], axis=-1)
    if keepdim:
        vals = np.expand_dims(vals, axis)
        idx = np.expand_dims(idx, axis)
    return wrap_out(jnp.asarray(vals)), wrap_out(jnp.asarray(idx, dtype=jnp.int64))
