"""Shape/layout manipulation ops (reference: python/paddle/tensor/manipulation.py)."""
import builtins
import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, run_op, wrap_out
from ._helpers import ensure_tensor, axes_arg, shape_arg, jdt, as_static_int

__all__ = [
    'reshape_', 'squeeze_', 'unsqueeze_', 'scatter_',
    'reshape', 'transpose', 'concat', 'stack', 'unstack', 'split', 'chunk',
    'squeeze', 'unsqueeze', 'flatten', 'gather', 'gather_nd', 'scatter',
    'scatter_nd', 'scatter_nd_add', 'tile', 'expand', 'expand_as',
    'broadcast_to', 'broadcast_tensors', 'flip', 'roll', 'cast', 'slice',
    'strided_slice', 'unique', 'unique_consecutive', 'masked_select',
    'index_select', 'index_sample', 'take_along_axis', 'put_along_axis',
    'tensordot', 'moveaxis', 'rot90', 'as_complex', 'as_real', 'repeat_interleave',
    'tolist', 'crop', 'fill_diagonal_', 'unbind', 'atleast_1d', 'atleast_2d', 'atleast_3d',
 'shard_index',]


def _identity_op(x):
    return run_op('identity', lambda a: a + 0, ensure_tensor(x))


def cast(x, dtype):
    x = ensure_tensor(x)
    jd = jdt(dtype)
    if jnp.issubdtype(jd, jnp.inexact) and jnp.issubdtype(x._data.dtype, jnp.inexact):
        return run_op('cast', lambda a: a.astype(jd), x)
    return wrap_out(x._data.astype(jd))


def reshape(x, shape, name=None):
    x = ensure_tensor(x)
    shp = shape_arg(shape)
    return run_op('reshape', lambda a: jnp.reshape(a, shp), x)


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._data, x._grad_node, x._node_out_idx = out._data, out._grad_node, out._node_out_idx
    x.stop_gradient = out.stop_gradient
    return x


def transpose(x, perm=None, name=None):
    x = ensure_tensor(x)
    p = tuple(int(v) for v in perm) if perm is not None else None
    return run_op('transpose', lambda a: jnp.transpose(a, p), x)


def t(x, name=None):
    x = ensure_tensor(x)
    if x.ndim > 2:
        raise ValueError("paddle.t only supports ndim<=2")
    return run_op('t', lambda a: a.T, x)


def moveaxis(x, source, destination, name=None):
    return run_op('moveaxis',
                  lambda a: jnp.moveaxis(a, source, destination), ensure_tensor(x))


def concat(x, axis=0, name=None):
    tensors = [ensure_tensor(t) for t in x]
    ax = as_static_int(axis)
    return run_op('concat', lambda *xs: jnp.concatenate(xs, axis=ax), *tensors)


def stack(x, axis=0, name=None):
    tensors = [ensure_tensor(t) for t in x]
    return run_op('stack', lambda *xs: jnp.stack(xs, axis=axis), *tensors)


def unstack(x, axis=0, num=None):
    x = ensure_tensor(x)
    n = num or x.shape[axis]
    outs = run_op('unstack',
                  lambda a: tuple(jnp.squeeze(s, axis=axis)
                                  for s in jnp.split(a, n, axis=axis)), x)
    return list(outs) if isinstance(outs, tuple) else [outs]


def unbind(input, axis=0):
    return unstack(input, axis=axis)


def split(x, num_or_sections, axis=0, name=None):
    x = ensure_tensor(x)
    ax = as_static_int(axis)
    if isinstance(num_or_sections, int):
        outs = run_op('split', lambda a: tuple(jnp.split(a, num_or_sections, axis=ax)), x)
    else:
        secs = [as_static_int(s) for s in num_or_sections]
        total = x.shape[ax]
        known = [s for s in secs if s != -1]
        secs = [s if s != -1 else total - int(np.sum(known)) for s in secs]
        idx = np.cumsum(secs)[:-1].tolist()
        outs = run_op('split', lambda a: tuple(jnp.split(a, idx, axis=ax)), x)
    return list(outs) if isinstance(outs, tuple) else [outs]


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis)


def squeeze(x, axis=None, name=None):
    x = ensure_tensor(x)
    ax = axes_arg(axis)
    if isinstance(ax, int):
        ax = (ax,)

    def fn(a):
        if ax is None:
            return jnp.squeeze(a)
        real = tuple(i for i in ax if a.shape[i if i >= 0 else a.ndim + i] == 1)
        return jnp.squeeze(a, axis=real) if real else a
    return run_op('squeeze', fn, x)


def unsqueeze(x, axis, name=None):
    x = ensure_tensor(x)
    ax = axes_arg(axis)
    if isinstance(ax, int):
        ax = (ax,)

    def fn(a):
        for i in sorted(ax):
            a = jnp.expand_dims(a, i)
        return a
    return run_op('unsqueeze', fn, x)


unsqueeze_ = unsqueeze
squeeze_ = squeeze


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = ensure_tensor(x)
    nd = x.ndim
    s = start_axis if start_axis >= 0 else nd + start_axis
    e = stop_axis if stop_axis >= 0 else nd + stop_axis

    def fn(a):
        shp = list(a.shape)
        new = shp[:s] + [-1] + shp[e + 1:]
        return jnp.reshape(a, new)
    return run_op('flatten', fn, x)


def gather(x, index, axis=0, name=None):
    x = ensure_tensor(x)
    idx = ensure_tensor(index)._data
    ax = as_static_int(axis) if not isinstance(axis, type(None)) else 0

    def fn(a):
        i = idx.reshape(-1) if idx.ndim > 1 else idx
        return jnp.take(a, i, axis=ax)
    return run_op('gather', fn, x)


def gather_nd(x, index, name=None):
    x = ensure_tensor(x)
    idx = ensure_tensor(index)._data

    def fn(a):
        ii = tuple(jnp.moveaxis(idx, -1, 0))
        return a[ii]
    return run_op('gather_nd', fn, x)


def scatter(x, index, updates, overwrite=True, name=None):
    x = ensure_tensor(x)
    u = ensure_tensor(updates)
    idx = ensure_tensor(index)._data.reshape(-1)

    def fn(a, up):
        if overwrite:
            return a.at[idx].set(up)
        zeroed = a.at[idx].set(jnp.zeros_like(up))
        return zeroed.at[idx].add(up)
    return run_op('scatter', fn, x, u)


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite)
    x._data, x._grad_node, x._node_out_idx = out._data, out._grad_node, out._node_out_idx
    x.stop_gradient = out.stop_gradient
    return x


def scatter_nd_add(x, index, updates, name=None):
    x = ensure_tensor(x)
    u = ensure_tensor(updates)
    idx = ensure_tensor(index)._data

    def fn(a, up):
        ii = tuple(jnp.moveaxis(idx, -1, 0))
        return a.at[ii].add(up)
    return run_op('scatter_nd_add', fn, x, u)


def scatter_nd(index, updates, shape, name=None):
    u = ensure_tensor(updates)
    idx = ensure_tensor(index)._data
    shp = shape_arg(shape)

    def fn(up):
        base = jnp.zeros(shp, up.dtype)
        ii = tuple(jnp.moveaxis(idx, -1, 0))
        return base.at[ii].add(up)
    return run_op('scatter_nd', fn, u)


def tile(x, repeat_times, name=None):
    x = ensure_tensor(x)
    reps = shape_arg(repeat_times)
    return run_op('tile', lambda a: jnp.tile(a, reps), x)


def expand(x, shape, name=None):
    x = ensure_tensor(x)
    shp = list(shape_arg(shape))
    xs = x.shape
    off = len(shp) - len(xs)
    for i in range(len(shp)):
        if shp[i] == -1:
            shp[i] = xs[i - off] if i >= off else 1
    return run_op('expand', lambda a: jnp.broadcast_to(a, tuple(shp)), x)


def expand_as(x, y, name=None):
    return expand(x, ensure_tensor(y).shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(input, name=None):
    ts = [ensure_tensor(t) for t in input]
    shp = jnp.broadcast_shapes(*[tuple(t.shape) for t in ts])
    return [expand(t, shp) for t in ts]


def flip(x, axis, name=None):
    x = ensure_tensor(x)
    ax = axes_arg(axis)
    return run_op('flip', lambda a: jnp.flip(a, axis=ax), x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return run_op('rot90', lambda a: jnp.rot90(a, k=k, axes=tuple(axes)),
                  ensure_tensor(x))


def roll(x, shifts, axis=None, name=None):
    x = ensure_tensor(x)
    ax = axes_arg(axis)
    sh = shifts if isinstance(shifts, int) else tuple(int(s) for s in shifts)
    return run_op('roll', lambda a: jnp.roll(a, sh, axis=ax), x)


def slice(input, axes, starts, ends, name=None):
    x = ensure_tensor(input)
    axes = [as_static_int(a) for a in axes]
    starts = [as_static_int(s) for s in starts]
    ends = [as_static_int(e) for e in ends]

    def fn(a):
        idx = [builtin_slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            idx[ax] = builtin_slice(s, e)
        return a[tuple(idx)]
    return run_op('slice', fn, x)


builtin_slice = builtins.slice


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = ensure_tensor(x)
    axes = [as_static_int(a) for a in axes]
    starts = [as_static_int(s) for s in starts]
    ends = [as_static_int(e) for e in ends]
    strides = [as_static_int(s) for s in strides]

    def fn(a):
        idx = [builtin_slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtin_slice(s, e, st)
        return a[tuple(idx)]
    return run_op('strided_slice', fn, x)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype='int64', name=None):
    x = ensure_tensor(x)
    vals, idx, inv, cnt = np.unique(x.numpy(), return_index=True,
                                    return_inverse=True, return_counts=True, axis=axis)
    outs = [wrap_out(jnp.asarray(vals))]
    if return_index:
        outs.append(wrap_out(jnp.asarray(idx, dtype=jdt(dtype))))
    if return_inverse:
        outs.append(wrap_out(jnp.asarray(inv, dtype=jdt(dtype))))
    if return_counts:
        outs.append(wrap_out(jnp.asarray(cnt, dtype=jdt(dtype))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype='int64', name=None):
    a = ensure_tensor(x).numpy()
    if axis is None:
        a = a.reshape(-1)
    keep = np.ones(a.shape[0], dtype=bool)
    keep[1:] = np.any((a[1:] != a[:-1]).reshape(a.shape[0] - 1, -1), axis=1) \
        if a.ndim > 1 else a[1:] != a[:-1]
    vals = a[keep]
    outs = [wrap_out(jnp.asarray(vals))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(wrap_out(jnp.asarray(inv, dtype=jdt(dtype))))
    if return_counts:
        pos = np.flatnonzero(keep)
        cnt = np.diff(np.append(pos, a.shape[0]))
        outs.append(wrap_out(jnp.asarray(cnt, dtype=jdt(dtype))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def masked_select(x, mask, name=None):
    x = ensure_tensor(x)
    m = ensure_tensor(mask).numpy().astype(bool)
    flat_idx = jnp.asarray(np.flatnonzero(np.broadcast_to(m, x._data.shape).reshape(-1)))

    def fn(a):
        return a.reshape(-1)[flat_idx]
    return run_op('masked_select', fn, x)


def index_select(x, index, axis=0, name=None):
    x = ensure_tensor(x)
    idx = ensure_tensor(index)._data
    return run_op('index_select', lambda a: jnp.take(a, idx, axis=axis), x)


def index_sample(x, index):
    x = ensure_tensor(x)
    idx = ensure_tensor(index)._data

    def fn(a):
        rows = jnp.arange(a.shape[0])[:, None]
        return a[rows, idx]
    return run_op('index_sample', fn, x)


def take_along_axis(arr, indices, axis, name=None):
    x = ensure_tensor(arr)
    idx = ensure_tensor(indices)._data
    return run_op('take_along_axis',
                  lambda a: jnp.take_along_axis(a, idx, axis=axis), x)


def put_along_axis(arr, indices, values, axis, reduce='assign', name=None):
    x = ensure_tensor(arr)
    v = ensure_tensor(values)
    idx = ensure_tensor(indices)._data

    def fn(a, val):
        val = jnp.broadcast_to(val, idx.shape).astype(a.dtype)
        if reduce == 'add':
            dim_idx = [jnp.arange(s).reshape([-1 if i == d else 1
                                              for i in range(a.ndim)])
                       for d, s in enumerate(idx.shape)]
            dim_idx[axis] = idx
            return a.at[tuple(dim_idx)].add(val)
        dim_idx = [jnp.arange(s).reshape([-1 if i == d else 1
                                          for i in range(a.ndim)])
                   for d, s in enumerate(idx.shape)]
        dim_idx[axis] = idx
        if reduce == 'multiply' or reduce == 'mul':
            return a.at[tuple(dim_idx)].multiply(val)
        return a.at[tuple(dim_idx)].set(val)
    return run_op('put_along_axis', fn, x, v)


def tensordot(x, y, axes=2, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    ax = axes
    if isinstance(ax, Tensor):
        ax = ax.tolist()
    if isinstance(ax, (list, tuple)):
        ax = tuple(tuple(a) if isinstance(a, (list, tuple)) else a for a in ax)
    return run_op('tensordot', lambda a, b: jnp.tensordot(a, b, axes=ax), x, y)


def as_complex(x, name=None):
    return run_op('as_complex', lambda a: jax.lax.complex(a[..., 0], a[..., 1]),
                  ensure_tensor(x))


def as_real(x, name=None):
    return run_op('as_real',
                  lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1),
                  ensure_tensor(x))


def repeat_interleave(x, repeats, axis=None, name=None):
    x = ensure_tensor(x)
    r = ensure_tensor(repeats)._data if isinstance(repeats, Tensor) else repeats
    total = None
    if not isinstance(r, int):
        total = int(np.sum(np.asarray(r)))
    return run_op('repeat_interleave',
                  lambda a: jnp.repeat(a, r, axis=axis, total_repeat_length=total), x)


def tolist(x):
    return ensure_tensor(x).tolist()


def crop(x, shape=None, offsets=None, name=None):
    x = ensure_tensor(x)
    shp = shape_arg(shape)
    offs = [as_static_int(o) for o in offsets] if offsets is not None else [0] * x.ndim
    shp = [s if s != -1 else x.shape[i] - offs[i] for i, s in enumerate(shp)]

    def fn(a):
        idx = tuple(builtin_slice(o, o + s) for o, s in zip(offs, shp))
        return a[idx]
    return run_op('crop', fn, x)


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    x = ensure_tensor(x)
    rows, cols = x.shape[-2], x.shape[-1]
    n = min(rows, cols)
    i = jnp.arange(n - (offset if offset > 0 else 0))

    def fn(a):
        r = i + (-offset if offset < 0 else 0)
        c = i + (offset if offset > 0 else 0)
        out = a.at[..., r, c].set(value)
        if wrap and rows > cols and offset == 0:
            # numpy fill_diagonal(wrap=True): tall matrices restart the
            # diagonal after a one-row gap, every (cols+1) rows
            start = cols + 1
            while start < rows:
                m = min(cols, rows - start)
                rr = jnp.arange(m) + start
                cc = jnp.arange(m)
                out = out.at[..., rr, cc].set(value)
                start += cols + 1
        return out
    out = run_op('fill_diagonal_', fn, x)
    x._data, x._grad_node, x._node_out_idx = out._data, out._grad_node, out._node_out_idx
    x.stop_gradient = out.stop_gradient
    return x


def atleast_1d(*inputs, name=None):
    outs = [run_op('atleast_1d', jnp.atleast_1d, ensure_tensor(t)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [run_op('atleast_2d', jnp.atleast_2d, ensure_tensor(t)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [run_op('atleast_3d', jnp.atleast_3d, ensure_tensor(t)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """Recompute index ids for a sharded embedding table (reference
    operators/shard_index_op.cc): ids owned by shard_id map to a local
    index, all others become ignore_value."""
    x = ensure_tensor(input)
    if not (0 <= shard_id < nshards):
        raise ValueError('shard_id %d out of range [0, %d)'
                         % (shard_id, nshards))
    shard_size = (index_num + nshards - 1) // nshards

    def fn(a):
        lo = shard_id * shard_size
        hi = lo + shard_size
        # ids outside [0, index_num) are invalid (the reference op
        # enforces this); map them to ignore_value instead of silently
        # aliasing a valid local row
        in_shard = (a >= lo) & (a < hi) & (a >= 0) & (a < index_num)
        return jnp.where(in_shard, a - lo, ignore_value)
    return run_op('shard_index', fn, x)


# reference-parity inplace variants: functional purity on TPU means the
# trailing-underscore forms rebind the input Tensor's storage to the new
# value and return it (observable effect matches the reference's
# view-mutating semantics for the common x = op_(x) pattern)
def _inplace(op):
    def wrapped(x, *args, **kwargs):
        out = op(x, *args, **kwargs)
        if hasattr(x, '_data'):
            x._data = out._data
            x._grad_node = out._grad_node
            x._node_out_idx = getattr(out, '_node_out_idx', None)
            return x
        return out
    wrapped.__name__ = op.__name__ + '_'
    return wrapped


reshape_ = _inplace(reshape)
squeeze_ = _inplace(squeeze)
unsqueeze_ = _inplace(unsqueeze)
scatter_ = _inplace(scatter)
