"""Statistics ops (reference: python/paddle/tensor/stat.py)."""
import jax.numpy as jnp

from ..framework.core import run_op, wrap_out
from ._helpers import ensure_tensor, axes_arg
from .math import mean, sum

__all__ = ['mean', 'std', 'var', 'median', 'nanmedian', 'quantile',
           'nanquantile', 'numel']

from .creation import numel


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = axes_arg(axis)
    return run_op('var', lambda a: jnp.var(a, axis=ax, ddof=1 if unbiased else 0,
                                           keepdims=keepdim), x)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = axes_arg(axis)
    return run_op('std', lambda a: jnp.std(a, axis=ax, ddof=1 if unbiased else 0,
                                           keepdims=keepdim), x)


def median(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = axes_arg(axis)
    return run_op('median', lambda a: jnp.median(a, axis=ax, keepdims=keepdim), x)


def nanmedian(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = axes_arg(axis)
    return run_op('nanmedian', lambda a: jnp.nanmedian(a, axis=ax, keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = axes_arg(axis)
    qq = jnp.asarray(q)
    return run_op('quantile', lambda a: jnp.quantile(a, qq, axis=ax,
                                                     keepdims=keepdim), x)


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = axes_arg(axis)
    qq = jnp.asarray(q)
    return run_op('nanquantile', lambda a: jnp.nanquantile(a, qq, axis=ax,
                                                           keepdims=keepdim), x)
