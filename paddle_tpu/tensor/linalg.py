"""Linear algebra ops (reference: python/paddle/tensor/linalg.py)."""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, run_op, wrap_out
from ._helpers import ensure_tensor, axes_arg
from .math import matmul, dot, mm, bmm, mv, addmm

__all__ = [
    'matmul', 'dot', 'mm', 'bmm', 'mv', 'addmm', 'norm', 'dist', 'cond',
    'cholesky', 'inv', 'det', 'slogdet', 'svd', 'qr', 'eig', 'eigh',
    'eigvals', 'eigvalsh', 'solve', 'triangular_solve', 'cholesky_solve',
    'lstsq', 'matrix_power', 'matrix_rank', 'pinv', 'cross', 'multi_dot',
    'histogram', 'bincount', 'corrcoef', 'cov', 'lu',
    'inverse', 't',
]


def norm(x, p='fro', axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = axes_arg(axis)

    def fn(a):
        if p == 'fro' and ax is None:
            return jnp.sqrt(jnp.sum(jnp.square(a)))
        if p == 'fro':
            return jnp.linalg.norm(a, ord='fro' if isinstance(ax, tuple) else None,
                                   axis=ax, keepdims=keepdim)
        if p in (float('inf'), 'inf'):
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p in (float('-inf'), '-inf'):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=ax, keepdims=keepdim),
                         1.0 / p)
    return run_op('norm', fn, x)


def dist(x, y, p=2, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)

    def fn(a, b):
        d = jnp.abs(a - b)
        if p == 0:
            return jnp.sum((d != 0).astype(a.dtype))
        if p == float('inf'):
            return jnp.max(d)
        if p == float('-inf'):
            return jnp.min(d)
        return jnp.power(jnp.sum(jnp.power(d, p)), 1.0 / p)
    return run_op('dist', fn, x, y)


def cond(x, p=None, name=None):
    return run_op('cond', lambda a: jnp.linalg.cond(a, p=p), ensure_tensor(x))


def cholesky(x, upper=False, name=None):
    def fn(a):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2).conj() if upper else l
    return run_op('cholesky', fn, ensure_tensor(x))


def inv(x, name=None):
    return run_op('inv', jnp.linalg.inv, ensure_tensor(x))


def det(x, name=None):
    return run_op('det', jnp.linalg.det, ensure_tensor(x))


def slogdet(x, name=None):
    x = ensure_tensor(x)
    outs = run_op('slogdet', lambda a: tuple(jnp.linalg.slogdet(a)), x)
    return run_op('stack_slogdet', lambda s, l: jnp.stack([s, l]), outs[0], outs[1])


def svd(x, full_matrices=False, name=None):
    x = ensure_tensor(x)
    return run_op('svd',
                  lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)), x)


def qr(x, mode='reduced', name=None):
    x = ensure_tensor(x)
    if mode == 'r':
        return run_op('qr_r', lambda a: jnp.linalg.qr(a, mode='r'), x)
    return run_op('qr', lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x)


def eig(x, name=None):
    x = ensure_tensor(x)
    import numpy as np
    w, v = np.linalg.eig(x.numpy())
    return wrap_out(jnp.asarray(w)), wrap_out(jnp.asarray(v))


def eigh(x, UPLO='L', name=None):
    x = ensure_tensor(x)
    return run_op('eigh', lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), x)


def eigvals(x, name=None):
    import numpy as np
    return wrap_out(jnp.asarray(np.linalg.eigvals(ensure_tensor(x).numpy())))


def eigvalsh(x, UPLO='L', name=None):
    return run_op('eigvalsh', lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO),
                  ensure_tensor(x))


def solve(x, y, name=None):
    return run_op('solve', jnp.linalg.solve, ensure_tensor(x), ensure_tensor(y))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return run_op('triangular_solve', fn, ensure_tensor(x), ensure_tensor(y))


def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, l):
        return jax.scipy.linalg.cho_solve((l, not upper), b)
    return run_op('cholesky_solve', fn, ensure_tensor(x), ensure_tensor(y))


def lstsq(x, y, rcond=None, driver=None, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    sol, res, rank, sv = jnp.linalg.lstsq(x._data, y._data, rcond=rcond)
    return (wrap_out(sol), wrap_out(res), wrap_out(rank), wrap_out(sv))


def matrix_power(x, n, name=None):
    return run_op('matrix_power', lambda a: jnp.linalg.matrix_power(a, n),
                  ensure_tensor(x))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    x = ensure_tensor(x)
    t = tol._data if isinstance(tol, Tensor) else tol
    if hermitian:
        # rank from |eigvalsh| (reference uses syevd for hermitian=True)
        w = jnp.abs(jnp.linalg.eigvalsh(x._data))
        if t is None:
            t = w.max(-1, keepdims=True) * \
                max(x.shape[-2], x.shape[-1]) * jnp.finfo(x._data.dtype).eps
        else:
            t = jnp.asarray(t)
            t = t[..., None] if t.ndim else t
        return wrap_out(jnp.sum(w > t, axis=-1))
    return wrap_out(jnp.linalg.matrix_rank(x._data, tol=t))


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return run_op('pinv', lambda a: jnp.linalg.pinv(a, rcond=rcond,
                                                    hermitian=hermitian),
                  ensure_tensor(x))


def cross(x, y, axis=9, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    ax = axis
    if ax == 9:  # paddle default: first axis of size 3
        ax = next(i for i, s in enumerate(x.shape) if s == 3)
    return run_op('cross', lambda a, b: jnp.cross(a, b, axis=ax), x, y)


def multi_dot(x, name=None):
    ts = [ensure_tensor(t) for t in x]
    return run_op('multi_dot', lambda *xs: jnp.linalg.multi_dot(xs), *ts)


def histogram(input, bins=100, min=0, max=0, name=None):
    a = ensure_tensor(input)._data
    lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
    h, _ = jnp.histogram(a, bins=bins, range=(lo, hi))
    return wrap_out(h.astype(jnp.int64))


def bincount(x, weights=None, minlength=0, name=None):
    a = ensure_tensor(x)._data
    w = ensure_tensor(weights)._data if weights is not None else None
    n = max(int(a.max()) + 1 if a.size else 0, minlength)
    return wrap_out(jnp.bincount(a, weights=w, length=n))


def corrcoef(x, rowvar=True, name=None):
    return run_op('corrcoef', lambda a: jnp.corrcoef(a, rowvar=rowvar),
                  ensure_tensor(x))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = ensure_tensor(fweights)._data if fweights is not None else None
    aw = ensure_tensor(aweights)._data if aweights is not None else None
    return run_op('cov', lambda a: jnp.cov(a, rowvar=rowvar,
                                           ddof=1 if ddof else 0,
                                           fweights=fw, aweights=aw),
                  ensure_tensor(x))


def lu(x, pivot=True, get_infos=False, name=None):
    if not pivot:
        raise NotImplementedError(
            'lu(pivot=False): XLA exposes partial-pivoting LU only')
    x = ensure_tensor(x)
    lu_, piv = jax.scipy.linalg.lu_factor(x._data)
    outs = (wrap_out(lu_), wrap_out(piv.astype(jnp.int32) + 1))
    if get_infos:
        return outs + (wrap_out(jnp.zeros((), jnp.int32)),)
    return outs


def inverse(x, name=None):
    """Alias of inv (reference paddle.inverse)."""
    return inv(x, name=name)


def t(input, name=None):
    """Transpose a 0/1/2-D tensor (reference paddle.t)."""
    x = ensure_tensor(input)
    if x.ndim > 2:
        raise ValueError('paddle.t only supports ndim <= 2, got %d'
                         % x.ndim)
    if x.ndim < 2:
        return run_op('t', lambda a: a, x)
    return run_op('t', lambda a: a.T, x)
