"""Attribute ops (reference: python/paddle/tensor/attribute.py)."""
import jax.numpy as jnp

from ..framework.core import Tensor, wrap_out, run_op
from ._helpers import ensure_tensor

__all__ = ['shape', 'rank', 'is_floating_point', 'is_integer', 'is_complex',
           'real', 'imag']


def shape(input):
    return wrap_out(jnp.asarray(ensure_tensor(input).shape, dtype=jnp.int32))


def rank(input):
    return wrap_out(jnp.asarray(ensure_tensor(input).ndim, dtype=jnp.int32))


def is_floating_point(x):
    return jnp.issubdtype(ensure_tensor(x)._data.dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(ensure_tensor(x)._data.dtype, jnp.integer)


def is_complex(x):
    return jnp.issubdtype(ensure_tensor(x)._data.dtype, jnp.complexfloating)


def real(x, name=None):
    return run_op('real', jnp.real, ensure_tensor(x))


def imag(x, name=None):
    return run_op('imag', jnp.imag, ensure_tensor(x))
