"""Inplace-op function forms + LoDTensorArray ops (reference
tensor/__init__.py exports add_ / ceil_ / ... and the fluid
array_read/array_write family).

Inplace here means paddle's API contract — x is updated and returned —
implemented as out-of-place compute plus handle rebind (XLA arrays are
immutable; the tape linkage moves with the result so autograd agrees
with the reference's inplace grads).
"""
from ..framework.core import Tensor

__all__ = ['add_', 'subtract_', 'clip_', 'ceil_', 'exp_', 'floor_',
           'reciprocal_', 'round_', 'rsqrt_', 'scale_', 'sqrt_',
           'flatten_', 'create_array', 'array_write', 'array_read',
           'array_length']


def _make(op_name):
    def fn(x, *args, **kwargs):
        from . import math as M
        from . import manipulation as MA
        mod = M if hasattr(M, op_name) else MA
        if not x.stop_gradient and x._grad_node is None:
            # paddle parity: inplace on a grad-requiring LEAF is an error
            # (its pre-op value would be unrecoverable for backward)
            raise RuntimeError(
                'a leaf Tensor that requires grad is being used in an '
                'in-place operation (%s_)' % op_name)
        # record the op against a detached alias carrying x's history, so
        # rebinding x to the result cannot create a tape cycle; any other
        # argument that IS x aliases to the same src for the same reason
        src = Tensor(x._data, stop_gradient=x.stop_gradient)
        src._grad_node = x._grad_node
        src._node_out_idx = x._node_out_idx
        args = tuple(src if a is x else a for a in args)
        kwargs = {k: (src if v is x else v) for k, v in kwargs.items()}
        res = getattr(mod, op_name)(src, *args, **kwargs)
        x._data = res._data
        x._grad_node = res._grad_node
        x._node_out_idx = res._node_out_idx
        x.stop_gradient = res.stop_gradient
        return x
    fn.__name__ = op_name + '_'
    fn.__doc__ = 'Inplace form of paddle.%s (updates and returns x).' % op_name
    return fn


add_ = _make('add')
subtract_ = _make('subtract')
clip_ = _make('clip')
ceil_ = _make('ceil')
exp_ = _make('exp')
floor_ = _make('floor')
reciprocal_ = _make('reciprocal')
round_ = _make('round')
rsqrt_ = _make('rsqrt')
scale_ = _make('scale')
sqrt_ = _make('sqrt')
flatten_ = _make('flatten')


# -- LoDTensorArray ops (reference fluid/layers/tensor.py) -------------------
# TPU-native stance: the dynamic array is a host-side python list (static
# control flow uses lax.scan instead); these exist for ported fluid code.

def create_array(dtype='float32', initialized_list=None):
    arr = list(initialized_list or [])
    return arr


def array_write(x, i, array=None):
    i = int(i.numpy()) if isinstance(i, Tensor) else int(i)
    if array is None:
        array = create_array()
    while len(array) <= i:
        array.append(None)
    array[i] = x if isinstance(x, Tensor) else Tensor(x)
    return array


def array_read(array, i):
    i = int(i.numpy()) if isinstance(i, Tensor) else int(i)
    return array[i]


def array_length(array):
    import numpy as np
    return Tensor(np.asarray(len(array), np.int64))
