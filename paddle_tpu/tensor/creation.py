"""Tensor creation API (reference: python/paddle/tensor/creation.py)."""
import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor, run_op, to_tensor, wrap_out
from ..framework import dtype as dtype_mod
from ._helpers import ensure_tensor, jdt, shape_arg

__all__ = [
    'to_tensor', 'zeros', 'ones', 'full', 'zeros_like', 'ones_like',
    'full_like', 'arange', 'linspace', 'logspace', 'eye', 'empty',
    'empty_like', 'meshgrid', 'diag', 'diagflat', 'tril', 'triu', 'assign',
    'clone', 'numel', 'tril_indices', 'triu_indices', 'complex', 'as_tensor',
]


def _default(dtype):
    return jdt(dtype) if dtype else jdt(dtype_mod.get_default_dtype())


def zeros(shape, dtype=None, name=None):
    return wrap_out(jnp.zeros(shape_arg(shape), _default(dtype)))


def ones(shape, dtype=None, name=None):
    return wrap_out(jnp.ones(shape_arg(shape), _default(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return wrap_out(jnp.full(shape_arg(shape), fill_value, _default(dtype)))


def zeros_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    return wrap_out(jnp.zeros_like(x._data, dtype=jdt(dtype) if dtype else None))


def ones_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    return wrap_out(jnp.ones_like(x._data, dtype=jdt(dtype) if dtype else None))


def full_like(x, fill_value, dtype=None, name=None):
    x = ensure_tensor(x)
    return wrap_out(jnp.full_like(x._data, fill_value, dtype=jdt(dtype) if dtype else None))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype=dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype=dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = 'int64' if all(
            isinstance(v, (int, np.integer)) for v in (start, end, step)) else \
            dtype_mod.get_default_dtype()
    return wrap_out(jnp.arange(start, end, step, dtype=jdt(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v
    return wrap_out(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                                 dtype=_default(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v
    return wrap_out(jnp.logspace(_v(start), _v(stop), int(_v(num)),
                                 base=_v(base), dtype=_default(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return wrap_out(jnp.eye(int(num_rows),
                            int(num_columns) if num_columns is not None else None,
                            dtype=_default(dtype)))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    tensors = [ensure_tensor(a) for a in args]
    outs = run_op('meshgrid', lambda *xs: tuple(jnp.meshgrid(*xs, indexing='ij')),
                  *tensors)
    return list(outs) if isinstance(outs, tuple) else [outs]


def diag(x, offset=0, padding_value=0, name=None):
    x = ensure_tensor(x)
    if padding_value == 0 or x.ndim == 2:
        return run_op('diag', lambda a: jnp.diag(a, k=offset), x)

    def fn(a):
        d = jnp.diag(a, k=offset)
        mask = jnp.eye(d.shape[0], dtype=bool) if False else None
        n = a.shape[0] + abs(offset)
        out = jnp.full((n, n), padding_value, a.dtype)
        idx = jnp.arange(a.shape[0])
        return out.at[idx, idx + offset].set(a) if offset >= 0 else \
            out.at[idx - offset, idx].set(a)
    return run_op('diag', fn, x)


def diagflat(x, offset=0, name=None):
    x = ensure_tensor(x)
    return run_op('diagflat', lambda a: jnp.diagflat(a, k=offset), x)


def tril(x, diagonal=0, name=None):
    return run_op('tril', lambda a: jnp.tril(a, k=diagonal), ensure_tensor(x))


def triu(x, diagonal=0, name=None):
    return run_op('triu', lambda a: jnp.triu(a, k=diagonal), ensure_tensor(x))


def tril_indices(row, col=None, offset=0, dtype='int64'):
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return wrap_out(jnp.stack([r, c]).astype(jdt(dtype)))


def triu_indices(row, col=None, offset=0, dtype='int64'):
    r, c = jnp.triu_indices(row, k=offset, m=col)
    return wrap_out(jnp.stack([r, c]).astype(jdt(dtype)))


def assign(x, output=None):
    x = ensure_tensor(x) if not isinstance(x, (list, tuple, np.ndarray, float, int)) \
        else Tensor(np.asarray(x))
    out = run_op('assign', lambda a: a + 0, x)
    if output is not None:
        output._data = out._data
        output._grad_node = out._grad_node
        output._node_out_idx = out._node_out_idx
        output.stop_gradient = out.stop_gradient
        return output
    return out


def clone(x, name=None):
    return assign(x)


def numel(x, name=None):
    return wrap_out(jnp.asarray(ensure_tensor(x).size, dtype=jnp.int64))


def complex(real, imag, name=None):
    return run_op('complex', lambda r, i: jax_lax_complex(r, i),
                  ensure_tensor(real), ensure_tensor(imag))


def jax_lax_complex(r, i):
    import jax.lax as lax
    return lax.complex(r, i)


def as_tensor(data, dtype=None):
    return to_tensor(data, dtype=dtype)
