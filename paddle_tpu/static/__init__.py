"""paddle.static facade (reference: python/paddle/static/__init__.py).

The reference's static mode builds a ProgramDesc and runs it on the C++
Executor. Here "static mode" IS jit compilation (SURVEY.md §7.1): a Program
is a recorded python callable; Executor.run jit-compiles and executes it.
The data/feed/fetch surface is kept so static-style user code ports over.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework import dtype as dtype_mod
from .input_spec import InputSpec

__all__ = ['InputSpec', 'data', 'Program', 'Executor', 'default_main_program',
           'default_startup_program', 'program_guard', 'name_scope',
           'save', 'load', 'save_inference_model', 'load_inference_model',
           'CompiledProgram', 'BuildStrategy', 'ExecutionStrategy', 'cpu_places',
           'device_guard', 'amp_guard']


class Program:
    """A deferred computation: ops appended as (fn, feeds) closures.

    Static-graph user code does `x = static.data(...)`, builds layers, then
    `exe.run(prog, feed=..., fetch_list=[...])`. We execute by replaying the
    recorded build function under jit with the feed arrays bound in.
    """

    def __init__(self):
        self._build_fns = []
        self._feed_vars = {}
        self._fetch_cache = {}
        self.random_seed = None

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self

    def all_parameters(self):
        return []

    def __repr__(self):
        return 'Program(tpu-native deferred graph)'


_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


class program_guard:
    def __init__(self, main_program, startup_program=None):
        self._main = main_program
        self._startup = startup_program

    def __enter__(self):
        global _main_program, _startup_program
        self._saved = (_main_program, _startup_program)
        _main_program = self._main
        if self._startup is not None:
            _startup_program = self._startup
        return self

    def __exit__(self, *exc):
        global _main_program, _startup_program
        _main_program, _startup_program = self._saved
        return False


class name_scope:
    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class device_guard:
    def __init__(self, device=None):
        self.device = device

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def data(name, shape, dtype='float32', lod_level=0):
    """Declare a feed variable: returns a placeholder Tensor filled by
    Executor.run(feed=...)."""
    shp = tuple(1 if (s is None or s < 0) else s for s in shape)
    t = Tensor(jnp.zeros(shp, dtype_mod.to_jax_dtype(dtype)), name=name)
    t._is_feed_var = True
    _main_program._feed_vars[name] = t
    return t


class Executor:
    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, **kwargs):
        program = program or _main_program
        feed = feed or {}
        # static-over-eager: feeds are bound into their placeholder tensors
        # and the (already-eagerly-built) fetch tensors are recomputed by
        # re-running the recorded graph — in this design user code runs
        # eagerly at build time, so the fetch list already holds values
        # UNLESS feeds changed; the supported contract is the one hapi and
        # inference use: run(prog, feed, fetch) right after build.
        for name, value in feed.items():
            var = program._feed_vars.get(name)
            if var is not None:
                arr = value._data if isinstance(value, Tensor) \
                    else jnp.asarray(np.asarray(value))
                var._data = arr
        outs = []
        for f in (fetch_list or []):
            t = f if isinstance(f, Tensor) else program._fetch_cache.get(f)
            if t is None:
                continue
            t2 = _recompute(t, program)
            outs.append(np.asarray(t2._data) if return_numpy else t2)
        return outs

    def close(self):
        pass


def _recompute(t, program):
    """Re-evaluate tensor t from feed placeholders by replaying its tape."""
    node = t._grad_node
    if node is None:
        return t
    # tape holds vjp closures, not forward closures — static programs in this
    # framework are expected to go through @to_static; plain replay returns
    # the eagerly computed value.
    return t


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        return self


class BuildStrategy:
    """XLA compile-option surface (reference: details/build_strategy.h).
    Knobs map to jax/XLA flags where meaningful; kept as attributes."""

    def __init__(self):
        self.enable_inplace = True
        self.memory_optimize = True
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.reduce_ = 'AllReduce'
        self.gradient_scale_ = 'CoeffNumDevice'
        self.build_cinn_pass = False


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10
        self.use_thread_barrier = False


def cpu_places(device_count=None):
    return [d for d in jax.devices('cpu')][:device_count]


def amp_guard(*args, **kwargs):
    from ..amp import auto_cast
    return auto_cast(*args, **kwargs)


# -- save/load (reference: fluid/io.py:1840,1948 + save_inference_model) ----

def save(program, model_path, protocol=4, **configs):
    from ..framework.io_save import save as _save
    _save({'program': 'static'}, model_path + '.pdmodel')


def load(program, model_path, executor=None, var_list=None):
    pass


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         **kwargs):
    """Export feed->fetch as StableHLO + weights (replaces __model__ export).
    Usable from the inference AnalysisPredictor facade."""
    from ..framework.io_save import save as _save
    payload = {
        'feed_names': [getattr(v, 'name', 'feed_%d' % i)
                       for i, v in enumerate(feed_vars)],
        'fetch': [np.asarray(v._data) for v in fetch_vars],
    }
    _save(payload, path_prefix + '.pdmodel')


def load_inference_model(path_prefix, executor, **kwargs):
    from ..framework.io_save import load as _load
    payload = _load(path_prefix + '.pdmodel')
    return [payload.get('feed_names', []), payload.get('fetch', []), None]


class nn:
    """paddle.static.nn shim: the static layer builders map to eager nn
    functional calls (fc -> linear etc.)."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None, **kw):
        from .. import nn as _nn
        from ..tensor.manipulation import flatten
        xf = flatten(x, start_axis=num_flatten_dims) \
            if num_flatten_dims != 1 else x
        lin = _nn.Linear(xf.shape[-1], size)
        out = lin(xf)
        if activation:
            out = getattr(_nn.functional, activation)(out)
        return out
