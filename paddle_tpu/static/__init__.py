"""paddle.static facade (reference: python/paddle/static/__init__.py).

The reference's static mode builds a ProgramDesc and runs it on the C++
Executor. Here "static mode" IS jit compilation (SURVEY.md §7.1): a Program
is a recorded python callable; Executor.run jit-compiles and executes it.
The data/feed/fetch surface is kept so static-style user code ports over.
"""
import os

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework import dtype as dtype_mod
from .input_spec import InputSpec

__all__ = ['InputSpec', 'data', 'Program', 'Executor', 'default_main_program',
           'default_startup_program', 'program_guard', 'name_scope',
           'save', 'load', 'save_inference_model', 'load_inference_model',
           'accuracy', 'auc', 'Variable', 'Scope', 'global_scope', 'scope_guard',
           'create_global_var', 'create_parameter', 'append_backward',
           'gradients', 'Print', 'py_func', 'cuda_places', 'xpu_places',
           'WeightNormParamAttr', 'ParallelExecutor', 'serialize_program',
           'deserialize_program', 'serialize_persistables',
           'deserialize_persistables', 'save_to_file', 'load_from_file',
           'save_vars', 'load_vars', 'load_program_state',
           'set_program_state', 'normalize_program',
           'CompiledProgram', 'BuildStrategy', 'ExecutionStrategy', 'cpu_places',
           'device_guard', 'amp_guard']


class Program:
    """A recorded computation (the reference's ProgramDesc without the
    protobuf IR — SURVEY.md §7.1: "Program" = recorded ops + feed specs).

    Static-graph user code does `x = static.data(...)` inside a
    `program_guard`, builds layers (which execute eagerly AND record into
    the program via the core._fwd_recorder hook), then
    `exe.run(prog, feed=..., fetch_list=[...])` — which REPLAYS the
    recorded ops from the new feed values (jit-compiled per feed
    signature), so feeding fresh data returns fresh fetches.
    """

    def __init__(self):
        self._ops = []          # [(fn, [in Tensor], [out Tensor])]
        self._feed_vars = {}
        self._fetch_cache = {}
        self._replay_cache = {}
        self.random_seed = None

    def _record(self, fn, ins, outs):
        self._ops.append((fn, list(ins), list(outs)))
        self._replay_cache.clear()

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self

    def all_parameters(self):
        return []

    def __repr__(self):
        return 'Program(tpu-native deferred graph)'


_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


class program_guard:
    """Scope that routes static.data() AND op recording to `main_program`
    (reference: fluid/framework.py program_guard). Every op executed in
    the scope is appended to the program, making Executor.run replay
    possible."""

    def __init__(self, main_program, startup_program=None):
        self._main = main_program
        self._startup = startup_program

    def __enter__(self):
        global _main_program, _startup_program
        from ..framework import core as core_mod
        self._saved = (_main_program, _startup_program)
        self._saved_rec = core_mod._fwd_recorder[0]
        _main_program = self._main
        if self._startup is not None:
            _startup_program = self._startup
        core_mod._fwd_recorder[0] = self._main._record
        return self

    def __exit__(self, *exc):
        global _main_program, _startup_program
        from ..framework import core as core_mod
        _main_program, _startup_program = self._saved
        core_mod._fwd_recorder[0] = self._saved_rec
        return False


class name_scope:
    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class device_guard:
    def __init__(self, device=None):
        self.device = device

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def data(name, shape, dtype='float32', lod_level=0):
    """Declare a feed variable: returns a placeholder Tensor filled by
    Executor.run(feed=...)."""
    shp = tuple(1 if (s is None or s < 0) else s for s in shape)
    t = Tensor(jnp.zeros(shp, dtype_mod.to_jax_dtype(dtype)), name=name)
    _main_program._feed_vars[name] = t
    return t


class Executor:
    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, **kwargs):
        program = program or _main_program
        feed = feed or {}
        if isinstance(program, LoadedProgram):
            outs = program(feed)
            if fetch_list:
                outs = [outs[i] for i in fetch_list]
            return [np.asarray(a) if return_numpy else Tensor(a)
                    for a in outs]
        feed_arrays = {}
        for name, value in feed.items():
            var = program._feed_vars.get(name)
            if var is None:
                raise KeyError(
                    'feed name %r is not a declared feed var of this '
                    'Program (declared: %s)'
                    % (name, sorted(program._feed_vars)))
            arr = value._data if isinstance(value, Tensor) \
                else jnp.asarray(np.asarray(value))
            feed_arrays[name] = arr
            var._data = arr
        fetches = []
        for f in (fetch_list or []):
            t = f if isinstance(f, Tensor) else program._fetch_cache.get(f)
            if t is None:
                raise KeyError('fetch target %r is neither a Tensor nor a '
                               'registered fetch name' % (f,))
            fetches.append(t)
        if feed_arrays and program._ops:
            out_arrays = _replay(program, feed_arrays, fetches)
        elif feed_arrays:
            raise RuntimeError(
                'Executor.run got feeds but this Program recorded no ops — '
                'build the graph inside `with static.program_guard(program):`'
                ' so run() can replay it with fresh feed values (feeding a '
                'never-recorded program would silently return stale '
                'build-time values)')
        else:
            out_arrays = [t._data for t in fetches]
        outs = [np.asarray(a) if return_numpy else Tensor(a)
                for a in out_arrays]
        return outs

    def close(self):
        pass


def _replay(program, feed_arrays, fetches):
    """Re-evaluate the fetch tensors from fresh feed values by replaying
    the program's recorded ops (jitted per feed signature — the
    ProgramDesc→Executor contract; reference naive_executor.cc:38 flat
    op loop, here one fused XLA program)."""
    feed_names = sorted(feed_arrays)
    sig = (tuple((name, tuple(np.shape(feed_arrays[name])),
                  str(jnp.asarray(feed_arrays[name]).dtype))
                 for name in feed_names),
           tuple(id(t) for t in fetches))
    compiled = program._replay_cache.get(sig)
    if compiled is None:
        ops = list(program._ops)
        feed_ids = {id(program._feed_vars[n]): i
                    for i, n in enumerate(feed_names)}
        fetch_ids = [id(t) for t in fetches]

        def replay(feed_list):
            env = {}
            for tid, i in feed_ids.items():
                env[tid] = feed_list[i]
            for fn, ins, outs in ops:
                in_arrays = [env.get(id(t), t._data) for t in ins]
                res = fn(*in_arrays)
                res = res if isinstance(res, tuple) else (res,)
                for t, a in zip(outs, res):
                    env[id(t)] = a
            return [env.get(tid) for tid in fetch_ids]

        missing = [tid for tid in sig[1]
                   if not any(tid in (id(o) for o in outs)
                              for _, _, outs in ops)
                   and tid not in {id(v) for v in
                                   program._feed_vars.values()}]
        if missing:
            raise RuntimeError(
                'fetch target(s) were not produced by any recorded op of '
                'this Program — fetch tensors must be built inside the '
                'program_guard scope')
        compiled = jax.jit(replay)
        program._replay_cache[sig] = compiled
    return compiled([jnp.asarray(feed_arrays[n]) for n in feed_names])


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        return self


class BuildStrategy:
    """XLA compile-option surface (reference: details/build_strategy.h).
    Knobs map to jax/XLA flags where meaningful; kept as attributes."""

    def __init__(self):
        self.enable_inplace = True
        self.memory_optimize = True
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.reduce_ = 'AllReduce'
        self.gradient_scale_ = 'CoeffNumDevice'
        self.build_cinn_pass = False


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10
        self.use_thread_barrier = False


def cpu_places(device_count=None):
    return [d for d in jax.devices('cpu')][:device_count]


def accuracy(input, label, k=1, correct=None, total=None):
    """paddle.static.accuracy parity (operators/metrics/accuracy_op.cc)."""
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k, correct=correct, total=total)


def auc(input, label, curve='ROC', num_thresholds=4095, topk=1,
        slide_steps=1):
    """paddle.static.auc parity (operators/metrics/auc_op.cc)."""
    from ..metric import auc as _auc
    out = _auc(input, label, curve=curve, num_thresholds=num_thresholds)
    return out, out, []


def amp_guard(*args, **kwargs):
    from ..amp import auto_cast
    return auto_cast(*args, **kwargs)


# -- save/load (reference: fluid/io.py:1840,1948 + save_inference_model) ----

def save(program, model_path, protocol=4, **configs):
    from ..framework.io_save import save as _save
    _save({'program': 'static'}, model_path + '.pdmodel')


def load(program, model_path, executor=None, var_list=None):
    pass


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None, **kwargs):
    """Export feed->fetch as a serialized XLA program + metadata
    (reference: fluid/io.py save_inference_model writing __model__+params;
    here the artifact is a jax.export blob — weights are baked in as
    constants, which IS the pruned inference graph).

    The program must have been built inside a program_guard (recorded
    ops), same requirement as Executor.run replay."""
    from ..framework.io_save import save as _save
    from jax import export as jax_export
    program = program or _main_program
    if not program._ops:
        raise RuntimeError(
            'save_inference_model needs a recorded Program — build the '
            'graph inside `with static.program_guard(program):`')
    feed_names = [getattr(v, 'name', None) or 'feed_%d' % i
                  for i, v in enumerate(feed_vars)]
    name_of = {id(v): n for v, n in zip(feed_vars, feed_names)}
    feed_arrays = {name_of[id(v)]: v._data for v in feed_vars}
    ordered = sorted(feed_arrays)
    ops = list(program._ops)
    feed_ids = {id(v): ordered.index(name_of[id(v)]) for v in feed_vars}
    fetch_ids = [id(t) for t in fetch_vars]

    def replay(feed_list):
        env = {tid: feed_list[i] for tid, i in feed_ids.items()}
        for fn, ins, outs in ops:
            in_arrays = [env.get(id(t), t._data) for t in ins]
            res = fn(*in_arrays)
            res = res if isinstance(res, tuple) else (res,)
            for t, a in zip(outs, res):
                env[id(t)] = a
        return [env[tid] for tid in fetch_ids]

    shaped = [jax.ShapeDtypeStruct(feed_arrays[n].shape,
                                   feed_arrays[n].dtype) for n in ordered]
    exported = jax_export.export(jax.jit(replay))(shaped)
    _save({'feed_names': ordered,
           'exported': bytes(exported.serialize()),
           'n_fetch': len(fetch_vars)}, path_prefix + '.pdmodel')


class LoadedProgram:
    """What load_inference_model returns as `program`: a deserialized XLA
    program Executor.run can execute with fresh feeds."""

    def __init__(self, feed_names, exported_blob, n_fetch):
        from jax import export as jax_export
        self.feed_names = list(feed_names)
        self._exported = jax_export.deserialize(bytearray(exported_blob))
        self.n_fetch = n_fetch

    def __call__(self, feed):
        args = [jnp.asarray(np.asarray(feed[n])) for n in self.feed_names]
        return self._exported.call(args)


class FluidLoadedProgram(LoadedProgram):
    """A reference-produced inference model (__model__ ProgramDesc +
    LoDTensor params, inference/fluid_program.py) served through the same
    Executor.run contract as our own artifacts — the fluid
    load_inference_model + executor path of the reference book tests
    (fluid/io.py load_inference_model; analysis_predictor.cc:201)."""

    def __init__(self, fluid_prog):
        self.feed_names = list(fluid_prog.feed_names)
        self.n_fetch = len(fluid_prog.fetch_names)
        self._fluid = fluid_prog

    def __call__(self, feed):
        return self._fluid.run(feed)


def _fluid_artifact_candidate(path_prefix, model_filename=None):
    """Path of a reference-format ProgramDesc under `path_prefix`, or
    None when path_prefix is one of our own artifact prefixes."""
    if os.path.isdir(path_prefix):
        if model_filename:
            return os.path.join(path_prefix, model_filename)
        if os.path.exists(os.path.join(path_prefix, '__model__')):
            return path_prefix
        if any(f.endswith('.pdmodel') for f in os.listdir(path_prefix)):
            return path_prefix
        return None
    if path_prefix.endswith('.pdmodel') or \
            os.path.basename(path_prefix) == '__model__':
        return path_prefix
    return None


def load_inference_model(path_prefix, executor, **kwargs):
    """Returns [program, feed_target_names, fetch_targets] (paddle order);
    run via exe.run(program, feed={...}, fetch_list=fetch_targets).

    Accepts BOTH our own save_inference_model artifacts (path prefix) and
    reference-produced model directories (__model__ / *.pdmodel +
    LoDTensor params; pass model_filename/params_filename for combined
    layouts, as in the reference API)."""
    model_filename = kwargs.get('model_filename')
    params_filename = kwargs.get('params_filename')
    cand = _fluid_artifact_candidate(path_prefix, model_filename)
    if cand is not None:
        from ..inference.fluid_program import load_fluid_model
        pp = params_filename
        if pp and os.path.isdir(path_prefix):
            pp = os.path.join(path_prefix, pp)
        prog = FluidLoadedProgram(load_fluid_model(cand, pp))
        return [prog, list(prog.feed_names), list(range(prog.n_fetch))]
    from ..framework.io_save import load as _load
    payload = _load(path_prefix + '.pdmodel')
    prog = LoadedProgram(payload['feed_names'], payload['exported'],
                         payload['n_fetch'])
    return [prog, list(prog.feed_names), list(range(prog.n_fetch))]


from . import nn  # noqa: E402,F401
# -- fluid-era static surface (reference: python/paddle/static/__init__.py
# re-exports of fluid Executor-world APIs) ----------------------------------

Variable = Tensor  # the reference's graph Variable ≈ our recorded Tensor


class Scope:
    """Name -> value store (reference framework/scope.h Scope facade)."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        self._vars.setdefault(name, Tensor(jnp.zeros((), jnp.float32),
                                           name=name))
        return self._vars[name]

    def find_var(self, name):
        return self._vars.get(name)

    def local_scope(self):
        return Scope()


_global_scope = Scope()


def global_scope():
    return _global_scope


class scope_guard:
    def __init__(self, scope):
        self._scope = scope

    def __enter__(self):
        global _global_scope
        self._saved = _global_scope
        _global_scope = self._scope
        return self

    def __exit__(self, *exc):
        global _global_scope
        _global_scope = self._saved
        return False


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    t = Tensor(jnp.full(tuple(shape), value,
                        dtype_mod.to_jax_dtype(dtype)), name=name)
    t.persistable = persistable
    if name:
        _global_scope._vars[name] = t
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..framework.core import Parameter
    from ..nn import initializer as init_mod
    init = default_initializer or (init_mod.Constant(0.0) if is_bias
                                   else init_mod.XavierNormal())
    return Parameter(init(list(shape), dtype), name=name)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Reference fluid/backward.py:1369 — computes grads for the loss and
    returns [(param, grad)] pairs. Here the tape IS the backward builder:
    loss.backward() populates .grad on every reachable Parameter."""
    from ..framework.core import Parameter
    # walk the tape BEFORE backward consumes it to find the reachable
    # Parameters, then run backward and pair them with their grads
    params = []
    seen = set()
    node = getattr(loss, '_grad_node', None)
    stack = [node] if node is not None else []
    visited = set()
    while stack:
        nd = stack.pop()
        if id(nd) in visited:
            continue
        visited.add(id(nd))
        for t in nd.inputs:
            if isinstance(t, Parameter) and id(t) not in seen:
                seen.add(id(t))
                params.append(t)
            sub = getattr(t, '_grad_node', None)
            if sub is not None:
                stack.append(sub)
    loss.backward()
    pairs = [(p, p.grad) for p in params if p.grad is not None]
    if parameter_list:
        wanted = {id(p) for p in parameter_list}
        pairs = [pg for pg in pairs if id(pg[0]) in wanted]
    return pairs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference fluid/backward.py:1964 — symbolic d(targets)/d(inputs);
    delegates to autograd.grad."""
    from ..autograd import grad as _grad
    outs = _grad(targets, inputs, grad_outputs=target_gradients,
                 allow_unused=True)
    return list(outs) if isinstance(outs, (list, tuple)) else [outs]


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase='both'):
    """Debug print op (reference operators/print_op): prints through the
    jit boundary via jax.debug.print and passes the value through."""
    from ..framework.core import run_op

    def fn(a):
        jax.debug.print((message or '') + ' {x}', x=a)
        return a
    return run_op('print', fn, input)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference operators/py_func_op: wrap a host python callable as an
    op via pure_callback. `out` provides the result template(s).
    backward_func(*inputs, *output_grads) -> input grads wires the custom
    gradient; without it, gradient-requiring inputs raise (a host
    callback has no automatic derivative)."""
    from ..framework.core import run_op
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = [jax.ShapeDtypeStruct(tuple(o.shape), o._data.dtype)
              for o in outs]
    needs_grad = any(not getattr(t, 'stop_gradient', True) for t in xs)
    if needs_grad and backward_func is None:
        raise ValueError(
            'py_func input requires grad but no backward_func was given — '
            'host callbacks have no automatic derivative (reference '
            'py_func_op needs one too)')

    def call_fwd(*arrays):
        res = jax.pure_callback(
            lambda *a: func(*[np.asarray(v) for v in a]),
            shapes if len(shapes) > 1 else shapes[0], *arrays)
        return tuple(res) if isinstance(res, (list, tuple)) else res

    if backward_func is None:
        return run_op('py_func', call_fwd, *xs)

    in_shapes = [jax.ShapeDtypeStruct(tuple(t.shape), t._data.dtype)
                 for t in xs]

    @jax.custom_vjp
    def fn(*arrays):
        return call_fwd(*arrays)

    def fwd(*arrays):
        return fn(*arrays), arrays

    def bwd(res_arrays, g):
        gs = g if isinstance(g, tuple) else (g,)
        dx = jax.pure_callback(
            lambda *a: backward_func(*[np.asarray(v) for v in a]),
            in_shapes if len(in_shapes) > 1 else in_shapes[0],
            *res_arrays, *gs)
        return tuple(dx) if isinstance(dx, (list, tuple)) else (dx,)

    fn.defvjp(fwd, bwd)
    return run_op('py_func', fn, *xs)


def cuda_places(device_ids=None):
    # accelerator places == the TPU devices here
    devs = [d for d in jax.devices() if d.platform != 'cpu'] or jax.devices()
    if device_ids is not None:
        devs = [devs[i] for i in device_ids]
    return devs


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


class WeightNormParamAttr:
    """Accepted for API parity; weight-norm reparameterization comes from
    nn.utils.weight_norm on the built layer."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


class ParallelExecutor:
    """Legacy multi-device executor facade (reference
    parallel_executor.cc): delegates to Executor — device parallelism is
    pjit's job now."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        self._program = main_program or _main_program
        self._exe = Executor()

    def run(self, fetch_list, feed=None, feed_dict=None,
            return_numpy=True):
        return self._exe.run(self._program, feed=feed or feed_dict,
                             fetch_list=fetch_list,
                             return_numpy=return_numpy)


# -- program/vars (de)serialization (reference static/io.py) ----------------

def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    from jax import export as jax_export
    program = program or _main_program
    import io as _io
    import pickle as _pickle
    buf = _io.BytesIO()
    # reuse the replay exporter from save_inference_model
    names = [getattr(v, 'name', None) or 'feed_%d' % i
             for i, v in enumerate(feed_vars)]
    payload = _export_program_payload(program, feed_vars, fetch_vars, names)
    _pickle.dump(payload, buf, protocol=4)
    return buf.getvalue()


def _export_program_payload(program, feed_vars, fetch_vars, feed_names):
    from jax import export as jax_export
    if not program._ops:
        raise RuntimeError('program recorded no ops — build it inside '
                           'static.program_guard')
    name_of = {id(v): n for v, n in zip(feed_vars, feed_names)}
    feed_arrays = {name_of[id(v)]: v._data for v in feed_vars}
    ordered = sorted(feed_arrays)
    ops = list(program._ops)
    feed_ids = {id(v): ordered.index(name_of[id(v)]) for v in feed_vars}
    fetch_ids = [id(t) for t in fetch_vars]

    def replay(feed_list):
        env = {tid: feed_list[i] for tid, i in feed_ids.items()}
        for fn, ins, outs in ops:
            res = fn(*[env.get(id(t), t._data) for t in ins])
            res = res if isinstance(res, tuple) else (res,)
            for t, a in zip(outs, res):
                env[id(t)] = a
        return [env[tid] for tid in fetch_ids]

    shaped = [jax.ShapeDtypeStruct(feed_arrays[n].shape,
                                   feed_arrays[n].dtype) for n in ordered]
    exported = jax_export.export(jax.jit(replay))(shaped)
    return {'feed_names': ordered,
            'exported': bytes(exported.serialize()),
            'n_fetch': len(fetch_vars)}


def deserialize_program(data):
    import pickle as _pickle
    payload = _pickle.loads(data)
    return LoadedProgram(payload['feed_names'], payload['exported'],
                         payload['n_fetch'])


def _program_parameters(program):
    """Parameters appearing as recorded-op inputs, in discovery order."""
    from ..framework.core import Parameter
    seen, out = set(), []
    for _fn, ins, _outs in program._ops:
        for t in ins:
            if isinstance(t, Parameter) and id(t) not in seen:
                seen.add(id(t))
                out.append(t)
    return out


def serialize_persistables(feed_vars, fetch_vars, program=None, **kwargs):
    """Weights of the program's recorded Parameters (+ global-scope
    vars), keyed by name or discovery index."""
    import pickle as _pickle
    program = program or _main_program
    state = {}
    for i, p in enumerate(_program_parameters(program)):
        state[p.name or 'param_%d' % i] = np.asarray(p._data)
    for n, t in _global_scope._vars.items():
        state.setdefault(n, np.asarray(t._data))
    return _pickle.dumps(state, protocol=4)


def deserialize_persistables(program, data, executor=None):
    import pickle as _pickle
    state = _pickle.loads(data)
    params = _program_parameters(program) if program is not None \
        and getattr(program, '_ops', None) else []
    for i, p in enumerate(params):
        key = p.name or 'param_%d' % i
        if key in state:
            p._data = jnp.asarray(state[key])
    for n, arr in state.items():
        if n in _global_scope._vars:
            _global_scope._vars[n]._data = jnp.asarray(arr)
    return state


def save_to_file(path, content):
    with open(path, 'wb') as f:
        f.write(content)


def load_from_file(path):
    with open(path, 'rb') as f:
        return f.read()


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    from ..framework import io_save
    vars = vars or list(_global_scope._vars.values())
    state = {getattr(v, 'name', 'var_%d' % i) or 'var_%d' % i:
             np.asarray(v._data) for i, v in enumerate(vars)}
    io_save.save(state, os.path.join(dirname, filename or '__vars__'))


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    from ..framework import io_save
    state = io_save.load(os.path.join(dirname, filename or '__vars__'),
                         return_numpy=True)
    if vars:
        by_name = {getattr(v, 'name', None): v for v in vars}
        for n, arr in state.items():
            if n in by_name and by_name[n] is not None:
                by_name[n]._data = jnp.asarray(arr)
    return state


def load_program_state(model_path, var_list=None):
    """numpy-level state surgery (reference io.py:2144)."""
    from ..framework import io_save
    return io_save.load(model_path, return_numpy=True)


def set_program_state(program, state_dict):
    for n, arr in state_dict.items():
        var = _global_scope._vars.get(n)
        if var is not None:
            var._data = jnp.asarray(arr)


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """Prune to the feed->fetch computation (reference normalize_program):
    returns the self-contained LoadedProgram."""
    names = [getattr(v, 'name', None) or 'feed_%d' % i
             for i, v in enumerate(feed_vars)]
    payload = _export_program_payload(program, feed_vars, fetch_vars, names)
    return LoadedProgram(payload['feed_names'], payload['exported'],
                         payload['n_fetch'])

from .. import amp  # noqa: F401,E402 — paddle.static.amp submodule parity
