"""paddle.static.nn (reference: python/paddle/static/nn/__init__.py —
static layer builders + the control-flow ops of
fluid/layers/control_flow.py).

TPU-native control flow: cond/case/switch_case/while_loop ARE
lax.cond/lax.switch/lax.while_loop (SURVEY §7.1 — the reference's
conditional_block/while ops compile to XLA control flow here, no
sub-block machinery). cond and switch_case differentiate through the
tape; while_loop is forward-only (XLA while has no reverse — use
lax.scan-style bounded loops in differentiable paths, same guidance the
reference gives for DynamicRNN).

sequence_* builders are deliberately not ported (SURVEY §7.5: ragged
data rides masks — see nn.functional.sequence_mask); they raise with
that guidance.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax import tree_util as jtu

from ..framework.core import Tensor, Parameter, run_op, no_grad_guard

__all__ = ['fc', 'cond', 'case', 'switch_case', 'while_loop', 'embedding',
           'batch_norm', 'layer_norm', 'instance_norm', 'group_norm',
           'prelu', 'conv2d', 'conv2d_transpose', 'conv3d', 'spectral_norm',
           'create_parameter', 'py_func', 'data_norm', 'nce',
           'conv3d_transpose',
           'sparse_embedding', 'bilinear_tensor_product', 'deform_conv2d']


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _wrap_tree(tree):
    if isinstance(tree, (list, tuple)):
        return type(tree)(_wrap_tree(v) for v in tree)
    return Tensor(tree) if not isinstance(tree, Tensor) else tree


def _unwrap_tree(tree):
    if isinstance(tree, (list, tuple)):
        return type(tree)(_unwrap_tree(v) for v in tree)
    return _unwrap(tree)


# -- control flow ------------------------------------------------------------

def _record_branch(fn):
    """Run a branch builder eagerly while recording its paddle ops
    (core._fwd_recorder — the same hook static.program_guard uses).
    Mirrors the reference: cond BUILDS both sub-blocks
    (conditional_block ops) at construction time."""
    from ..framework import core as core_mod
    rec = []
    prev = core_mod._fwd_recorder[0]
    core_mod._fwd_recorder[0] = \
        lambda f, ins, outs: rec.append((f, list(ins), list(outs)))
    try:
        out = fn()
    finally:
        core_mod._fwd_recorder[0] = prev
    return out, rec


def _branch_leaves(rec):
    """Input Tensors of a recording that no earlier recorded op produced
    — the operands grads must flow to."""
    produced = set()
    leaves, seen = [], set()
    for _f, ins, outs in rec:
        for t in ins:
            if id(t) not in produced and id(t) not in seen:
                seen.add(id(t))
                leaves.append(t)
        produced.update(id(t) for t in outs)
    return leaves


def _replay_rec(rec, result, env):
    """Re-evaluate a branch recording with `env` (id -> array)."""
    for f, ins, outs in rec:
        arrays = [env.get(id(t), t._data) for t in ins]
        res = f(*arrays)
        res = res if isinstance(res, tuple) else (res,)
        for t, a in zip(outs, res):
            env[id(t)] = a

    # Tensors are unregistered pytree leaves, so tree_map substitutes
    # them in-place across any output structure (list/tuple/dict/...)
    return jtu.tree_map(
        lambda t: env.get(id(t), t._data) if isinstance(t, Tensor) else t,
        result)


def _flat_unwrapped(tree):
    """Flatten a branch-output tree (Tensors are leaves) to arrays."""
    return tuple(_unwrap(v) for v in jtu.tree_flatten(tree)[0])


def cond(pred, true_fn=None, false_fn=None, name=None):
    """lax.cond (reference control_flow.py cond / conditional_block op).
    Both branches are built once eagerly (the reference builds both
    sub-blocks too) and replayed inside lax.cond; every leaf Tensor a
    branch reads becomes a tape operand, so grads flow. Branch outputs
    may be a Tensor or any pytree of them; run_op sees a flat tuple and
    the caller gets the original structure back."""
    t_out, t_rec = _record_branch(true_fn)
    f_out, f_rec = _record_branch(false_fn)
    t_leaves, t_def = jtu.tree_flatten(t_out)
    _f_leaves, f_def = jtu.tree_flatten(f_out)
    if t_def != f_def:
        raise TypeError('cond branches must return the same structure: '
                        '%s vs %s' % (t_def, f_def))
    if not t_leaves:
        return t_out  # e.g. both branches return None (side-effect build)
    leaves, seen = [], set()
    for t in _branch_leaves(t_rec) + _branch_leaves(f_rec):
        if id(t) not in seen:
            seen.add(id(t))
            leaves.append(t)

    def fn(p, *arrays):
        env0 = {id(t): a for t, a in zip(leaves, arrays)}

        def tf(_):
            return _flat_unwrapped(_replay_rec(t_rec, t_out, dict(env0)))

        def ff(_):
            return _flat_unwrapped(_replay_rec(f_rec, f_out, dict(env0)))

        out = lax.cond(jnp.reshape(p, ()).astype(bool), tf, ff, None)
        return out if len(out) > 1 else out[0]

    pred_t = pred if isinstance(pred, Tensor) else Tensor(pred)
    out = run_op('cond', fn, pred_t, *leaves)
    outs = out if isinstance(out, tuple) else (out,)
    return jtu.tree_unflatten(t_def, _wrap_tree(list(outs)))


def case(pred_fn_pairs, default=None, name=None):
    """First-true-wins chain of conds (reference control_flow.case)."""
    if not pred_fn_pairs:
        raise ValueError('case needs at least one (pred, fn) pair')

    def build(pairs):
        (p, fn) = pairs[0]
        if len(pairs) == 1:
            if default is None:
                return fn()
            return cond(p, fn, default)
        return cond(p, fn, lambda: build(pairs[1:]))
    return build(list(pred_fn_pairs))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """lax.switch (reference control_flow.switch_case). branch_fns:
    {index: fn} or [(index, fn)] or [fn, ...]. Branches are recorded
    eagerly and replayed inside lax.switch through the tape (same
    machinery as cond), so grads flow to Tensors the branches read."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        items = sorted((int(i), f) for i, f in branch_fns)
    else:
        items = list(enumerate(branch_fns))
    if not items:
        raise ValueError('switch_case needs at least one branch')
    if items[0][0] < 0:
        raise ValueError('switch_case branch indices must be non-negative, '
                         'got %r' % (items[0][0],))
    max_idx = items[-1][0]
    table = {}
    for i, f in items:
        table[int(i)] = f
    fallback = default or items[-1][1]
    branches = [table.get(i, fallback) for i in range(max_idx + 1)] + \
        [fallback]

    # record each distinct builder once; gaps/out-of-range share a record
    rec_by_id = {}
    recorded = []
    for f in branches:
        if id(f) not in rec_by_id:
            rec_by_id[id(f)] = _record_branch(f)
        recorded.append(rec_by_id[id(f)])
    first_out = recorded[0][0]
    first_leaves, first_def = jtu.tree_flatten(first_out)
    for out_i, _rec in recorded[1:]:
        if jtu.tree_flatten(out_i)[1] != first_def:
            raise TypeError('switch_case branches must return the same '
                            'structure')
    if not first_leaves:
        return first_out
    leaves, seen = [], set()
    for _out, rec in recorded:
        for t in _branch_leaves(rec):
            if id(t) not in seen:
                seen.add(id(t))
                leaves.append(t)

    def fn(bidx, *arrays):
        env0 = {id(t): a for t, a in zip(leaves, arrays)}
        fns = [lambda _, o=o, r=r: _flat_unwrapped(
                   _replay_rec(r, o, dict(env0)))
               for o, r in recorded]
        flat_idx = jnp.reshape(bidx, ()).astype(jnp.int32)
        idx = jnp.clip(flat_idx, 0, max_idx + 1)
        in_table = jnp.isin(flat_idx, jnp.asarray(sorted(table)))
        idx = jnp.where(in_table, idx, max_idx + 1)
        out = lax.switch(idx, fns, None)
        return out if len(out) > 1 else out[0]

    bidx_t = branch_index if isinstance(branch_index, Tensor) \
        else Tensor(branch_index)
    out = run_op('switch_case', fn, bidx_t, *leaves)
    outs = out if isinstance(out, tuple) else (out,)
    return jtu.tree_unflatten(first_def, _wrap_tree(list(outs)))


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """lax.while_loop (reference control_flow.while_loop / while op).
    Forward-only: XLA's while has no reverse-mode — outputs come back
    stop_gradient=True."""
    init = _unwrap_tree(list(loop_vars))

    def c(vs):
        return jnp.reshape(_unwrap(cond_fn(*_wrap_tree(vs))), ()).astype(bool)

    def b(vs):
        out = body_fn(*_wrap_tree(vs))
        out = out if isinstance(out, (list, tuple)) else [out]
        return _unwrap_tree(list(out))

    with no_grad_guard():
        out = lax.while_loop(c, b, init)
    return _wrap_tree(list(out))


# -- layer builders over the functional/eager surface ------------------------

def fc(x, size, num_flatten_dims=1, activation=None, name=None, **kw):
    from .. import nn as _nn
    from ..tensor.manipulation import flatten
    xf = flatten(x, start_axis=num_flatten_dims) \
        if num_flatten_dims != 1 else x
    lin = _nn.Linear(xf.shape[-1], size)
    out = lin(xf)
    if activation:
        out = getattr(_nn.functional, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype='float32'):
    from .. import nn as _nn
    emb = _nn.Embedding(size[0], size[1], padding_idx=padding_idx)
    return emb(input)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, **kw):
    from .. import nn as _nn
    bn = _nn.BatchNorm2D(input.shape[1], momentum=momentum, epsilon=epsilon)
    out = bn(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, **kw):
    from ..nn import functional as F
    shape = input.shape[begin_norm_axis:]
    w = Tensor(jnp.ones(shape, jnp.float32)) if scale else None
    b = Tensor(jnp.zeros(shape, jnp.float32)) if shift else None
    return F.layer_norm(input, shape, weight=w, bias=b)


def instance_norm(input, epsilon=1e-5, **kw):
    from .. import nn as _nn
    return _nn.InstanceNorm2D(input.shape[1], epsilon=epsilon)(input)


def group_norm(input, groups, epsilon=1e-5, **kw):
    from .. import nn as _nn
    return _nn.GroupNorm(groups, input.shape[1], epsilon=epsilon)(input)


def prelu(x, mode='all', param_attr=None, **kw):
    from ..nn import functional as F
    n = 1 if mode == 'all' else x.shape[1]
    return F.prelu(x, Tensor(jnp.full((n,), 0.25, jnp.float32)))


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, act=None, **kw):
    from .. import nn as _nn
    conv = _nn.Conv2D(input.shape[1], num_filters, filter_size,
                      stride=stride, padding=padding, dilation=dilation,
                      groups=groups)
    out = conv(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def conv2d_transpose(input, num_filters, filter_size=None, stride=1,
                     padding=0, **kw):
    from .. import nn as _nn
    conv = _nn.Conv2DTranspose(input.shape[1], num_filters,
                               filter_size or 3, stride=stride,
                               padding=padding)
    return conv(input)


def conv3d(input, num_filters, filter_size, **kw):
    from .. import nn as _nn
    return _nn.Conv3D(input.shape[1], num_filters, filter_size)(input)


def conv3d_transpose(input, num_filters, filter_size=None, output_size=None,
                     stride=1, padding=0, **kw):
    from .. import nn as _nn
    return _nn.Conv3DTranspose(input.shape[1], num_filters,
                               filter_size or 4, stride=stride,
                               padding=padding)(input, output_size)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, **kw):
    raise NotImplementedError(
        'spectral_norm: use nn.utils.spectral_norm on the Layer instead')


def create_parameter(*args, **kwargs):
    from . import create_parameter as _cp
    return _cp(*args, **kwargs)


def py_func(*args, **kwargs):
    from . import py_func as _pf
    return _pf(*args, **kwargs)


def data_norm(input, **kw):
    # data_norm = batch stats normalization without scale/shift learning
    from ..framework.core import run_op

    def fn(a):
        mu = jnp.mean(a, axis=0, keepdims=True)
        var = jnp.var(a, axis=0, keepdims=True)
        return (a - mu) / jnp.sqrt(var + 1e-5)
    return run_op('data_norm', fn, input)


def nce(input, label, num_total_classes, **kw):
    raise NotImplementedError(
        'nce: use nn.functional.hsigmoid_loss or sampled softmax via '
        'paddle_tpu ops — the NCE op family is superseded')


def sparse_embedding(input, size, **kw):
    raise NotImplementedError(
        'sparse_embedding (PS-backed): construct distributed.ps.'
        'HeterEmbedding(client, table_id, dim) with an embedding service '
        'client — the 100B-feature path needs the explicit service handle')


def bilinear_tensor_product(x, y, size, **kw):
    from ..framework.core import run_op, Parameter
    import numpy as _np
    w = Parameter((_np.random.RandomState(0).randn(
        size, x.shape[-1], y.shape[-1]) * 0.01).astype(_np.float32))

    def fn(a, b, ww):
        return jnp.einsum('bi,kij,bj->bk', a, ww, b)
    return run_op('bilinear_tensor_product', fn, x, y, w)


def deform_conv2d(*args, **kwargs):
    from ..vision.ops import deform_conv2d as _dc
    return _dc(*args, **kwargs)


def _sequence_unsupported(name):
    def fn(*a, **k):
        raise NotImplementedError(
            '%s: LoD sequence ops are not ported (SURVEY §7.5) — ragged '
            'data rides masks on TPU; see nn.functional.sequence_mask'
            % name)
    fn.__name__ = name
    return fn


for _n in ('sequence_conv', 'sequence_softmax', 'sequence_pool',
           'sequence_concat', 'sequence_first_step', 'sequence_last_step',
           'sequence_slice', 'sequence_expand', 'sequence_expand_as',
           'sequence_pad', 'sequence_unpad', 'sequence_reshape',
           'sequence_scatter', 'sequence_enumerate', 'sequence_reverse',
           'multi_box_head'):
    globals()[_n] = _sequence_unsupported(_n)
    __all__.append(_n)


# -- fluid-era losses / CTR ops (batch layout, mask-based) -------------------

def rank_loss(label, left, right, name=None):
    """RankNet pairwise loss (reference rank_loss_op.cc): o = left-right,
    C = log(1 + e^o) - label*o."""
    def fn(t, lo, ro):
        o = lo - ro
        return jnp.logaddexp(0.0, o) - t * o
    return run_op('rank_loss', fn, label, left, right)


def bpr_loss(input, label, name=None):
    """Bayesian personalized ranking (reference bpr_loss_op.cc): per row,
    -mean over j != y of log(sigmoid(x_y - x_j))."""
    def fn(x, y):
        n, c = x.shape
        pos = jnp.take_along_axis(x, y.reshape(n, 1).astype(jnp.int32),
                                  axis=1)
        diff = pos - x                       # [n, c]
        lsig = jax.nn.log_sigmoid(diff)
        mask = jnp.ones((n, c), x.dtype).at[
            jnp.arange(n), y.reshape(n).astype(jnp.int32)].set(0.0)
        return (-(lsig * mask).sum(1) / jnp.maximum(mask.sum(1), 1.0)
                ).reshape(n, 1)
    return run_op('bpr_loss', fn, input, label)


def center_loss(input, label, num_classes, alpha, centers=None,
                update_center=True, name=None):
    """Center loss (reference center_loss_op.cc): 0.5*||x - c_y||^2 per
    sample; class centers drift toward their members by `alpha` (eager
    side update, like the reference's in-op center update). Returns
    (loss [N,1], centers)."""
    x = input if isinstance(input, Tensor) else Tensor(input)
    if centers is None:
        centers = Tensor(jnp.zeros((num_classes, x.shape[-1]),
                                   x._data.dtype))
    y = (label if isinstance(label, Tensor) else Tensor(label))

    def fn(a, c):
        yy = y._data.reshape(-1).astype(jnp.int32)
        diff = a - c[yy]
        return 0.5 * jnp.sum(diff * diff, axis=1, keepdims=True)
    loss = run_op('center_loss', fn, x, centers)

    if update_center:
        with no_grad_guard():
            yy = y._data.reshape(-1).astype(jnp.int32)
            diff = centers._data[yy] - x._data          # [N, D]
            num = jax.ops.segment_sum(diff, yy, num_segments=num_classes)
            cnt = jax.ops.segment_sum(jnp.ones_like(yy, x._data.dtype), yy,
                                      num_segments=num_classes)
            centers._data = centers._data - alpha * num / (
                1.0 + cnt).reshape(-1, 1)
    return loss, centers


def cvm(input, cvm_input, use_cvm=True, name=None):
    """CTR show/click feature op (reference cvm_op.cc). First two columns
    of each embedding row carry (show, click); use_cvm=True rewrites them
    to (log(show+1), log(click+1)-log(show+1)), else strips them."""
    def fn(x, c):
        if not use_cvm:
            return x[:, 2:]
        show = jnp.log(c[:, :1] + 1.0)
        ctr = jnp.log(c[:, 1:2] + 1.0) - show
        return jnp.concatenate([show, ctr, x[:, 2:]], axis=1)
    return run_op('cvm', fn, input, cvm_input)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """Pad y up to x's shape (reference pad_constant_like_op.cc)."""
    ref = x if isinstance(x, Tensor) else Tensor(x)

    def fn(b):
        pads = [(0, int(sx - sy)) for sx, sy in zip(ref.shape, b.shape)]
        return jnp.pad(b, pads, constant_values=pad_value)
    return run_op('pad_constant_like', fn, y)


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None,
                **kw):
    """Patch extraction to sequence rows (reference im2sequence_op.cc):
    [N,C,H,W] -> [N*oh*ow, C*fh*fw]."""
    fh, fw = (filter_size, filter_size) if isinstance(filter_size, int) \
        else filter_size
    sh, sw = (stride, stride) if isinstance(stride, int) else stride[:2]
    if isinstance(padding, int):
        pads = [(padding, padding), (padding, padding)]
    elif len(padding) == 4:
        # reference im2sequence_op layout: [up, left, down, right]
        up, left, down, right = padding
        pads = [(up, down), (left, right)]
    else:
        ph, pw = padding[:2]
        pads = [(ph, ph), (pw, pw)]

    def fn(a):
        n, c, _h, _w = a.shape
        patches = lax.conv_general_dilated_patches(
            a, (fh, fw), (sh, sw), pads)
        # patches: [N, C*fh*fw, oh, ow] -> [N*oh*ow, C*fh*fw]
        n_, cf, oh, ow = patches.shape
        return patches.transpose(0, 2, 3, 1).reshape(n_ * oh * ow, cf)
    return run_op('im2sequence', fn, input)


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    """Lookahead row convolution (reference row_conv_op.cc), batch
    layout [B, L, D]: out[t] = sum_{i<=future} w[i] * x[t+i]."""
    x = input if isinstance(input, Tensor) else Tensor(input)
    b, l, d = x.shape
    # fresh trainable filter per call, like this module's fc/conv builders
    w = Parameter(jnp.full((future_context_size + 1, d), 1.0 /
                           (future_context_size + 1), x._data.dtype))

    def fn(a, ww):
        out = jnp.zeros_like(a)
        for i in range(future_context_size + 1):
            sh = jnp.pad(a, ((0, 0), (0, i), (0, 0)))[:, i:i + l]
            out = out + sh * ww[i]
        return out
    out = run_op('row_conv', fn, x, w)
    if act:
        from ..nn import functional as _F
        out = getattr(_F, act)(out)
    return out


def sample_logits(logits, label, num_samples, num_true=1, seed=0,
                  remove_accidental_hits=True, use_customized_samples=False,
                  customized_samples=None, customized_probabilities=None,
                  name=None):
    """Sampled-softmax helper (reference sample_logits_op.cc): gather the
    true-class logits plus `num_samples` log-uniform negatives, correct
    both by -log(Q) so softmax over the sampled set estimates the full
    softmax. Returns (sampled_logits [N, T+S], sampled_labels [N, T])."""
    lg = logits if isinstance(logits, Tensor) else Tensor(logits)
    lb = label if isinstance(label, Tensor) else Tensor(label)
    n, k = lg.shape
    rng = np.random.RandomState(seed or None)
    if use_customized_samples:
        samples = jnp.asarray(customized_samples._data
                              if isinstance(customized_samples, Tensor)
                              else customized_samples)
        probs = jnp.asarray(customized_probabilities._data
                            if isinstance(customized_probabilities, Tensor)
                            else customized_probabilities)
    else:
        # log-uniform (Zipfian) candidate sampler, as the reference uses
        u = rng.uniform(size=(num_samples,))
        samples = jnp.asarray(
            np.clip((np.exp(u * np.log(k + 1.0)) - 1.0).astype(np.int64),
                    0, k - 1))
        probs = jnp.asarray(
            (np.log((samples + 2.0) / (samples + 1.0)) /
             np.log(k + 1.0)).astype(np.float32))

    def fn(x, y):
        yy = y.reshape(n, num_true).astype(jnp.int32)
        true_logit = jnp.take_along_axis(x, yy, axis=1)
        true_q = (jnp.log((yy + 2.0) / (yy + 1.0)) /
                  jnp.log(k + 1.0)).astype(x.dtype)
        samp_logit = x[:, samples.astype(jnp.int32)]
        if remove_accidental_hits:
            hit = (samples[None, None, :] == yy[:, :, None]).any(1)
            samp_logit = samp_logit - hit.astype(x.dtype) * 1e20
        out = jnp.concatenate([true_logit - jnp.log(true_q),
                               samp_logit - jnp.log(probs)[None, :].astype(
                                   x.dtype)], axis=1)
        return out
    out = run_op('sample_logits', fn, lg, lb)
    sampled_label = Tensor(jnp.tile(jnp.arange(num_true, dtype=jnp.int32),
                                    (n, 1)))
    return out, sampled_label


# -- linear-chain CRF (reference linear_chain_crf_op.cc / crf_decoding) ------

def _crf_scan_nll(emission, transition, label, length):
    """Negative log-likelihood per sequence. emission [B,L,K]; transition
    [K+2, K] paddle layout (row 0 start, row 1 stop, rows 2.. K x K);
    label [B,L] int; length [B] int."""
    b, l, k = emission.shape
    start, stop, trans = transition[0], transition[1], transition[2:]
    t_idx = jnp.arange(l)
    mask = (t_idx[None, :] < length[:, None]).astype(emission.dtype)  # [B,L]

    # log partition: alpha recursion over time
    def step(alpha, xs):
        em_t, m_t = xs                       # [B,K], [B,1]
        nxt = jax.scipy.special.logsumexp(
            alpha[:, :, None] + trans[None], axis=1) + em_t
        return jnp.where(m_t > 0, nxt, alpha), None
    alpha0 = start[None] + emission[:, 0]
    alphaT, _ = lax.scan(
        step, alpha0,
        (jnp.swapaxes(emission[:, 1:], 0, 1),
         jnp.swapaxes(mask[:, 1:, None], 0, 1)))
    log_z = jax.scipy.special.logsumexp(alphaT + stop[None], axis=1)

    # gold path score
    lb = label.astype(jnp.int32)
    em_score = (jnp.take_along_axis(emission, lb[:, :, None],
                                    axis=2)[..., 0] * mask).sum(1)
    pair_m = mask[:, 1:]
    tr_score = (trans[lb[:, :-1], lb[:, 1:]] * pair_m).sum(1)
    last = jnp.maximum(length - 1, 0)
    last_lb = jnp.take_along_axis(lb, last[:, None].astype(jnp.int32),
                                  axis=1)[:, 0]
    gold = (em_score + tr_score + start[lb[:, 0]] + stop[last_lb])
    return (log_z - gold).reshape(b, 1)


def linear_chain_crf(input, label, param_attr=None, length=None,
                     transition=None, name=None):
    """Linear-chain CRF cost (reference linear_chain_crf_op.cc), batch
    layout: input [B,L,K] emissions, label [B,L], length [B] (defaults
    to full L). Returns (cost [B,1], transition) — minimize the cost
    directly, as fluid does with the op's LogLikelihood output."""
    em = input if isinstance(input, Tensor) else Tensor(input)
    lb = label if isinstance(label, Tensor) else Tensor(label)
    b, l, k = em.shape
    if transition is None:
        transition = Parameter((np.random.RandomState(0)
                                .uniform(-0.1, 0.1, (k + 2, k))
                                ).astype(np.float32))
    if length is None:
        length = Tensor(jnp.full((b,), l, jnp.int32))
    ln = length if isinstance(length, Tensor) else Tensor(length)

    def fn(e, t):
        return _crf_scan_nll(e, t, lb._data, ln._data)
    return run_op('linear_chain_crf', fn, em, transition), transition


def crf_decoding(input, transition, length=None, label=None, name=None):
    """Viterbi decode (reference crf_decoding_op.cc): argmax path under
    the CRF. Returns [B,L] int32 (entries past `length` are 0); with
    `label` given, returns 1 where the decoded tag matches the label
    (reference crf_decoding_op.h marks correct tags with 1)."""
    em = input if isinstance(input, Tensor) else Tensor(input)
    tr = transition if isinstance(transition, Tensor) else Tensor(transition)
    b, l, k = em.shape
    if length is None:
        length = Tensor(jnp.full((b,), l, jnp.int32))
    ln = length if isinstance(length, Tensor) else Tensor(length)

    def fn(e, t):
        start, stop, trans = t[0], t[1], t[2:]
        lens = ln._data
        mask = (jnp.arange(l)[None, :] < lens[:, None])

        def step(carry, xs):
            score = carry                       # [B,K]
            em_t, m_t = xs
            cand = score[:, :, None] + trans[None]     # [B,K,K]
            best = cand.max(1) + em_t
            back = cand.argmax(1).astype(jnp.int32)    # [B,K]
            nscore = jnp.where(m_t[:, None], best, score)
            return nscore, back
        score0 = start[None] + e[:, 0]
        scoreT, backs = lax.scan(
            step, score0,
            (jnp.swapaxes(e[:, 1:], 0, 1),
             jnp.swapaxes(mask[:, 1:], 0, 1)))   # backs [L-1,B,K]
        final = (scoreT + stop[None]).argmax(1).astype(jnp.int32)  # [B]

        def walk(carry, xs):
            cur = carry                          # [B]
            back_t, m_t = xs
            prev = jnp.take_along_axis(back_t, cur[:, None], axis=1)[:, 0]
            nxt = jnp.where(m_t, prev, cur)
            return nxt, cur
        # walk backward: at masked steps the pointer is frozen. The scan
        # emits the tag at each t from L-1 down to 1; its final carry is
        # the tag at t=0.
        tag0, path_rev = lax.scan(walk, final,
                                  (backs[::-1], jnp.swapaxes(mask[:, 1:],
                                                             0, 1)[::-1]))
        path = jnp.concatenate([tag0[None], path_rev[::-1]], axis=0)  # [L,B]
        path = jnp.swapaxes(path, 0, 1)
        path = jnp.where(mask, path, 0)
        return path
    out = run_op('crf_decoding', fn, em, tr)
    if label is not None:
        # reference crf_decoding_op.h: 1 marks a correctly decoded tag
        lb = label if isinstance(label, Tensor) else Tensor(label)
        return Tensor((out._data == lb._data.astype(out._data.dtype))
                      .astype(jnp.int32))
    return out


# -- fluid-era aliases over the modern functional surface --------------------

def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,
        data_format='NCHW'):
    from ..nn import functional as _F
    return _F.local_response_norm(input, size=n, alpha=alpha, beta=beta,
                                  k=k, data_format=data_format)


def cos_sim(X, Y, name=None):
    from ..nn import functional as _F
    from ..tensor.manipulation import reshape
    return reshape(_F.cosine_similarity(X, Y, axis=1), [-1, 1])


def space_to_depth(x, blocksize, name=None):
    from ..nn import functional as _F
    return _F.pixel_unshuffle(x, blocksize)


def reverse(x, axis, name=None):
    from ..tensor.manipulation import flip
    return flip(x, axis)


__all__ += ['rank_loss', 'bpr_loss', 'center_loss', 'cvm',
            'pad_constant_like', 'im2sequence', 'row_conv', 'sample_logits',
            'linear_chain_crf', 'crf_decoding', 'lrn', 'cos_sim',
            'space_to_depth', 'reverse']
