"""paddle.static.nn (reference: python/paddle/static/nn/__init__.py —
static layer builders + the control-flow ops of
fluid/layers/control_flow.py).

TPU-native control flow: cond/case/switch_case/while_loop ARE
lax.cond/lax.switch/lax.while_loop (SURVEY §7.1 — the reference's
conditional_block/while ops compile to XLA control flow here, no
sub-block machinery). cond and switch_case differentiate through the
tape; while_loop is forward-only (XLA while has no reverse — use
lax.scan-style bounded loops in differentiable paths, same guidance the
reference gives for DynamicRNN).

sequence_* builders are deliberately not ported (SURVEY §7.5: ragged
data rides masks — see nn.functional.sequence_mask); they raise with
that guidance.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax import tree_util as jtu

from ..framework.core import Tensor, run_op, no_grad_guard

__all__ = ['fc', 'cond', 'case', 'switch_case', 'while_loop', 'embedding',
           'batch_norm', 'layer_norm', 'instance_norm', 'group_norm',
           'prelu', 'conv2d', 'conv2d_transpose', 'conv3d', 'spectral_norm',
           'create_parameter', 'py_func', 'data_norm', 'nce',
           'sparse_embedding', 'bilinear_tensor_product', 'deform_conv2d']


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _wrap_tree(tree):
    if isinstance(tree, (list, tuple)):
        return type(tree)(_wrap_tree(v) for v in tree)
    return Tensor(tree) if not isinstance(tree, Tensor) else tree


def _unwrap_tree(tree):
    if isinstance(tree, (list, tuple)):
        return type(tree)(_unwrap_tree(v) for v in tree)
    return _unwrap(tree)


# -- control flow ------------------------------------------------------------

def _record_branch(fn):
    """Run a branch builder eagerly while recording its paddle ops
    (core._fwd_recorder — the same hook static.program_guard uses).
    Mirrors the reference: cond BUILDS both sub-blocks
    (conditional_block ops) at construction time."""
    from ..framework import core as core_mod
    rec = []
    prev = core_mod._fwd_recorder[0]
    core_mod._fwd_recorder[0] = \
        lambda f, ins, outs: rec.append((f, list(ins), list(outs)))
    try:
        out = fn()
    finally:
        core_mod._fwd_recorder[0] = prev
    return out, rec


def _branch_leaves(rec):
    """Input Tensors of a recording that no earlier recorded op produced
    — the operands grads must flow to."""
    produced = set()
    leaves, seen = [], set()
    for _f, ins, outs in rec:
        for t in ins:
            if id(t) not in produced and id(t) not in seen:
                seen.add(id(t))
                leaves.append(t)
        produced.update(id(t) for t in outs)
    return leaves


def _replay_rec(rec, result, env):
    """Re-evaluate a branch recording with `env` (id -> array)."""
    for f, ins, outs in rec:
        arrays = [env.get(id(t), t._data) for t in ins]
        res = f(*arrays)
        res = res if isinstance(res, tuple) else (res,)
        for t, a in zip(outs, res):
            env[id(t)] = a

    # Tensors are unregistered pytree leaves, so tree_map substitutes
    # them in-place across any output structure (list/tuple/dict/...)
    return jtu.tree_map(
        lambda t: env.get(id(t), t._data) if isinstance(t, Tensor) else t,
        result)


def _flat_unwrapped(tree):
    """Flatten a branch-output tree (Tensors are leaves) to arrays."""
    return tuple(_unwrap(v) for v in jtu.tree_flatten(tree)[0])


def cond(pred, true_fn=None, false_fn=None, name=None):
    """lax.cond (reference control_flow.py cond / conditional_block op).
    Both branches are built once eagerly (the reference builds both
    sub-blocks too) and replayed inside lax.cond; every leaf Tensor a
    branch reads becomes a tape operand, so grads flow. Branch outputs
    may be a Tensor or any pytree of them; run_op sees a flat tuple and
    the caller gets the original structure back."""
    t_out, t_rec = _record_branch(true_fn)
    f_out, f_rec = _record_branch(false_fn)
    t_leaves, t_def = jtu.tree_flatten(t_out)
    _f_leaves, f_def = jtu.tree_flatten(f_out)
    if t_def != f_def:
        raise TypeError('cond branches must return the same structure: '
                        '%s vs %s' % (t_def, f_def))
    if not t_leaves:
        return t_out  # e.g. both branches return None (side-effect build)
    leaves, seen = [], set()
    for t in _branch_leaves(t_rec) + _branch_leaves(f_rec):
        if id(t) not in seen:
            seen.add(id(t))
            leaves.append(t)

    def fn(p, *arrays):
        env0 = {id(t): a for t, a in zip(leaves, arrays)}

        def tf(_):
            return _flat_unwrapped(_replay_rec(t_rec, t_out, dict(env0)))

        def ff(_):
            return _flat_unwrapped(_replay_rec(f_rec, f_out, dict(env0)))

        out = lax.cond(jnp.reshape(p, ()).astype(bool), tf, ff, None)
        return out if len(out) > 1 else out[0]

    pred_t = pred if isinstance(pred, Tensor) else Tensor(pred)
    out = run_op('cond', fn, pred_t, *leaves)
    outs = out if isinstance(out, tuple) else (out,)
    return jtu.tree_unflatten(t_def, _wrap_tree(list(outs)))


def case(pred_fn_pairs, default=None, name=None):
    """First-true-wins chain of conds (reference control_flow.case)."""
    if not pred_fn_pairs:
        raise ValueError('case needs at least one (pred, fn) pair')

    def build(pairs):
        (p, fn) = pairs[0]
        if len(pairs) == 1:
            if default is None:
                return fn()
            return cond(p, fn, default)
        return cond(p, fn, lambda: build(pairs[1:]))
    return build(list(pred_fn_pairs))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """lax.switch (reference control_flow.switch_case). branch_fns:
    {index: fn} or [(index, fn)] or [fn, ...]. Branches are recorded
    eagerly and replayed inside lax.switch through the tape (same
    machinery as cond), so grads flow to Tensors the branches read."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        items = sorted((int(i), f) for i, f in branch_fns)
    else:
        items = list(enumerate(branch_fns))
    if not items:
        raise ValueError('switch_case needs at least one branch')
    if items[0][0] < 0:
        raise ValueError('switch_case branch indices must be non-negative, '
                         'got %r' % (items[0][0],))
    max_idx = items[-1][0]
    table = {}
    for i, f in items:
        table[int(i)] = f
    fallback = default or items[-1][1]
    branches = [table.get(i, fallback) for i in range(max_idx + 1)] + \
        [fallback]

    # record each distinct builder once; gaps/out-of-range share a record
    rec_by_id = {}
    recorded = []
    for f in branches:
        if id(f) not in rec_by_id:
            rec_by_id[id(f)] = _record_branch(f)
        recorded.append(rec_by_id[id(f)])
    first_out = recorded[0][0]
    first_leaves, first_def = jtu.tree_flatten(first_out)
    for out_i, _rec in recorded[1:]:
        if jtu.tree_flatten(out_i)[1] != first_def:
            raise TypeError('switch_case branches must return the same '
                            'structure')
    if not first_leaves:
        return first_out
    leaves, seen = [], set()
    for _out, rec in recorded:
        for t in _branch_leaves(rec):
            if id(t) not in seen:
                seen.add(id(t))
                leaves.append(t)

    def fn(bidx, *arrays):
        env0 = {id(t): a for t, a in zip(leaves, arrays)}
        fns = [lambda _, o=o, r=r: _flat_unwrapped(
                   _replay_rec(r, o, dict(env0)))
               for o, r in recorded]
        flat_idx = jnp.reshape(bidx, ()).astype(jnp.int32)
        idx = jnp.clip(flat_idx, 0, max_idx + 1)
        in_table = jnp.isin(flat_idx, jnp.asarray(sorted(table)))
        idx = jnp.where(in_table, idx, max_idx + 1)
        out = lax.switch(idx, fns, None)
        return out if len(out) > 1 else out[0]

    bidx_t = branch_index if isinstance(branch_index, Tensor) \
        else Tensor(branch_index)
    out = run_op('switch_case', fn, bidx_t, *leaves)
    outs = out if isinstance(out, tuple) else (out,)
    return jtu.tree_unflatten(first_def, _wrap_tree(list(outs)))


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """lax.while_loop (reference control_flow.while_loop / while op).
    Forward-only: XLA's while has no reverse-mode — outputs come back
    stop_gradient=True."""
    init = _unwrap_tree(list(loop_vars))

    def c(vs):
        return jnp.reshape(_unwrap(cond_fn(*_wrap_tree(vs))), ()).astype(bool)

    def b(vs):
        out = body_fn(*_wrap_tree(vs))
        out = out if isinstance(out, (list, tuple)) else [out]
        return _unwrap_tree(list(out))

    with no_grad_guard():
        out = lax.while_loop(c, b, init)
    return _wrap_tree(list(out))


# -- layer builders over the functional/eager surface ------------------------

def fc(x, size, num_flatten_dims=1, activation=None, name=None, **kw):
    from .. import nn as _nn
    from ..tensor.manipulation import flatten
    xf = flatten(x, start_axis=num_flatten_dims) \
        if num_flatten_dims != 1 else x
    lin = _nn.Linear(xf.shape[-1], size)
    out = lin(xf)
    if activation:
        out = getattr(_nn.functional, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype='float32'):
    from .. import nn as _nn
    emb = _nn.Embedding(size[0], size[1], padding_idx=padding_idx)
    return emb(input)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, **kw):
    from .. import nn as _nn
    bn = _nn.BatchNorm2D(input.shape[1], momentum=momentum, epsilon=epsilon)
    out = bn(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, **kw):
    from ..nn import functional as F
    shape = input.shape[begin_norm_axis:]
    w = Tensor(jnp.ones(shape, jnp.float32)) if scale else None
    b = Tensor(jnp.zeros(shape, jnp.float32)) if shift else None
    return F.layer_norm(input, shape, weight=w, bias=b)


def instance_norm(input, epsilon=1e-5, **kw):
    from .. import nn as _nn
    return _nn.InstanceNorm2D(input.shape[1], epsilon=epsilon)(input)


def group_norm(input, groups, epsilon=1e-5, **kw):
    from .. import nn as _nn
    return _nn.GroupNorm(groups, input.shape[1], epsilon=epsilon)(input)


def prelu(x, mode='all', param_attr=None, **kw):
    from ..nn import functional as F
    n = 1 if mode == 'all' else x.shape[1]
    return F.prelu(x, Tensor(jnp.full((n,), 0.25, jnp.float32)))


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, act=None, **kw):
    from .. import nn as _nn
    conv = _nn.Conv2D(input.shape[1], num_filters, filter_size,
                      stride=stride, padding=padding, dilation=dilation,
                      groups=groups)
    out = conv(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def conv2d_transpose(input, num_filters, filter_size=None, stride=1,
                     padding=0, **kw):
    from .. import nn as _nn
    conv = _nn.Conv2DTranspose(input.shape[1], num_filters,
                               filter_size or 3, stride=stride,
                               padding=padding)
    return conv(input)


def conv3d(input, num_filters, filter_size, **kw):
    from .. import nn as _nn
    return _nn.Conv3D(input.shape[1], num_filters, filter_size)(input)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, **kw):
    raise NotImplementedError(
        'spectral_norm: use nn.utils.spectral_norm on the Layer instead')


def create_parameter(*args, **kwargs):
    from . import create_parameter as _cp
    return _cp(*args, **kwargs)


def py_func(*args, **kwargs):
    from . import py_func as _pf
    return _pf(*args, **kwargs)


def data_norm(input, **kw):
    # data_norm = batch stats normalization without scale/shift learning
    from ..framework.core import run_op

    def fn(a):
        mu = jnp.mean(a, axis=0, keepdims=True)
        var = jnp.var(a, axis=0, keepdims=True)
        return (a - mu) / jnp.sqrt(var + 1e-5)
    return run_op('data_norm', fn, input)


def nce(input, label, num_total_classes, **kw):
    raise NotImplementedError(
        'nce: use nn.functional.hsigmoid_loss or sampled softmax via '
        'paddle_tpu ops — the NCE op family is superseded')


def sparse_embedding(input, size, **kw):
    raise NotImplementedError(
        'sparse_embedding (PS-backed): construct distributed.ps.'
        'HeterEmbedding(client, table_id, dim) with an embedding service '
        'client — the 100B-feature path needs the explicit service handle')


def bilinear_tensor_product(x, y, size, **kw):
    from ..framework.core import run_op, Parameter
    import numpy as _np
    w = Parameter((_np.random.RandomState(0).randn(
        size, x.shape[-1], y.shape[-1]) * 0.01).astype(_np.float32))

    def fn(a, b, ww):
        return jnp.einsum('bi,kij,bj->bk', a, ww, b)
    return run_op('bilinear_tensor_product', fn, x, y, w)


def deform_conv2d(*args, **kwargs):
    from ..vision.ops import deform_conv2d as _dc
    return _dc(*args, **kwargs)


def _sequence_unsupported(name):
    def fn(*a, **k):
        raise NotImplementedError(
            '%s: LoD sequence ops are not ported (SURVEY §7.5) — ragged '
            'data rides masks on TPU; see nn.functional.sequence_mask'
            % name)
    fn.__name__ = name
    return fn


for _n in ('sequence_conv', 'sequence_softmax', 'sequence_pool',
           'sequence_concat', 'sequence_first_step', 'sequence_last_step',
           'sequence_slice', 'sequence_expand', 'sequence_expand_as',
           'sequence_pad', 'sequence_unpad', 'sequence_reshape',
           'sequence_scatter', 'sequence_enumerate', 'crf_decoding',
           'row_conv', 'multi_box_head'):
    globals()[_n] = _sequence_unsupported(_n)
    __all__.append(_n)
