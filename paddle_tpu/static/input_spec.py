"""InputSpec (reference: python/paddle/static/input.py)."""
import numpy as np

from ..framework import dtype as dtype_mod

__all__ = ['InputSpec']


class InputSpec:
    def __init__(self, shape, dtype='float32', name=None):
        self.shape = tuple(shape)
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.name = name

    def __repr__(self):
        return 'InputSpec(shape=%s, dtype=%s, name=%s)' % (
            self.shape, self.dtype, self.name)

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def batch(self, batch_size):
        return InputSpec((batch_size,) + self.shape, self.dtype, self.name)

    def unbatch(self):
        return InputSpec(self.shape[1:], self.dtype, self.name)
