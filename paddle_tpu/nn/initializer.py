"""Weight initializers (reference: python/paddle/fluid/initializer.py,
python/paddle/nn/initializer/).

An initializer is a callable shape,dtype -> jax array; Layers call
`create_parameter` with one. Draws keys from the global Generator so
`paddle.seed` reproduces the reference's determinism contract.
"""
import math
import numpy as np
import jax
import jax.numpy as jnp

from ..framework import random as rng
from ..framework.dtype import to_jax_dtype

__all__ = [
    'Initializer', 'Constant', 'Normal', 'TruncatedNormal', 'Uniform',
    'XavierNormal', 'XavierUniform', 'KaimingNormal', 'KaimingUniform',
    'Assign', 'Orthogonal', 'Dirac', 'calculate_gain',
]


def calculate_gain(nonlinearity, param=None):
    table = {'sigmoid': 1.0, 'linear': 1.0, 'conv1d': 1.0, 'conv2d': 1.0,
             'conv3d': 1.0, 'tanh': 5.0 / 3, 'relu': math.sqrt(2.0),
             'leaky_relu': math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
             'selu': 3.0 / 4}
    return table[nonlinearity]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype='float32'):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype='float32'):
        return jnp.full(tuple(shape), self.value, to_jax_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype='float32'):
        return self.mean + self.std * jax.random.normal(
            rng.next_key(), tuple(shape), to_jax_dtype(dtype))


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype='float32'):
        return self.mean + self.std * jax.random.truncated_normal(
            rng.next_key(), -2.0, 2.0, tuple(shape), to_jax_dtype(dtype))


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype='float32'):
        return jax.random.uniform(rng.next_key(), tuple(shape),
                                  to_jax_dtype(dtype), self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype='float32'):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(rng.next_key(), tuple(shape),
                                       to_jax_dtype(dtype))


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype='float32'):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(rng.next_key(), tuple(shape),
                                  to_jax_dtype(dtype), -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity='relu'):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype='float32'):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(rng.next_key(), tuple(shape),
                                       to_jax_dtype(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity='relu'):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype='float32'):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(rng.next_key(), tuple(shape),
                                  to_jax_dtype(dtype), -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype='float32'):
        from ..framework.core import Tensor
        v = self.value
        if isinstance(v, Tensor):
            v = v._data
        arr = jnp.asarray(v, to_jax_dtype(dtype)).reshape(tuple(shape))
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype='float32'):
        return self.gain * jax.nn.initializers.orthogonal()(
            rng.next_key(), tuple(shape), to_jax_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype='float32'):
        arr = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        per = oc // self.groups
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(min(per, ic)):
                idx = (g * per + i, i) + tuple(centers)
                arr[idx] = 1.0
        return jnp.asarray(arr, to_jax_dtype(dtype))


# paddle.nn.initializer compat aliases
ConstantInitializer = Constant
NormalInitializer = Normal
UniformInitializer = Uniform
XavierInitializer = XavierNormal
MSRAInitializer = KaimingNormal
TruncatedNormalInitializer = TruncatedNormal
NumpyArrayInitializer = Assign
