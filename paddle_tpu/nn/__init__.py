"""paddle.nn parity surface (reference: python/paddle/nn/__init__.py)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer import *  # noqa: F401,F403
from .layer.layers import Layer, ParamAttr  # noqa: F401
from .clip import (ClipGradByValue, ClipGradByNorm,  # noqa: F401
                   ClipGradByGlobalNorm)
from .utils_weight_norm import (weight_norm, remove_weight_norm,  # noqa: F401
                               spectral_norm, remove_spectral_norm)
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
from . import quant  # noqa: F401


class utils:  # namespace shim: paddle.nn.utils.*
    from .utils_weight_norm import (weight_norm, remove_weight_norm,
                                    spectral_norm, remove_spectral_norm)
    from .clip import clip_grad_norm_, clip_grad_value_

    @staticmethod
    def parameters_to_vector(parameters, name=None):
        import jax.numpy as jnp
        from ..framework.core import Tensor
        return Tensor(jnp.concatenate([p._data.reshape(-1) for p in parameters]))

    @staticmethod
    def vector_to_parameters(vec, parameters, name=None):
        import numpy as np
        offset = 0
        for p in parameters:
            n = int(np.prod(p.shape)) if p.shape else 1
            p.set_value(vec._data[offset:offset + n].reshape(tuple(p.shape)))
            offset += n

from .layer.loss import HSigmoidLoss  # noqa: F401
