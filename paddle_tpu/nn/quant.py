"""paddle.nn.quant parity (reference: python/paddle/nn/quant/
quant_layers.py): the fake-quant layer family — implementations live in
slim.quant_layers (one source of truth for QAT/PTQ and this namespace).
"""
from ..slim import quant_layers  # noqa: F401
from ..slim.quant_layers import *  # noqa: F401,F403
from ..slim.quant_layers import QUANT_LAYER_MAP  # noqa: F401
