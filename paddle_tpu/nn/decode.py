"""Beam-search decoding (reference: python/paddle/fluid/layers/rnn.py
BeamSearchDecoder/dynamic_decode, exposed as paddle.nn.* in 2.x).

TPU-native shape discipline: all per-beam state rides a merged
[batch*beam, ...] leading dim (one big batched matmul per step instead of
beam small ones); the decode loop runs eagerly with early exit on
all-finished, and finalize backtracks with F.gather_tree.
"""
import numpy as np
import jax.numpy as jnp
from jax import tree_util as jtu

from ..framework.core import Tensor, run_op
from ..tensor._helpers import ensure_tensor

__all__ = ['Decoder', 'BeamSearchDecoder', 'dynamic_decode']


def _map_state(tree, fn):
    """Apply fn over every Tensor leaf of a (possibly nested) state —
    Tensors are unregistered pytree leaves, so tree_map handles
    list/tuple/dict-shaped cell states alike."""
    return jtu.tree_map(fn, tree)


class Decoder:
    """Abstract decoder contract (initialize/step/finalize)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """reference fluid/layers/rnn.py BeamSearchDecoder: beam search over an
    RNNCell. embedding_fn maps token ids -> cell inputs; output_fn maps
    cell outputs -> vocab logits (identity if the cell already emits
    logits)."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers over merged [batch*beam, ...] layout ------------------------

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[batch, ...] -> [batch*beam, ...] by repeating each row."""
        t = ensure_tensor(x)

        def fn(a):
            return jnp.repeat(a, beam_size, axis=0)
        return run_op('tile_beam_merge', fn, t)

    def _split(self, a):
        return a.reshape((-1, self.beam_size) + a.shape[1:])

    def _merge(self, a):
        return a.reshape((-1,) + a.shape[2:])

    def initialize(self, initial_cell_states):
        states = _map_state(
            initial_cell_states,
            lambda s: self.tile_beam_merge_with_batch(s, self.beam_size))
        first = jtu.tree_leaves(states)[0]
        nbw = first.shape[0]
        batch = nbw // self.beam_size
        # only beam 0 is live at t=0 (all beams hold the same start token)
        log_probs = jnp.tile(
            jnp.asarray([0.0] + [-1e9] * (self.beam_size - 1), jnp.float32),
            (batch, 1))                                    # [B, W]
        finished = jnp.zeros((batch, self.beam_size), bool)
        lengths = jnp.zeros((batch, self.beam_size), jnp.int32)
        token = Tensor(jnp.full((nbw,), self.start_token, jnp.int32))
        inputs = self.embedding_fn(token) if self.embedding_fn else token
        beam_state = {'cell': states, 'log_probs': Tensor(log_probs),
                      'finished': Tensor(finished), 'lengths': Tensor(lengths)}
        return inputs, beam_state, Tensor(finished)

    def step(self, time, inputs, states, **kwargs):
        import jax
        cell_out, next_cell = self.cell(inputs, states['cell'], **kwargs)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        logits = ensure_tensor(cell_out)._data          # [B*W, V]
        vocab = logits.shape[-1]
        w = self.beam_size
        logp = ensure_tensor(states['log_probs'])._data  # [B, W]
        fin = ensure_tensor(states['finished'])._data    # [B, W]
        lens = ensure_tensor(states['lengths'])._data

        step_logp = jax.nn.log_softmax(
            logits.astype(jnp.float32), axis=-1)        # [B*W, V]
        step_logp = self._split(step_logp)              # [B, W, V]
        # finished beams may only emit end_token, at probability 1, so
        # their total score is frozen while live beams keep extending
        onehot_end = jnp.full((vocab,), -1e9, jnp.float32
                              ).at[self.end_token].set(0.0)
        step_logp = jnp.where(fin[:, :, None], onehot_end[None, None],
                              step_logp)
        total = logp[:, :, None] + step_logp            # [B, W, V]
        flat = total.reshape(total.shape[0], w * vocab)
        top_val, top_idx = jax.lax.top_k(flat, w)       # [B, W]
        parent = top_idx // vocab
        token = top_idx % vocab

        fin_parent = jnp.take_along_axis(fin, parent, axis=1)
        new_fin = fin_parent | (token == self.end_token)
        new_lens = jnp.take_along_axis(lens, parent, axis=1) + \
            (~fin_parent).astype(jnp.int32)

        # reorder every cell-state row by its beam's parent
        def regather(s):
            t = ensure_tensor(s)

            def fn(a):
                sp = self._split(a)                     # [B, W, ...]
                idx = parent.reshape(parent.shape + (1,) *
                                     (sp.ndim - 2)).astype(jnp.int32)
                return self._merge(jnp.take_along_axis(
                    sp, jnp.broadcast_to(idx, parent.shape + sp.shape[2:]),
                    axis=1))
            return run_op('beam_regather', fn, t)
        next_cell = _map_state(next_cell, regather)

        beam_state = {'cell': next_cell, 'log_probs': Tensor(top_val),
                      'finished': Tensor(new_fin), 'lengths': Tensor(new_lens)}
        tok_t = Tensor(self._merge(token))
        next_inputs = self.embedding_fn(tok_t) if self.embedding_fn else tok_t
        outputs = {'token': Tensor(token), 'parent': Tensor(parent),
                   'scores': Tensor(top_val)}
        return outputs, beam_state, next_inputs, Tensor(new_fin)

    def finalize(self, outputs, final_states, sequence_lengths):
        """Backtrack parent pointers into full sequences via gather_tree;
        returns predicted ids [T, B, W] time-major."""
        from . import functional as F
        ids = outputs['token']          # [T, B, W]
        parents = outputs['parent']
        return F.gather_tree(ids, parents), final_states


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """reference fluid/layers/rnn.py dynamic_decode: drive
    decoder.initialize/step until every beam is finished or max_step_num.
    Eager loop with early exit (decode is inference; each step is one
    fused device program)."""
    if impute_finished:
        raise NotImplementedError(
            'impute_finished=True: finished beams are already frozen by '
            'BeamSearchDecoder.step (end-token-only extension), so their '
            'outputs need no imputation; file an issue if a custom Decoder '
            'needs it')
    if max_step_num is not None and max_step_num <= 0:
        raise ValueError('max_step_num must be >= 1, got %r' % max_step_num)
    # max_step_num=None means "until finished" (the reference's while op) —
    # bounded by a safety cap so a beam that never emits end_token returns
    # partial sequences instead of hanging the host loop
    import os
    cap = max_step_num if max_step_num is not None else \
        int(os.environ.get('PADDLE_TPU_MAX_DECODE_STEPS', 10000))
    inputs, states, finished = decoder.initialize(inits)
    tokens, parents, scores = [], [], []
    step = 0
    while True:
        if step >= cap:
            break
        outputs, states, inputs, finished = decoder.step(step, inputs,
                                                         states, **kwargs)
        tokens.append(outputs['token']._data)
        parents.append(outputs['parent']._data)
        scores.append(outputs['scores']._data)
        step += 1
        if bool(np.asarray(finished._data).all()):
            break

    stacked = {'token': Tensor(jnp.stack(tokens)),
               'parent': Tensor(jnp.stack(parents)),
               'scores': Tensor(jnp.stack(scores))}
    lengths = states['lengths'] if isinstance(states, dict) and \
        'lengths' in states else None
    preds, final_states = decoder.finalize(stacked, states, lengths)
    if not output_time_major:
        preds = Tensor(jnp.transpose(preds._data, (1, 0, 2)))
    if return_length:
        return preds, final_states, lengths
    return preds, final_states
