"""Block-sparse attention (reference: paddle.nn.functional.sparse_attention,
operators/sparse_attention_op). Reference semantics with CSR block layout;
computed densely with masking (XLA-friendly) — a Pallas block-skip kernel is
the upgrade path."""
import math

import jax
import jax.numpy as jnp

from ...framework.core import run_op
from ...tensor._helpers import ensure_tensor


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    q = ensure_tensor(query)
    k = ensure_tensor(key)
    v = ensure_tensor(value)
    offs = ensure_tensor(sparse_csr_offset)._data
    cols = ensure_tensor(sparse_csr_columns)._data

    kpm = ensure_tensor(key_padding_mask)._data \
        if key_padding_mask is not None else None
    am = ensure_tensor(attn_mask)._data if attn_mask is not None else None

    def fn(qq, kk, vv):
        scale = 1.0 / math.sqrt(qq.shape[-1])
        s = jnp.einsum('bhqd,bhkd->bhqk', qq, kk) * scale
        B, H, N, M = s.shape
        # build dense mask from CSR: row i attends cols[offs[i]:offs[i+1]]
        row_ids = jnp.repeat(jnp.arange(N), jnp.diff(offs[0, 0]),
                             total_repeat_length=cols.shape[-1])
        mask = jnp.zeros((N, M), bool).at[row_ids, cols[0, 0]].set(True)
        mask = jnp.broadcast_to(mask, (B, H, N, M))
        if kpm is not None:
            # reference contract: 0 marks a masked-out key position
            mask = mask & (kpm != 0)[:, None, None, :]
        if am is not None:
            mask = mask & (am != 0)[None, None]
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        p = jnp.where(mask, p, 0.0)
        return jnp.einsum('bhqk,bhkd->bhqd', p, vv)
    return run_op('sparse_attention', fn, q, k, v)
