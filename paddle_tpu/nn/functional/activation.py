"""Activation functionals (reference: python/paddle/nn/functional/activation.py)."""
import jax
import jax.numpy as jnp

from ...framework.core import run_op
from ...tensor._helpers import ensure_tensor, unary_op

__all__ = [
    'relu', 'relu6', 'relu_', 'elu', 'selu', 'celu', 'gelu', 'leaky_relu',
    'prelu', 'rrelu', 'sigmoid', 'hardsigmoid', 'hardswish', 'hardtanh',
    'hardshrink', 'softshrink', 'tanhshrink', 'softsign', 'softplus',
    'swish', 'silu', 'mish', 'tanh', 'tanh_', 'thresholded_relu',
    'log_sigmoid', 'maxout', 'softmax', 'log_softmax', 'gumbel_softmax',
    'glu',
]

relu = unary_op('relu', jax.nn.relu)
relu6 = unary_op('relu6', jax.nn.relu6)
sigmoid = unary_op('sigmoid', jax.nn.sigmoid)
tanh = unary_op('tanh', jnp.tanh)
softsign = unary_op('softsign', jax.nn.soft_sign)
silu = unary_op('silu', jax.nn.silu)
log_sigmoid = unary_op('log_sigmoid', jax.nn.log_sigmoid)
mish = unary_op('mish', lambda x: x * jnp.tanh(jax.nn.softplus(x)))
tanhshrink = unary_op('tanhshrink', lambda x: x - jnp.tanh(x))


def relu_(x, name=None):
    out = relu(x)
    x._data, x._grad_node = out._data, out._grad_node
    x._node_out_idx, x.stop_gradient = out._node_out_idx, out.stop_gradient
    return x


def tanh_(x, name=None):
    out = tanh(x)
    x._data, x._grad_node = out._data, out._grad_node
    x._node_out_idx, x.stop_gradient = out._node_out_idx, out.stop_gradient
    return x


def elu(x, alpha=1.0, name=None):
    return run_op('elu', lambda a: jax.nn.elu(a, alpha=alpha), ensure_tensor(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return run_op('selu',
                  lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)),
                  ensure_tensor(x))


def celu(x, alpha=1.0, name=None):
    return run_op('celu', lambda a: jax.nn.celu(a, alpha=alpha), ensure_tensor(x))


def gelu(x, approximate=False, name=None):
    return run_op('gelu', lambda a: jax.nn.gelu(a, approximate=approximate),
                  ensure_tensor(x))


def leaky_relu(x, negative_slope=0.01, name=None):
    return run_op('leaky_relu',
                  lambda a: jax.nn.leaky_relu(a, negative_slope=negative_slope),
                  ensure_tensor(x))


def prelu(x, weight, data_format="NCHW", name=None):
    x, w = ensure_tensor(x), ensure_tensor(weight)

    def fn(a, ww):
        if ww.size > 1:
            ch_axis = 1 if data_format == "NCHW" else a.ndim - 1
            shape = [1] * a.ndim
            shape[ch_axis] = -1
            ww = ww.reshape(shape)
        return jnp.where(a > 0, a, ww * a)
    return run_op('prelu', fn, x, w)


def rrelu(x, lower=0.125, upper=0.3333333333333333, training=False, name=None):
    x = ensure_tensor(x)
    if training:
        from ...framework import random as rng
        k = rng.next_key()

        def fn(a):
            r = jax.random.uniform(k, a.shape, a.dtype, lower, upper)
            return jnp.where(a > 0, a, r * a)
        return run_op('rrelu', fn, x)
    mid = (lower + upper) / 2.0
    return run_op('rrelu', lambda a: jnp.where(a > 0, a, mid * a), x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return run_op('hardsigmoid',
                  lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), ensure_tensor(x))


def hardswish(x, name=None):
    return run_op('hardswish',
                  lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, ensure_tensor(x))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return run_op('hardtanh', lambda a: jnp.clip(a, min, max), ensure_tensor(x))


def hardshrink(x, threshold=0.5, name=None):
    return run_op('hardshrink',
                  lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0),
                  ensure_tensor(x))


def softshrink(x, threshold=0.5, name=None):
    return run_op(
        'softshrink',
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold, 0.0)),
        ensure_tensor(x))


def softplus(x, beta=1, threshold=20, name=None):
    return run_op(
        'softplus',
        lambda a: jnp.where(beta * a > threshold, a,
                            jnp.log1p(jnp.exp(beta * a)) / beta),
        ensure_tensor(x))


def swish(x, name=None):
    return silu(x)


def thresholded_relu(x, threshold=1.0, name=None):
    return run_op('thresholded_relu',
                  lambda a: jnp.where(a > threshold, a, 0.0), ensure_tensor(x))


def maxout(x, groups, axis=1, name=None):
    x = ensure_tensor(x)

    def fn(a):
        ax = axis if axis >= 0 else a.ndim + axis
        c = a.shape[ax]
        shp = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(shp), axis=ax + 1)
    return run_op('maxout', fn, x)


def softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)
    from ...framework.dtype import to_jax_dtype

    def fn(a):
        if dtype is not None:
            a = a.astype(to_jax_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)
    return run_op('softmax', fn, x)


softmax_ = softmax


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)
    from ...framework.dtype import to_jax_dtype

    def fn(a):
        if dtype is not None:
            a = a.astype(to_jax_dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)
    return run_op('log_softmax', fn, x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    x = ensure_tensor(x)
    from ...framework import random as rng
    k = rng.next_key()

    def fn(a):
        g = jax.random.gumbel(k, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis)
            onehot = jax.nn.one_hot(idx, y.shape[axis], axis=axis, dtype=y.dtype)
            y = jax.lax.stop_gradient(onehot - y) + y
        return y
    return run_op('gumbel_softmax', fn, x)


def glu(x, axis=-1, name=None):
    return run_op('glu', lambda a: jax.nn.glu(a, axis=axis), ensure_tensor(x))
