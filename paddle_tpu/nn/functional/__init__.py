"""paddle.nn.functional parity surface."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .sparse_attention import sparse_attention  # noqa: F401
from . import activation, common, conv, loss, norm, pooling  # noqa: F401

# attention lives in its own module (pallas-backed flash attention)
from .attention import scaled_dot_product_attention, flash_attention  # noqa: F401
