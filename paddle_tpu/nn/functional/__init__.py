"""paddle.nn.functional parity surface."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .sparse_attention import sparse_attention  # noqa: F401
from . import activation, common, conv, loss, norm, pooling  # noqa: F401

# attention lives in its own module (pallas-backed flash attention)
from .attention import scaled_dot_product_attention, flash_attention  # noqa: F401
from .extension import (gather_tree, temporal_shift,  # noqa: F401
                        sequence_mask, diag_embed, affine_grid,
                        grid_sample, hsigmoid_loss)

# reference-parity inplace aliases: functional purity makes true inplace
# meaningless on TPU; x_(...) returns the new value like the reference's
# return does

def elu_(x, alpha=1.0, name=None):
    return elu(x, alpha=alpha)


def softmax_(x, axis=-1, dtype=None, name=None):
    return softmax(x, axis=axis, dtype=dtype)
