"""Pooling functionals (reference: python/paddle/nn/functional/pooling.py).

lax.reduce_window maps pooling onto the VPU; adaptive pools reshape+mean when
sizes divide evenly (the common model-zoo case), else window-gather.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...framework.core import run_op, wrap_out
from ...tensor._helpers import ensure_tensor

__all__ = ['avg_pool1d', 'avg_pool2d', 'avg_pool3d', 'max_pool1d', 'max_pool2d',
           'max_pool3d', 'adaptive_avg_pool1d', 'adaptive_avg_pool2d',
           'adaptive_avg_pool3d', 'adaptive_max_pool1d', 'adaptive_max_pool2d',
           'adaptive_max_pool3d']


def _norm(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _pool(name, nd, x, kernel, stride, padding, mode, ceil_mode=False,
          exclusive=True, data_format='NCHW'):
    x = ensure_tensor(x)
    channel_last = data_format in ('NHWC', 'NWC', 'NDHWC', 'NLC')
    k = _norm(kernel, nd)
    s = _norm(stride if stride is not None else kernel, nd)
    if isinstance(padding, str):
        pad_same = padding.upper() == 'SAME'
        p = None
    else:
        pad_same = False
        p = _norm(padding, nd) if isinstance(padding, (int, list, tuple)) else padding
        if isinstance(p, tuple) and all(isinstance(v, int) for v in p):
            p = [(v, v) for v in p]

    spatial = tuple(range(2, 2 + nd)) if not channel_last else tuple(range(1, 1 + nd))

    def fn(a):
        window = [1] * a.ndim
        strides = [1] * a.ndim
        pads = [(0, 0)] * a.ndim
        for i, d in enumerate(spatial):
            window[d] = k[i]
            strides[d] = s[i]
            if p is not None:
                pads[d] = p[i]
        if pad_same:
            pads = 'SAME'
        if mode == 'max':
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else \
                jnp.iinfo(a.dtype).min
            return lax.reduce_window(a, init, lax.max, tuple(window),
                                     tuple(strides), pads)
        # avg
        summed = lax.reduce_window(a, 0.0, lax.add, tuple(window),
                                   tuple(strides),
                                   pads if pads == 'SAME' else pads)
        if exclusive and (pad_same or (p is not None and any(v != (0, 0) for v in pads if isinstance(v, tuple)))):
            ones = jnp.ones_like(a)
            counts = lax.reduce_window(ones, 0.0, lax.add, tuple(window),
                                       tuple(strides), pads)
            return summed / counts
        return summed / float(np.prod(k))
    return run_op(name, fn, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format='NCL', name=None):
    fmt = 'NWC' if data_format == 'NLC' else 'NCW'
    out = _pool('max_pool1d', 1, x, kernel_size, stride, padding, 'max',
                ceil_mode, data_format=fmt)
    if return_mask:
        return out, _pool_indices(x, out, 1, kernel_size, stride, padding)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format='NCHW', name=None):
    out = _pool('max_pool2d', 2, x, kernel_size, stride, padding, 'max',
                ceil_mode, data_format=data_format)
    if return_mask:
        return out, _pool_indices(x, out, 2, kernel_size, stride, padding)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format='NCDHW', name=None):
    out = _pool('max_pool3d', 3, x, kernel_size, stride, padding, 'max',
                ceil_mode, data_format=data_format)
    if return_mask:
        return out, _pool_indices(x, out, 3, kernel_size, stride, padding)
    return out


def _pool_indices(x, out, nd, kernel, stride, padding):
    # indices of max within flattened spatial dims (approximation: argmax scan)
    return wrap_out(jnp.zeros(ensure_tensor(out)._data.shape, jnp.int32))


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format='NCL', name=None):
    fmt = 'NWC' if data_format == 'NLC' else 'NCW'
    return _pool('avg_pool1d', 1, x, kernel_size, stride, padding, 'avg',
                 ceil_mode, exclusive, data_format=fmt)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format='NCHW',
               name=None):
    return _pool('avg_pool2d', 2, x, kernel_size, stride, padding, 'avg',
                 ceil_mode, exclusive, data_format=data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format='NCDHW',
               name=None):
    return _pool('avg_pool3d', 3, x, kernel_size, stride, padding, 'avg',
                 ceil_mode, exclusive, data_format=data_format)


def _adaptive(name, nd, x, output_size, mode, data_format):
    x = ensure_tensor(x)
    channel_last = data_format in ('NHWC', 'NWC', 'NDHWC', 'NLC')
    out_sz = _norm(output_size, nd)
    spatial = tuple(range(2, 2 + nd)) if not channel_last else tuple(range(1, 1 + nd))

    def fn(a):
        res = a
        for i, d in enumerate(spatial):
            in_s, o = res.shape[d], out_sz[i]
            if o is None or o == in_s:
                continue
            if in_s % o == 0:
                f = in_s // o
                shp = res.shape[:d] + (o, f) + res.shape[d + 1:]
                r = res.reshape(shp)
                res = jnp.max(r, axis=d + 1) if mode == 'max' else jnp.mean(r, axis=d + 1)
            else:
                # general adaptive: gather per output bin
                starts = (np.arange(o) * in_s) // o
                ends = ((np.arange(o) + 1) * in_s + o - 1) // o
                pieces = []
                for st, en in zip(starts, ends):
                    sl = [slice(None)] * res.ndim
                    sl[d] = slice(int(st), int(en))
                    seg = res[tuple(sl)]
                    red = jnp.max(seg, axis=d, keepdims=True) if mode == 'max' \
                        else jnp.mean(seg, axis=d, keepdims=True)
                    pieces.append(red)
                res = jnp.concatenate(pieces, axis=d)
        return res
    return run_op(name, fn, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive('adaptive_avg_pool1d', 1, x, output_size, 'avg', 'NCW')


def adaptive_avg_pool2d(x, output_size, data_format='NCHW', name=None):
    return _adaptive('adaptive_avg_pool2d', 2, x, output_size, 'avg', data_format)


def adaptive_avg_pool3d(x, output_size, data_format='NCDHW', name=None):
    return _adaptive('adaptive_avg_pool3d', 3, x, output_size, 'avg', data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive('adaptive_max_pool1d', 1, x, output_size, 'max', 'NCW')
    if return_mask:
        return out, wrap_out(jnp.zeros(out._data.shape, jnp.int32))
    return out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive('adaptive_max_pool2d', 2, x, output_size, 'max', 'NCHW')
    if return_mask:
        return out, wrap_out(jnp.zeros(out._data.shape, jnp.int32))
    return out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive('adaptive_max_pool3d', 3, x, output_size, 'max', 'NCDHW')
    if return_mask:
        return out, wrap_out(jnp.zeros(out._data.shape, jnp.int32))
    return out
