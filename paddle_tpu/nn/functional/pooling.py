"""Pooling functionals (reference: python/paddle/nn/functional/pooling.py).

lax.reduce_window maps pooling onto the VPU; adaptive pools reshape+mean when
sizes divide evenly (the common model-zoo case), else window-gather.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...framework.core import run_op, wrap_out
from ...tensor._helpers import ensure_tensor

__all__ = ['avg_pool1d', 'avg_pool2d', 'avg_pool3d', 'max_pool1d', 'max_pool2d',
           'max_pool3d', 'max_unpool2d', 'adaptive_avg_pool1d',
           'adaptive_avg_pool2d', 'adaptive_avg_pool3d', 'adaptive_max_pool1d',
           'adaptive_max_pool2d', 'adaptive_max_pool3d']


def _norm(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _pool(name, nd, x, kernel, stride, padding, mode, ceil_mode=False,
          exclusive=True, data_format='NCHW', divisor_override=None):
    if divisor_override is not None:
        if divisor_override <= 0:
            raise ValueError('divisor_override must be > 0, got %r'
                             % divisor_override)
        exclusive = False
    x = ensure_tensor(x)
    channel_last = data_format in ('NHWC', 'NWC', 'NDHWC', 'NLC')
    k = _norm(kernel, nd)
    s = _norm(stride if stride is not None else kernel, nd)
    if isinstance(padding, str):
        pad_same = padding.upper() == 'SAME'
        p = None
    else:
        pad_same = False
        p = _norm(padding, nd) if isinstance(padding, (int, list, tuple)) else padding
        if isinstance(p, tuple) and all(isinstance(v, int) for v in p):
            p = [(v, v) for v in p]

    spatial = tuple(range(2, 2 + nd)) if not channel_last else tuple(range(1, 1 + nd))

    def fn(a):
        window = [1] * a.ndim
        strides = [1] * a.ndim
        pads = [(0, 0)] * a.ndim
        for i, d in enumerate(spatial):
            window[d] = k[i]
            strides[d] = s[i]
            if p is not None:
                pads[d] = p[i]
        if pad_same:
            pads = 'SAME'
        if mode == 'max':
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else \
                jnp.iinfo(a.dtype).min
            return lax.reduce_window(a, init, lax.max, tuple(window),
                                     tuple(strides), pads)
        # avg
        summed = lax.reduce_window(a, 0.0, lax.add, tuple(window),
                                   tuple(strides),
                                   pads if pads == 'SAME' else pads)
        if exclusive and (pad_same or (p is not None and any(v != (0, 0) for v in pads if isinstance(v, tuple)))):
            ones = jnp.ones_like(a)
            counts = lax.reduce_window(ones, 0.0, lax.add, tuple(window),
                                       tuple(strides), pads)
            return summed / counts
        if divisor_override is not None:
            # reference: window SUM divided by the override instead of
            # the (padding-inclusive) window size
            return summed / float(divisor_override)
        return summed / float(np.prod(k))
    return run_op(name, fn, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format='NCL', name=None):
    fmt = 'NWC' if data_format == 'NLC' else 'NCW'
    out = _pool('max_pool1d', 1, x, kernel_size, stride, padding, 'max',
                ceil_mode, data_format=fmt)
    if return_mask:
        _check_mask_supported(fmt, 'NCW', padding)
        return out, _pool_indices(x, out, 1, kernel_size, stride, padding)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format='NCHW', name=None):
    out = _pool('max_pool2d', 2, x, kernel_size, stride, padding, 'max',
                ceil_mode, data_format=data_format)
    if return_mask:
        _check_mask_supported(data_format, 'NCHW', padding)
        return out, _pool_indices(x, out, 2, kernel_size, stride, padding)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format='NCDHW', name=None):
    out = _pool('max_pool3d', 3, x, kernel_size, stride, padding, 'max',
                ceil_mode, data_format=data_format)
    if return_mask:
        _check_mask_supported(data_format, 'NCDHW', padding)
        return out, _pool_indices(x, out, 3, kernel_size, stride, padding)
    return out


def _check_mask_supported(data_format, channel_first, padding):
    """return_mask needs channel-first layout and numeric padding (the
    reference raises the same way for non-NCHW; string padding would
    desync _pool_indices' window origin from the pooled values)."""
    if data_format != channel_first:
        raise ValueError('return_mask=True requires data_format=%r, got %r'
                         % (channel_first, data_format))
    if isinstance(padding, str):
        raise ValueError('return_mask=True requires numeric padding, '
                         'got %r' % padding)


def _pool_indices(x, out, nd, kernel, stride, padding):
    """Flat spatial index of each window's max (paddle return_mask contract:
    index into the flattened input spatial dims, per (N, C)).

    Enumerates the kernel offsets (small static product), slicing the
    padded input once per offset — XLA fuses the stack+argmax; no gather.
    """
    import itertools

    a = ensure_tensor(x)._data
    o = ensure_tensor(out)._data
    k = _norm(kernel, nd)
    s = _norm(stride if stride is not None else kernel, nd)
    p = _norm(padding if not isinstance(padding, str) else 0, nd)
    spatial = a.shape[2:]
    out_sp = o.shape[2:]
    neg = jnp.asarray(-jnp.inf, a.dtype) if jnp.issubdtype(a.dtype, jnp.floating) \
        else jnp.iinfo(a.dtype).min
    # pad enough that every window slice is in-bounds
    pad_cfg = [(0, 0), (0, 0)]
    for d in range(nd):
        need = (out_sp[d] - 1) * s[d] + k[d]
        pad_cfg.append((p[d], max(0, need - spatial[d] - p[d])))
    padded = jnp.pad(a, pad_cfg, constant_values=neg)

    strides_flat = []
    for d in range(nd):
        strides_flat.append(int(np.prod(spatial[d + 1:])) if d + 1 <= nd else 1)

    vals, idxs = [], []
    for off in itertools.product(*[range(kd) for kd in k]):
        sl = [slice(None), slice(None)]
        coord_flat = jnp.zeros((1, 1) + tuple(out_sp), jnp.int32)
        oob = jnp.zeros((1, 1) + tuple(out_sp), bool)
        for d in range(nd):
            sl.append(slice(off[d], off[d] + s[d] * out_sp[d], s[d]))
            coords = jnp.arange(out_sp[d], dtype=jnp.int32) * s[d] - p[d] + off[d]
            shape = [1] * (2 + nd)
            shape[2 + d] = out_sp[d]
            cd = coords.reshape(shape)
            coord_flat = coord_flat + cd * strides_flat[d]
            oob = oob | (cd < 0) | (cd >= spatial[d])
        vals.append(jnp.where(oob, neg, padded[tuple(sl)]))
        idxs.append(jnp.broadcast_to(coord_flat, vals[-1].shape))
    stacked = jnp.stack(vals)             # [K, N, C, *out_sp]
    which = jnp.argmax(stacked, axis=0)   # [N, C, *out_sp]
    flat = jnp.take_along_axis(jnp.stack(idxs), which[None], axis=0)[0]
    return wrap_out(flat.astype(jnp.int32))


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format='NCHW', output_size=None, name=None):
    """Inverse of max_pool2d(return_mask=True) (reference unpool op,
    paddle/fluid/operators/unpool_op.cc): scatters each pooled value back
    to the flat spatial index its window's max came from; the rest is 0."""
    if data_format != 'NCHW':
        raise ValueError('max_unpool2d supports NCHW only')
    xt = ensure_tensor(x)
    it = ensure_tensor(indices)
    k = _norm(kernel_size, 2)
    s = _norm(stride if stride is not None else kernel_size, 2)
    p = _norm(padding, 2)
    n, c, hin, win = xt.shape
    if output_size is None:
        hout = (hin - 1) * s[0] - 2 * p[0] + k[0]
        wout = (win - 1) * s[1] - 2 * p[1] + k[1]
    else:
        hout, wout = [int(v) for v in output_size[-2:]]

    def fn(a, idx):
        flat = jnp.zeros((n, c, hout * wout), a.dtype)
        bi = jnp.arange(n).reshape(n, 1, 1)
        ci = jnp.arange(c).reshape(1, c, 1)
        flat = flat.at[bi, ci, idx.reshape(n, c, -1)].set(a.reshape(n, c, -1))
        return flat.reshape(n, c, hout, wout)

    return run_op('max_unpool2d', fn, xt, it)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format='NCL', name=None):
    fmt = 'NWC' if data_format == 'NLC' else 'NCW'
    return _pool('avg_pool1d', 1, x, kernel_size, stride, padding, 'avg',
                 ceil_mode, exclusive, data_format=fmt)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format='NCHW',
               name=None):
    return _pool('avg_pool2d', 2, x, kernel_size, stride, padding, 'avg',
                 ceil_mode, exclusive, data_format=data_format,
                 divisor_override=divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format='NCDHW',
               name=None):
    return _pool('avg_pool3d', 3, x, kernel_size, stride, padding, 'avg',
                 ceil_mode, exclusive, data_format=data_format,
                 divisor_override=divisor_override)


def _adaptive(name, nd, x, output_size, mode, data_format):
    x = ensure_tensor(x)
    channel_last = data_format in ('NHWC', 'NWC', 'NDHWC', 'NLC')
    out_sz = _norm(output_size, nd)
    spatial = tuple(range(2, 2 + nd)) if not channel_last else tuple(range(1, 1 + nd))

    def fn(a):
        res = a
        for i, d in enumerate(spatial):
            in_s, o = res.shape[d], out_sz[i]
            if o is None or o == in_s:
                continue
            if in_s % o == 0:
                f = in_s // o
                shp = res.shape[:d] + (o, f) + res.shape[d + 1:]
                r = res.reshape(shp)
                res = jnp.max(r, axis=d + 1) if mode == 'max' else jnp.mean(r, axis=d + 1)
            else:
                # general adaptive: gather per output bin
                starts = (np.arange(o) * in_s) // o
                ends = ((np.arange(o) + 1) * in_s + o - 1) // o
                pieces = []
                for st, en in zip(starts, ends):
                    sl = [slice(None)] * res.ndim
                    sl[d] = slice(int(st), int(en))
                    seg = res[tuple(sl)]
                    red = jnp.max(seg, axis=d, keepdims=True) if mode == 'max' \
                        else jnp.mean(seg, axis=d, keepdims=True)
                    pieces.append(red)
                res = jnp.concatenate(pieces, axis=d)
        return res
    return run_op(name, fn, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive('adaptive_avg_pool1d', 1, x, output_size, 'avg', 'NCW')


def adaptive_avg_pool2d(x, output_size, data_format='NCHW', name=None):
    return _adaptive('adaptive_avg_pool2d', 2, x, output_size, 'avg', data_format)


def adaptive_avg_pool3d(x, output_size, data_format='NCDHW', name=None):
    return _adaptive('adaptive_avg_pool3d', 3, x, output_size, 'avg', data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive('adaptive_max_pool1d', 1, x, output_size, 'max', 'NCW')
    if return_mask:
        return out, wrap_out(jnp.zeros(out._data.shape, jnp.int32))
    return out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive('adaptive_max_pool2d', 2, x, output_size, 'max', 'NCHW')
    if return_mask:
        return out, wrap_out(jnp.zeros(out._data.shape, jnp.int32))
    return out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive('adaptive_max_pool3d', 3, x, output_size, 'max', 'NCDHW')
    if return_mask:
        return out, wrap_out(jnp.zeros(out._data.shape, jnp.int32))
    return out
