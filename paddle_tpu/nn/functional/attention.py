"""Attention functionals.

The jnp reference path always works (and XLA fuses it well); the Pallas flash
kernel (ops/flash_attention.py) kicks in on TPU for long sequences where HBM
traffic of the naive path dominates. Reference parity:
paddle incubate sparse_attention / nn.MultiHeadAttention core.
"""
import math
import os

import jax
import jax.numpy as jnp

from ...framework.core import run_op
from ...tensor._helpers import ensure_tensor


def _attn_impl():
    """PADDLE_TPU_ATTN_IMPL: auto (default) | flash | blockwise | quadratic.

    'auto' prefers the Pallas flash kernel when it can run, then blockwise
    (pure-XLA online softmax, ops/blockwise_attention.py) for sequences
    long enough that the quadratic path's [B,H,N,N] recompute dominates,
    then the quadratic + jax.checkpoint reference body.
    """
    return os.environ.get('PADDLE_TPU_ATTN_IMPL', 'auto')


def _blockwise_min_seq():
    return int(os.environ.get('PADDLE_TPU_BLOCKWISE_MIN_SEQ', 1024))


def _blockwise_block(seq_len):
    """Blockwise attention chunk size. The default (see
    ops.blockwise_attention.env_block_size) flows through _pick_block's
    graceful divisor shrink; an EXPLICITLY-set PADDLE_TPU_BLOCKWISE_BLOCK
    that cannot tile the q sequence (non-divisor, <= 0) would silently
    degrade to 1-row blocks - reject that loudly instead."""
    from ...ops.blockwise_attention import env_block_size
    blk = env_block_size()
    if 'PADDLE_TPU_BLOCKWISE_BLOCK' in os.environ:
        if blk <= 0:
            raise ValueError('PADDLE_TPU_BLOCKWISE_BLOCK must be '
                             'positive, got %d' % blk)
        if seq_len % min(blk, seq_len):
            raise ValueError(
                'PADDLE_TPU_BLOCKWISE_BLOCK=%d does not tile seq len %d '
                '(pick a divisor)' % (blk, seq_len))
    return blk


def _sdpa_ref(q, k, v, mask, dropout_p, causal, scale, drop_key=None):
    # q,k,v: [B, N, H, D] paddle layout
    qt = jnp.swapaxes(q, 1, 2)  # B,H,N,D
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    s = jnp.einsum('bhqd,bhkd->bhqk', qt, kt) * scale
    if causal:
        # bottom-right aligned (flash-attn convention): query i sits at
        # absolute position (m - n) + i, so KV-cache decode (n=1, m=T)
        # sees the whole cache. Top-left tril would mask it to key 0.
        n, m = s.shape[-2], s.shape[-1]
        if n > m:
            raise ValueError(
                'causal attention with more queries (%d) than keys (%d): '
                'the leading query rows would have no visible key' % (n, m))
        cm = jnp.tril(jnp.ones((n, m), bool), m - n)
        s = jnp.where(cm, s, -1e30)
    if mask is not None:
        s = s + mask
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p and drop_key is not None:
        keep = jax.random.bernoulli(drop_key, 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0).astype(q.dtype)
    o = jnp.einsum('bhqk,bhkd->bhqd', p, vt)
    return jnp.swapaxes(o, 1, 2)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Inputs [batch, seq, heads, head_dim] (paddle layout)."""
    q = ensure_tensor(query)
    k = ensure_tensor(key)
    v = ensure_tensor(value)
    scale = 1.0 / math.sqrt(q.shape[-1])
    if not training:
        # eval-mode dropout is a no-op; normalizing here keeps the
        # flash/blockwise fast paths eligible during inference
        dropout_p = 0.0

    # sequence-parallel routing: when the fleet strategy activated the sp
    # context, attention is the one op that mixes tokens across the
    # sequence shards — run it as ring/Ulysses over the 'sp' mesh axis
    try:
        from ...distributed.sp import sequence_parallel_state, sp_attention
        sp_state = sequence_parallel_state()
    except ImportError:
        sp_state = None
    if sp_state is not None and q._data.ndim == 4:
        if attn_mask is not None:
            raise ValueError('sequence-parallel attention supports causal/'
                             'full masks only (attn_mask must be None)')
        sp_drop_key = None
        if dropout_p and training:
            from ...framework import random as rng
            sp_drop_key = rng.next_key()

        def fn(qq, kk, vv):
            return sp_attention(qq, kk, vv, causal=is_causal, scale=scale,
                                state=sp_state,
                                dropout_p=dropout_p if sp_drop_key is not None
                                else 0.0,
                                dropout_key=sp_drop_key)
        return run_op('sp_attention', fn, q, k, v)

    impl = _attn_impl()
    use_flash = False
    if impl in ('auto', 'flash'):
        try:
            from ...ops import flash_attention as fa
            if q._data.ndim == 4 and q.shape[1] >= 512 and q.shape[-1] <= 256:
                use_flash = fa.is_available()
        except Exception:
            use_flash = False

    mask_arr = ensure_tensor(attn_mask)._data if attn_mask is not None else None

    if use_flash and mask_arr is None and dropout_p == 0.0:
        from ...ops import flash_attention as fa

        def fn(qq, kk, vv):
            return fa.flash_attention_bnhd(qq, kk, vv, causal=is_causal,
                                           scale=scale)
        return run_op('flash_attention', fn, q, k, v)

    use_blockwise = (impl == 'blockwise' or
                     (impl == 'auto' and q._data.ndim == 4 and
                      q.shape[1] >= _blockwise_min_seq()))
    if use_blockwise and q._data.ndim == 4 and mask_arr is None and \
            dropout_p == 0.0:
        from ...ops import blockwise_attention as bw
        # smaller blocks widen the causal-skip window (tq = N/block must
        # be > 1 for any future block to exist); tunable for benchmarking
        blk = _blockwise_block(int(q.shape[1]))

        def fn(qq, kk, vv):
            return bw.blockwise_attention(qq, kk, vv, causal=is_causal,
                                          scale=scale, block_q=blk,
                                          block_k=blk)
        return run_op('blockwise_attention', fn, q, k, v)

    # attention-prob dropout rides the framework RNG stream (same
    # convention as F.dropout: key drawn outside the pure fn); the remat
    # recompute reuses the key, so backward sees the same mask
    drop_key = None
    if dropout_p and training:
        from ...framework import random as rng
        drop_key = rng.next_key()

    # remat the quadratic body: backward recomputes the [B,H,N,N] scores
    # and probabilities from q/k/v instead of keeping them resident —
    # the flash-attention memory shape, in pure XLA (kicks in whenever
    # the Pallas kernel doesn't; ~1/3 extra attention flops, which are a
    # small slice of a transformer step)
    @jax.checkpoint
    def fn(qq, kk, vv):
        return _sdpa_ref(qq, kk, vv, mask_arr, dropout_p, is_causal, scale,
                         drop_key)
    return run_op('sdpa', fn, q, k, v)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, name=None):
    out = scaled_dot_product_attention(query, key, value, dropout_p=dropout,
                                       is_causal=causal)
    if return_softmax:
        return out, None
    return out, None
