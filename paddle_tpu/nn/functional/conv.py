"""Convolution functionals (reference: python/paddle/nn/functional/conv.py).

TPU-native: all convs lower to lax.conv_general_dilated, which XLA tiles onto
the MXU. NCHW (paddle default) and NHWC both supported; weights stay OIHW.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...framework.core import run_op
from ...tensor._helpers import ensure_tensor

__all__ = ['conv1d', 'conv2d', 'conv3d', 'conv1d_transpose', 'conv2d_transpose',
           'conv3d_transpose']


def _norm_tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _padding_arg(padding, n, strides=None):
    """paddle padding: int, list, pairs, or 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, (int, np.integer)) for p in padding):
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    return [tuple(int(x) for x in p) for p in padding]


def _dimnums(nd, channel_last):
    if nd == 1:
        return ('NWC', 'WIO', 'NWC') if channel_last else ('NCW', 'OIW', 'NCW')
    if nd == 2:
        return ('NHWC', 'HWIO', 'NHWC') if channel_last else ('NCHW', 'OIHW', 'NCHW')
    return ('NDHWC', 'DHWIO', 'NDHWC') if channel_last else ('NCDHW', 'OIDHW', 'NCDHW')


def _conv(name, nd, x, weight, bias, stride, padding, dilation, groups,
          data_format):
    x = ensure_tensor(x)
    w = ensure_tensor(weight)
    channel_last = data_format in ('NHWC', 'NWC', 'NDHWC', 'NLC')
    stride = _norm_tuple(stride, nd)
    dilation = _norm_tuple(dilation, nd)
    pad = _padding_arg(padding, nd)
    lhs_spec, rhs_spec, out_spec = _dimnums(nd, channel_last)
    dn = lax.conv_dimension_numbers((1,) * (nd + 2), (1,) * (nd + 2),
                                    (lhs_spec, rhs_spec, out_spec))

    def fn(a, ww, *maybe_b):
        if channel_last:
            # paddle weights are OIHW regardless of data layout; transpose to HWIO
            perm = tuple(range(2, 2 + nd)) + (1, 0)
            ww = jnp.transpose(ww, perm)
        # NOTE: no preferred_element_type here. The TPU MXU accumulates conv
        # in f32 regardless of operand dtype, and a bf16 output rounds once
        # either way — while an f32 output + astype(bf16) breaks the VJP (the
        # astype's cotangent arrives f32 at the bf16 conv transpose).
        out = lax.conv_general_dilated(
            a, ww, window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups)
        if maybe_b:
            b = maybe_b[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = -1
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return run_op(name, fn, x, w, ensure_tensor(bias))
    return run_op(name, fn, x, w)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCL', name=None):
    fmt = 'NWC' if data_format in ('NLC',) else 'NCW'
    return _conv('conv1d', 1, x, weight, bias, stride, padding, dilation,
                 groups, fmt)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCHW', name=None):
    return _conv('conv2d', 2, x, weight, bias, stride, padding, dilation,
                 groups, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCDHW', name=None):
    return _conv('conv3d', 3, x, weight, bias, stride, padding, dilation,
                 groups, data_format)


def _conv_transpose(name, nd, x, weight, bias, stride, padding, output_padding,
                    dilation, groups, data_format, output_size=None):
    x = ensure_tensor(x)
    w = ensure_tensor(weight)
    channel_last = data_format in ('NHWC', 'NWC', 'NDHWC', 'NLC')
    stride = _norm_tuple(stride, nd)
    dilation = _norm_tuple(dilation, nd)
    pad = _padding_arg(padding, nd)
    out_pad = _norm_tuple(output_padding, nd) if output_padding is not None else (0,) * nd
    if output_size is not None:
        # reference semantics: output_size overrides output_padding by
        # out_pad_d = output_size_d - ((in_d-1)*s - p0 - p1 + d*(k-1) + 1)
        want = [int(v) for v in (output_size if not isinstance(
            output_size, int) else (output_size,) * nd)][-nd:]
        spatial = x.shape[1:1 + nd] if channel_last else x.shape[2:2 + nd]
        k_sp = w.shape[2:2 + nd]
        if isinstance(pad, str):
            raise ValueError('output_size with string padding is not '
                             'supported — pass numeric padding')
        base = [(si - 1) * st - p0 - p1 + dl * (kk - 1) + 1
                for si, st, (p0, p1), dl, kk in zip(spatial, stride, pad,
                                                    dilation, k_sp)]
        out_pad = tuple(w_ - b_ for w_, b_ in zip(want, base))
        for op_, st in zip(out_pad, stride):
            if not 0 <= op_ < max(st, 1):
                raise ValueError(
                    'requested output_size %r unreachable: derived '
                    'output_padding %r must lie in [0, stride)' %
                    (want, out_pad))

    lhs_spec, rhs_spec, out_spec = _dimnums(nd, channel_last)
    dn = lax.conv_dimension_numbers((1,) * (nd + 2), (1,) * (nd + 2),
                                    (lhs_spec, rhs_spec, out_spec))

    def fn(a, ww, *maybe_b):
        # paddle transpose-conv weight layout: (in, out/groups, *k) -> use
        # conv_general_dilated with lhs_dilation (fractional stride)
        k = ww.shape[2:]
        if isinstance(pad, str):
            pads = [(0, 0)] * nd if pad == 'VALID' else None
        else:
            pads = pad
        # flip kernel and swap I/O for transpose conv
        wf = jnp.flip(ww, axis=tuple(range(2, 2 + nd)))
        if groups > 1:
            ci = wf.shape[0]
            co_g = wf.shape[1]
            wf = wf.reshape((groups, ci // groups) + wf.shape[1:])
            wf = jnp.swapaxes(wf, 1, 2)
            wf = wf.reshape((groups * co_g, ci // groups) + k)
        else:
            wf = jnp.swapaxes(wf, 0, 1)
        if channel_last:
            perm = tuple(range(2, 2 + nd)) + (1, 0)
            wf = jnp.transpose(wf, perm)
        if pads is None:
            # SAME: compute from shapes
            tp = [(d * (kk - 1) // 2, d * (kk - 1) - d * (kk - 1) // 2)
                  for kk, d in zip(k, dilation)]
        else:
            tp = [(d * (kk - 1) - p0, d * (kk - 1) - p1 + op)
                  for kk, (p0, p1), d, op in zip(k, pads, dilation, out_pad)]
        out = lax.conv_general_dilated(
            a, wf, window_strides=(1,) * nd, padding=tp,
            lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups)
        if maybe_b:
            b = maybe_b[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = -1
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return run_op(name, fn, x, w, ensure_tensor(bias))
    return run_op(name, fn, x, w)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format='NCL', name=None):
    fmt = 'NWC' if data_format in ('NLC',) else 'NCW'
    return _conv_transpose('conv1d_transpose', 1, x, weight, bias, stride,
                           padding, output_padding, dilation, groups, fmt,
                           output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format='NCHW', name=None):
    return _conv_transpose('conv2d_transpose', 2, x, weight, bias, stride,
                           padding, output_padding, dilation, groups,
                           data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format='NCDHW', name=None):
    return _conv_transpose('conv3d_transpose', 3, x, weight, bias, stride,
                           padding, output_padding, dilation, groups,
                           data_format, output_size)
