"""Normalization functionals (reference: python/paddle/nn/functional/norm.py).

batch_norm keeps running stats as mutable buffers on the Layer (paddle
semantics); under a functional trace the stats updates flow back through the
state pytree (framework/functional.py treats buffers as carried state).
"""
import jax
import jax.numpy as jnp

from ...framework.core import Tensor, run_op
from ...tensor._helpers import ensure_tensor

__all__ = ['batch_norm', 'layer_norm', 'instance_norm', 'group_norm',
           'local_response_norm', 'normalize']


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format='NCHW', use_global_stats=None, name=None):
    x = ensure_tensor(x)
    channel_last = data_format in ('NHWC', 'NLC', 'NWC', 'NDHWC')
    ch_axis = x.ndim - 1 if channel_last else (1 if x.ndim > 1 else 0)
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    shape = [1] * x.ndim
    shape[ch_axis] = -1

    use_stats = (not training) if use_global_stats is None else use_global_stats

    rm = ensure_tensor(running_mean)
    rv = ensure_tensor(running_var)
    rm_a, rv_a = rm._data, rv._data
    has_w = weight is not None
    has_b = bias is not None

    def fn(a, *wb):
        # stats computed INSIDE the vjp'd fn so eager backward differentiates
        # through them (true BN backward, not the frozen-stats approximation)
        if use_stats:
            m_flat, v_flat = rm_a, rv_a
        else:
            m_flat = jnp.mean(a, axis=reduce_axes)
            v_flat = jnp.var(a, axis=reduce_axes)
        m = m_flat.reshape(shape)
        v = v_flat.reshape(shape)
        out = (a - m) * jax.lax.rsqrt(v + epsilon)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out, m_flat, v_flat

    args = [x]
    if has_w:
        args.append(ensure_tensor(weight))
    if has_b:
        args.append(ensure_tensor(bias))
    out, batch_mean, batch_var = run_op('batch_norm', fn, *args)
    if not use_stats:
        # momentum update of running stats (reference: batch_norm_op); under
        # a functional trace these land in the harvested buffer outputs
        rm.set_value(momentum * rm_a + (1 - momentum) * batch_mean._data)
        rv.set_value(momentum * rv_a + (1 - momentum) * batch_var._data)
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    x = ensure_tensor(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    nd = len(tuple(normalized_shape))
    axes = tuple(range(x.ndim - nd, x.ndim))

    has_w = weight is not None
    has_b = bias is not None

    def fn(a, *wb):
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + epsilon)
        i = 0
        if has_w:
            out = out * wb[i]
            i += 1
        if has_b:
            out = out + wb[i]
        return out

    args = [x]
    if has_w:
        args.append(ensure_tensor(weight))
    if has_b:
        args.append(ensure_tensor(bias))
    return run_op('layer_norm', fn, *args)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format='NCHW', name=None):
    x = ensure_tensor(x)
    channel_last = data_format in ('NHWC', 'NLC', 'NWC', 'NDHWC')
    ch_axis = x.ndim - 1 if channel_last else 1
    spatial = tuple(i for i in range(2, x.ndim)) if not channel_last else \
        tuple(i for i in range(1, x.ndim - 1))
    shape = [1] * x.ndim
    shape[ch_axis] = -1

    has_w = weight is not None
    has_b = bias is not None
    if not use_input_stats and (running_mean is None or running_var is None):
        raise ValueError('use_input_stats=False requires running_mean and '
                         'running_var')
    rm = ensure_tensor(running_mean)._data if not use_input_stats else None
    rv = ensure_tensor(running_var)._data if not use_input_stats else None

    def fn(a, *wb):
        if use_input_stats:
            m = jnp.mean(a, axis=spatial, keepdims=True)
            v = jnp.var(a, axis=spatial, keepdims=True)
        else:
            m = rm.reshape(shape)
            v = rv.reshape(shape)
        out = (a - m) * jax.lax.rsqrt(v + eps)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out

    args = [x]
    if has_w:
        args.append(ensure_tensor(weight))
    if has_b:
        args.append(ensure_tensor(bias))
    return run_op('instance_norm', fn, *args)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format='NCHW', name=None):
    x = ensure_tensor(x)
    channel_last = data_format in ('NHWC', 'NLC', 'NWC', 'NDHWC')
    ch_axis = x.ndim - 1 if channel_last else 1
    shape = [1] * x.ndim
    shape[ch_axis] = -1
    has_w = weight is not None
    has_b = bias is not None

    def fn(a, *wb):
        if channel_last:
            a_t = jnp.moveaxis(a, -1, 1)
        else:
            a_t = a
        n, c = a_t.shape[0], a_t.shape[1]
        g = a_t.reshape((n, num_groups, c // num_groups) + a_t.shape[2:])
        axes = tuple(range(2, g.ndim))
        m = jnp.mean(g, axis=axes, keepdims=True)
        v = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - m) * jax.lax.rsqrt(v + epsilon)).reshape(a_t.shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out

    args = [x]
    if has_w:
        args.append(ensure_tensor(weight))
    if has_b:
        args.append(ensure_tensor(bias))
    return run_op('group_norm', fn, *args)


def local_response_norm(x, size, alpha=0.0001, beta=0.75, k=1.0,
                        data_format='NCHW', name=None):
    x = ensure_tensor(x)
    channel_last = data_format in ('NHWC', 'NLC', 'NWC', 'NDHWC')

    def fn(a):
        ch = a.ndim - 1 if channel_last else 1
        sq = jnp.square(a)
        half = size // 2
        pads = [(0, 0)] * a.ndim
        pads[ch] = (half, size - half - 1)
        sq_p = jnp.pad(sq, pads)
        window = [1] * a.ndim
        window[ch] = size
        s = jax.lax.reduce_window(sq_p, 0.0, jax.lax.add, tuple(window),
                                  (1,) * a.ndim, 'VALID')
        div = jnp.power(k + alpha * s, beta)
        return a / div
    return run_op('local_response_norm', fn, x)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    x = ensure_tensor(x)

    def fn(a):
        if p == 2:
            n = jnp.sqrt(jnp.sum(jnp.square(a), axis=axis, keepdims=True))
        else:
            n = jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=axis,
                                  keepdims=True), 1.0 / p)
        return a / jnp.maximum(n, epsilon)
    return run_op('normalize', fn, x)
