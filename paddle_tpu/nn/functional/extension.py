"""Extension functionals (reference: python/paddle/nn/functional/
extension.py + vision.py — sequence_mask, diag_embed, affine_grid,
grid_sample, hsigmoid_loss)."""
import numpy as np
import jax
import jax.numpy as jnp

from ...framework.core import run_op
from ...tensor._helpers import ensure_tensor

__all__ = ['sequence_mask', 'diag_embed', 'affine_grid', 'grid_sample',
           'hsigmoid_loss', 'gather_tree', 'temporal_shift']


def gather_tree(ids, parents):
    """Beam-search backtrace (reference gather_tree op,
    paddle/fluid/operators/gather_tree_op.cc): walk parent pointers from
    the last step back, re-linking each beam's token ids into full paths.
    ids/parents: [max_time, batch, beam_width] int."""
    ids_t = ensure_tensor(ids)
    par_t = ensure_tensor(parents)

    def fn(idv, parv):
        max_time, batch, beam = idv.shape
        bidx = jnp.arange(batch)[:, None]

        def step(carry, xs):
            beam_sel = carry                 # [batch, beam] parent slot
            idv_t, parv_t = xs               # this timestep, walking backward
            tok = idv_t[bidx, beam_sel]      # [batch, beam]
            nxt = parv_t[bidx, beam_sel]
            return nxt, tok

        init = jnp.broadcast_to(jnp.arange(beam, dtype=parv.dtype),
                                (batch, beam))
        # time-reversed scan: seed with each final beam slot, follow parents
        _, toks = jax.lax.scan(step, init, (idv[::-1], parv[::-1]))
        return toks[::-1]

    return run_op('gather_tree', fn, ids_t, par_t)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format='NCHW',
                   name=None):
    """TSM temporal shift (reference temporal_shift_op.cc): fold the batch
    into (N//seg_num, seg_num) segments and shift the first `shift_ratio`
    of channels one step back in time, the second forward, rest unchanged."""
    if data_format != 'NCHW':
        raise ValueError('temporal_shift supports NCHW only')
    xt = ensure_tensor(x)
    nt, c, h, w = xt.shape
    if nt % seg_num:
        raise ValueError('batch %d not divisible by seg_num %d'
                         % (nt, seg_num))
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)

    def fn(a):
        v = a.reshape(nt // seg_num, seg_num, c, h, w)
        # reference temporal_shift_op.h: first c1 channels read x[t-1]
        # (shift forward in time), next c1..c2 read x[t+1] (shift back)
        from_past = jnp.concatenate(
            [jnp.zeros_like(v[:, :1, :c1]), v[:, :-1, :c1]], axis=1)
        from_future = jnp.concatenate(
            [v[:, 1:, c1:c2], jnp.zeros_like(v[:, :1, c1:c2])], axis=1)
        out = jnp.concatenate([from_past, from_future, v[:, :, c2:]], axis=2)
        return out.reshape(nt, c, h, w)

    return run_op('temporal_shift', fn, xt)


def sequence_mask(x, maxlen=None, dtype='int64', name=None):
    """lengths [...,] -> mask [..., maxlen] (operators/sequence_ops/
    sequence_mask_op; the one sequence op kept — ragged-via-mask is the
    TPU answer to LoD, SURVEY §7.5)."""
    t = ensure_tensor(x)
    n = int(maxlen) if maxlen is not None else None

    def fn(lengths):
        m = n if n is not None else int(jnp.max(lengths))
        rng = jnp.arange(m, dtype=lengths.dtype)
        from ...framework.dtype import to_jax_dtype
        return (rng < lengths[..., None]).astype(to_jax_dtype(dtype))
    return run_op('sequence_mask', fn, t)


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """Last dim -> diagonal of a new matrix pair of dims (reference
    diag_embed op)."""
    t = ensure_tensor(input)

    def fn(a):
        n = a.shape[-1] + abs(offset)
        base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = base.at[..., r, c].set(a)
        nd = out.ndim
        d1 = dim1 % nd
        d2 = dim2 % nd
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        # place the two new axes at dim1/dim2
        order = []
        src = {d1: nd - 2, d2: nd - 1}
        it = iter(perm)
        for i in range(nd):
            order.append(src[i] if i in src else next(it))
        return jnp.transpose(out, order)
    return run_op('diag_embed', fn, t)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta [N, 2, 3] -> sampling grid [N, H, W, 2] (affine_grid_op)."""
    t = ensure_tensor(theta)
    if hasattr(out_shape, 'numpy'):
        out_shape = [int(v) for v in np.asarray(out_shape.numpy())]
    n, c, h, w = [int(v) for v in out_shape]

    def fn(th):
        if align_corners:
            xs = jnp.linspace(-1.0, 1.0, w)
            ys = jnp.linspace(-1.0, 1.0, h)
        else:
            xs = (jnp.arange(w) * 2 + 1) / w - 1.0
            ys = (jnp.arange(h) * 2 + 1) / h - 1.0
        gx, gy = jnp.meshgrid(xs, ys)                 # [H, W]
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)     # [H, W, 3]
        return jnp.einsum('hwk,njk->nhwj', base.astype(th.dtype), th)
    return run_op('affine_grid', fn, t)


def grid_sample(x, grid, mode='bilinear', padding_mode='zeros',
                align_corners=True, name=None):
    """Bilinear/nearest sampling of x [N,C,H,W] at grid [N,Hg,Wg,2]
    (normalized xy in [-1,1]; grid_sampler_op)."""
    xt = ensure_tensor(x)
    gt = ensure_tensor(grid)

    def fn(img, g):
        n, c, h, w = img.shape

        def unnorm(coord, size):
            if align_corners:
                return (coord + 1.0) / 2.0 * (size - 1)
            return ((coord + 1.0) * size - 1.0) / 2.0

        fx = unnorm(g[..., 0], w)                     # [N, Hg, Wg]
        fy = unnorm(g[..., 1], h)

        def sample(ix, iy):
            inb = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
            if padding_mode == 'border':
                ixc = jnp.clip(ix, 0, w - 1)
                iyc = jnp.clip(iy, 0, h - 1)
                inb = jnp.ones_like(inb)
            else:  # zeros
                ixc = jnp.clip(ix, 0, w - 1)
                iyc = jnp.clip(iy, 0, h - 1)
            vals = img[jnp.arange(n)[:, None, None], :,
                       iyc, ixc]                      # [N, Hg, Wg, C]
            return vals * inb[..., None].astype(img.dtype)

        if mode == 'nearest':
            out = sample(jnp.round(fx).astype(jnp.int32),
                         jnp.round(fy).astype(jnp.int32))
        else:
            x0 = jnp.floor(fx).astype(jnp.int32)
            y0 = jnp.floor(fy).astype(jnp.int32)
            x1, y1 = x0 + 1, y0 + 1
            wx = (fx - x0).astype(img.dtype)[..., None]
            wy = (fy - y0).astype(img.dtype)[..., None]
            out = (sample(x0, y0) * (1 - wx) * (1 - wy) +
                   sample(x1, y0) * wx * (1 - wy) +
                   sample(x0, y1) * (1 - wx) * wy +
                   sample(x1, y1) * wx * wy)
        return jnp.moveaxis(out, -1, 1)               # [N, C, Hg, Wg]
    return run_op('grid_sample', fn, xt, gt)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (hierarchical_sigmoid_op): default
    complete binary tree over num_classes; custom trees via
    path_table/path_code [N, L] (padded with -1)."""
    xt = ensure_tensor(input)
    lt = ensure_tensor(label)
    wt = ensure_tensor(weight)
    args = [xt, lt, wt]
    if bias is not None:
        args.append(ensure_tensor(bias))

    # default complete-tree paths (host-built, static in num_classes)
    if path_table is None:
        depth = max(int(np.ceil(np.log2(max(num_classes, 2)))), 1)
        tables = np.full((num_classes, depth), -1, np.int64)
        codes = np.full((num_classes, depth), -1, np.int64)
        for cls in range(num_classes):
            # leaf index in a complete tree; internal nodes numbered from 1
            node = cls + num_classes  # leaves occupy [num_classes, 2N)
            path = []
            while node > 1:
                parent = node // 2
                path.append((parent - 1, node % 2))
                node = parent
            for li, (nid, code) in enumerate(reversed(path)):
                if li < depth:
                    tables[cls, li] = nid
                    codes[cls, li] = code
        path_table_np, path_code_np = tables, codes
    else:
        path_table_np = np.asarray(path_table.numpy()
                                   if hasattr(path_table, 'numpy')
                                   else path_table, np.int64)
        path_code_np = np.asarray(path_code.numpy()
                                  if hasattr(path_code, 'numpy')
                                  else path_code, np.int64)

    def fn(x, lab, w, *maybe_bias):
        tables = jnp.asarray(path_table_np)
        codes = jnp.asarray(path_code_np)
        lab_flat = lab.reshape(-1).astype(jnp.int32)
        t = tables[lab_flat]                     # [N, L]
        cde = codes[lab_flat].astype(x.dtype)    # [N, L]
        valid = (t >= 0)
        t_safe = jnp.clip(t, 0, w.shape[0] - 1)
        wrows = w[t_safe]                        # [N, L, D]
        logits = jnp.einsum('nd,nld->nl', x.astype(w.dtype), wrows)
        if maybe_bias:
            logits = logits + maybe_bias[0].reshape(-1)[t_safe]
        # code 1 => sigmoid(logit), code 0 => 1 - sigmoid(logit)
        zls = jnp.maximum(logits, 0) - logits * cde + \
            jnp.log1p(jnp.exp(-jnp.abs(logits)))
        loss = jnp.sum(jnp.where(valid, zls, 0.0), axis=1)
        return jnp.mean(loss)[None]
    return run_op('hsigmoid_loss', fn, *args)
