"""Loss functionals (reference: python/paddle/nn/functional/loss.py)."""
import functools

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, run_op
from ...tensor._helpers import ensure_tensor


# -- memory-lean fused softmax cross-entropy ---------------------------------
#
# The naive log_softmax + take_along_axis path saves an f32 [N, V] logp
# residual for backward — 2GB/step on the bench config (vocab 30k). This
# custom_vjp saves only the (bf16) logits and recomputes softmax in the
# backward, cutting the dominant HBM term of LM training; the grad is the
# classic softmax(logits) - onehot(label).

@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _ce_with_logits(logits, label, ignore_index):
    return _ce_value(logits, label, ignore_index)


def _ce_value(logits, label, ignore_index):
    af = logits.astype(jnp.float32)
    m = jax.lax.stop_gradient(af.max(axis=-1))
    lse = m + jnp.log(jnp.sum(jnp.exp(af - m[..., None]), axis=-1))
    picked = jnp.take_along_axis(af, label[..., None], axis=-1)[..., 0]
    return jnp.where(label != ignore_index, lse - picked, 0.0)


def _ce_fwd(logits, label, ignore_index):
    return _ce_value(logits, label, ignore_index), (logits, label)


def _ce_bwd(ignore_index, res, g):
    logits, label = res
    p = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    onehot = (jnp.arange(logits.shape[-1], dtype=label.dtype)
              == label[..., None])
    valid = (label != ignore_index)
    grad = (p - onehot) * (g * valid)[..., None]
    return grad.astype(logits.dtype), jnp.zeros(label.shape,
                                                jax.dtypes.float0)


_ce_with_logits.defvjp(_ce_fwd, _ce_bwd)

__all__ = [
    'cross_entropy', 'linear_cross_entropy',
    'softmax_with_cross_entropy', 'binary_cross_entropy',
    'binary_cross_entropy_with_logits', 'nll_loss', 'mse_loss', 'l1_loss',
    'smooth_l1_loss', 'kl_div', 'margin_ranking_loss', 'hinge_embedding_loss',
    'cosine_embedding_loss', 'ctc_loss', 'log_loss', 'square_error_cost',
    'triplet_margin_loss', 'sigmoid_focal_loss', 'dice_loss',
    'npair_loss', 'multi_label_soft_margin_loss', 'soft_margin_loss',
]


def _reduce(out, reduction):
    if reduction == 'mean':
        return jnp.mean(out)
    if reduction == 'sum':
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction='mean', soft_label=False, axis=-1,
                  use_softmax=True, name=None):
    x = ensure_tensor(input)
    l = ensure_tensor(label)
    w = ensure_tensor(weight) if weight is not None else None

    if soft_label:
        def fn(a, lab, *mw):
            logp = jax.nn.log_softmax(a, axis=axis) if use_softmax else jnp.log(a)
            out = -jnp.sum(lab * logp, axis=axis)
            return _reduce(out, reduction)
        return run_op('cross_entropy', fn, x, l, *( [w] if w is not None else []))

    lab = l._data
    if lab.ndim == x.ndim and lab.shape[axis] == 1:
        lab = jnp.squeeze(lab, axis=axis)
    lab = lab.astype(jnp.int32)

    def fn(a, *mw):
        if use_softmax and axis in (-1, a.ndim - 1):
            # f32 internal math; output dtype matches the slow path
            out = _ce_with_logits(a, lab, ignore_index).astype(a.dtype)
            valid = (lab != ignore_index)
        else:
            logp = jax.nn.log_softmax(a, axis=axis) if use_softmax \
                else jnp.log(a)
            picked = jnp.take_along_axis(logp, jnp.expand_dims(lab, axis),
                                         axis=axis)
            out = -jnp.squeeze(picked, axis=axis)
            valid = (lab != ignore_index)
            out = jnp.where(valid, out, 0.0)
        if mw:
            cw = jnp.take(mw[0], jnp.clip(lab, 0, mw[0].shape[0] - 1))
            out = out * cw
            if reduction == 'mean':
                denom = jnp.sum(jnp.where(valid, cw, 0.0))
                return jnp.sum(out) / jnp.maximum(denom, 1e-12)
        if reduction == 'mean':
            denom = jnp.maximum(jnp.sum(valid.astype(a.dtype)), 1.0)
            return jnp.sum(out) / denom
        return _reduce(out, reduction)
    return run_op('cross_entropy', fn, x, *([w] if w is not None else []))


def linear_cross_entropy(input, weight, label, bias=None, ignore_index=-100,
                         transpose_weight=False, chunk_rows=None, name=None):
    """Fused linear head + mean softmax cross-entropy (hard labels).

    Computes ``cross_entropy(input @ weight + bias, label)`` without ever
    materializing the [rows, vocab] logits — the memory-optimal LM loss
    for large vocabularies (see ops/fused_ce.py for the algorithm and
    the reference counterparts it replaces). Beyond-reference op: the
    reference's analog is the vocab-parallel
    c_softmax_with_cross_entropy (operators/collective/); this is the
    single-chip fused form.

    input: [..., d] activations (leading dims are flattened to rows).
    weight: [d, vocab], or [vocab, d] with transpose_weight=True (the
        tied-embedding layout; the transpose folds into the matmuls).
    label: integer tensor matching input's leading dims.
    Returns a scalar: mean CE over rows whose label != ignore_index.
    """
    from ...ops import fused_ce as _fce
    x = ensure_tensor(input)
    l = ensure_tensor(label)
    wt = ensure_tensor(weight)
    bt = ensure_tensor(bias) if bias is not None else None
    d = x.shape[-1]
    chunk = chunk_rows if chunk_rows is not None else _fce.env_chunk_rows()

    lab = l._data.reshape(-1).astype(jnp.int32)

    def fn(a, warr, *rest):
        x2 = a.reshape(-1, d)
        wmat = warr.T if transpose_weight else warr
        barr = rest[0] if rest else None
        return _fce.linear_cross_entropy_arrays(
            x2, wmat, lab, barr, int(ignore_index), int(chunk))

    args = [x, wt] + ([bt] if bt is not None else [])
    return run_op('linear_cross_entropy', fn, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction='none', axis=axis)
    from .activation import softmax as softmax_fn
    from ...tensor.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, softmax_fn(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction='mean',
                         name=None):
    x, l = ensure_tensor(input), ensure_tensor(label)

    def fn(a, lab, *mw):
        a = jnp.clip(a, 1e-12, 1.0 - 1e-7)
        out = -(lab * jnp.log(a) + (1 - lab) * jnp.log(1 - a))
        if mw:
            out = out * mw[0]
        return _reduce(out, reduction)
    args = [x, l] + ([ensure_tensor(weight)] if weight is not None else [])
    return run_op('bce', fn, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction='mean', pos_weight=None,
                                     name=None):
    x, l = ensure_tensor(logit), ensure_tensor(label)
    pw = ensure_tensor(pos_weight) if pos_weight is not None else None

    def fn(a, lab, *rest):
        maxv = jnp.maximum(-a, 0.0)
        if pw is not None:
            log_w = (pw._data - 1.0) * lab + 1.0
            out = (1 - lab) * a + log_w * (jnp.log1p(jnp.exp(-jnp.abs(a))) + maxv)
        else:
            out = (1 - lab) * a + jnp.log1p(jnp.exp(-jnp.abs(a))) + maxv
        if rest:
            out = out * rest[0]
        return _reduce(out, reduction)
    args = [x, l] + ([ensure_tensor(weight)] if weight is not None else [])
    return run_op('bce_logits', fn, *args)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction='mean',
             name=None):
    x, l = ensure_tensor(input), ensure_tensor(label)
    lab = l._data.astype(jnp.int32)

    def fn(a, *mw):
        picked = jnp.take_along_axis(a, jnp.expand_dims(lab, 1), axis=1)
        out = -jnp.squeeze(picked, axis=1)
        valid = (lab != ignore_index)
        out = jnp.where(valid, out, 0.0)
        if mw:
            cw = jnp.take(mw[0], jnp.clip(lab, 0, mw[0].shape[0] - 1))
            out = out * cw
            if reduction == 'mean':
                return jnp.sum(out) / jnp.maximum(
                    jnp.sum(jnp.where(valid, cw, 0.0)), 1e-12)
        if reduction == 'mean':
            return jnp.sum(out) / jnp.maximum(jnp.sum(valid.astype(a.dtype)), 1.0)
        return _reduce(out, reduction)
    return run_op('nll_loss', fn, x, *([ensure_tensor(weight)]
                                       if weight is not None else []))


def mse_loss(input, label, reduction='mean', name=None):
    return run_op('mse_loss',
                  lambda a, b: _reduce(jnp.square(a - b), reduction),
                  ensure_tensor(input), ensure_tensor(label))


def square_error_cost(input, label):
    return run_op('square_error_cost', lambda a, b: jnp.square(a - b),
                  ensure_tensor(input), ensure_tensor(label))


def l1_loss(input, label, reduction='mean', name=None):
    return run_op('l1_loss',
                  lambda a, b: _reduce(jnp.abs(a - b), reduction),
                  ensure_tensor(input), ensure_tensor(label))


def smooth_l1_loss(input, label, reduction='mean', delta=1.0, name=None):
    def fn(a, b):
        d = a - b
        absd = jnp.abs(d)
        out = jnp.where(absd < delta, 0.5 * d * d / delta, absd - 0.5 * delta)
        return _reduce(out, reduction)
    return run_op('smooth_l1', fn, ensure_tensor(input), ensure_tensor(label))


def kl_div(input, label, reduction='mean', name=None):
    def fn(a, b):
        out = b * (jnp.log(jnp.maximum(b, 1e-12)) - a)
        if reduction == 'batchmean':
            return jnp.sum(out) / a.shape[0]
        return _reduce(out, reduction)
    return run_op('kl_div', fn, ensure_tensor(input), ensure_tensor(label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction='mean',
                        name=None):
    def fn(a, b, lab):
        out = jnp.maximum(-lab * (a - b) + margin, 0.0)
        return _reduce(out, reduction)
    return run_op('margin_ranking', fn, ensure_tensor(input),
                  ensure_tensor(other), ensure_tensor(label))


def hinge_embedding_loss(input, label, margin=1.0, reduction='mean', name=None):
    def fn(a, lab):
        out = jnp.where(lab == 1.0, a, jnp.maximum(margin - a, 0.0))
        return _reduce(out, reduction)
    return run_op('hinge_embedding', fn, ensure_tensor(input),
                  ensure_tensor(label))


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction='mean',
                          name=None):
    def fn(a, b, lab):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        out = jnp.where(lab == 1, 1.0 - cos, jnp.maximum(cos - margin, 0.0))
        return _reduce(out, reduction)
    return run_op('cosine_embedding', fn, ensure_tensor(input1),
                  ensure_tensor(input2), ensure_tensor(label))


def log_loss(input, label, epsilon=0.0001, name=None):
    def fn(a, lab):
        return -lab * jnp.log(a + epsilon) - (1 - lab) * jnp.log(1 - a + epsilon)
    return run_op('log_loss', fn, ensure_tensor(input), ensure_tensor(label))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction='sum', name=None):
    def fn(a, lab, *mn):
        p = jax.nn.sigmoid(a)
        ce = (1 - lab) * a + jnp.log1p(jnp.exp(-jnp.abs(a))) + jnp.maximum(-a, 0.0)
        p_t = p * lab + (1 - p) * (1 - lab)
        a_t = alpha * lab + (1 - alpha) * (1 - lab)
        out = a_t * jnp.power(1 - p_t, gamma) * ce
        if mn:
            out = out / mn[0]
        return _reduce(out, reduction)
    args = [ensure_tensor(logit), ensure_tensor(label)]
    if normalizer is not None:
        args.append(ensure_tensor(normalizer))
    return run_op('sigmoid_focal', fn, *args)


def dice_loss(input, label, epsilon=1e-05, name=None):
    def fn(a, lab):
        lab_oh = jax.nn.one_hot(jnp.squeeze(lab, -1).astype(jnp.int32),
                                a.shape[-1], dtype=a.dtype)
        reduce_dims = tuple(range(1, a.ndim))
        inter = 2 * jnp.sum(a * lab_oh, axis=reduce_dims)
        union = jnp.sum(a, axis=reduce_dims) + jnp.sum(lab_oh, axis=reduce_dims)
        return jnp.mean(1.0 - (inter + epsilon) / (union + epsilon))
    return run_op('dice_loss', fn, ensure_tensor(input), ensure_tensor(label))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def fn(a, p, lab):
        sim = jnp.matmul(a, p.T)
        lab_c = lab.reshape(-1, 1)
        tgt = (lab_c == lab_c.T).astype(a.dtype)
        tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
        ce = -jnp.sum(tgt * jax.nn.log_softmax(sim, axis=1), axis=1)
        reg = l2_reg * (jnp.sum(jnp.square(a)) + jnp.sum(jnp.square(p))) \
            / (2.0 * a.shape[0])
        return jnp.mean(ce) + reg
    return run_op('npair_loss', fn, ensure_tensor(anchor),
                  ensure_tensor(positive), ensure_tensor(labels))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-06, swap=False, reduction='mean', name=None):
    def fn(a, pos, neg):
        dp = jnp.power(jnp.sum(jnp.power(jnp.abs(a - pos) + epsilon, p),
                               axis=-1), 1.0 / p)
        dn = jnp.power(jnp.sum(jnp.power(jnp.abs(a - neg) + epsilon, p),
                               axis=-1), 1.0 / p)
        if swap:
            dpn = jnp.power(jnp.sum(jnp.power(jnp.abs(pos - neg) + epsilon, p),
                                    axis=-1), 1.0 / p)
            dn = jnp.minimum(dn, dpn)
        out = jnp.maximum(dp - dn + margin, 0.0)
        return _reduce(out, reduction)
    return run_op('triplet_margin', fn, ensure_tensor(input),
                  ensure_tensor(positive), ensure_tensor(negative))


def multi_label_soft_margin_loss(input, label, weight=None, reduction='mean',
                                 name=None):
    def fn(a, lab, *mw):
        out = -(lab * jax.nn.log_sigmoid(a) + (1 - lab) * jax.nn.log_sigmoid(-a))
        if mw:
            out = out * mw[0]
        out = jnp.mean(out, axis=-1)
        return _reduce(out, reduction)
    args = [ensure_tensor(input), ensure_tensor(label)]
    if weight is not None:
        args.append(ensure_tensor(weight))
    return run_op('ml_soft_margin', fn, *args)


def soft_margin_loss(input, label, reduction='mean', name=None):
    def fn(a, lab):
        return _reduce(jnp.log1p(jnp.exp(-lab * a)), reduction)
    return run_op('soft_margin', fn, ensure_tensor(input), ensure_tensor(label))


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction='mean', norm_by_times=False):
    """CTC via dynamic-programming in log space (lax.scan over time).

    Reference: warpctc binding (operators/warpctc_op.*). Layout in:
    log_probs [T, B, C] (paddle convention), labels [B, L]."""
    lp = ensure_tensor(log_probs)
    lab = ensure_tensor(labels)._data.astype(jnp.int32)
    il = ensure_tensor(input_lengths)._data.astype(jnp.int32)
    ll = ensure_tensor(label_lengths)._data.astype(jnp.int32)

    def fn(logits):
        logp = jax.nn.log_softmax(logits, axis=-1)
        T, B, C = logp.shape
        L = lab.shape[1]
        S = 2 * L + 1
        # extended label seq: blank, l1, blank, l2, ... blank
        ext = jnp.full((B, S), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab)
        neg_inf = jnp.asarray(-1e30, logp.dtype)

        # alpha init
        alpha0 = jnp.full((B, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(logp[0, jnp.arange(B), blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(ll > 0, logp[0, jnp.arange(B), ext[:, 1]], neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, logp_t):
            a_shift1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a_shift1), a_shift2)
            emit = jnp.take_along_axis(logp_t, ext, axis=1)
            return merged + emit, None

        def scan_step(carry, t):
            alpha = carry
            new_alpha, _ = step(alpha, logp[t])
            # freeze past input_length
            keep = (t < il)[:, None]
            return jnp.where(keep, new_alpha, alpha), None

        alpha, _ = jax.lax.scan(scan_step, alpha0, jnp.arange(1, T))
        bidx = jnp.arange(B)
        end1 = alpha[bidx, 2 * ll]
        end2 = jnp.where(ll > 0, alpha[bidx, jnp.maximum(2 * ll - 1, 0)], neg_inf)
        ll_total = jnp.logaddexp(end1, end2)
        loss = -ll_total
        if norm_by_times:
            # reference warpctc norm_by_times: gradients (not the loss
            # VALUE) are scaled by 1/T — forward stays `loss`, backward
            # differentiates loss/T
            t_inv = loss / jnp.maximum(il.astype(loss.dtype), 1.0)
            loss = t_inv + jax.lax.stop_gradient(loss - t_inv)
        if reduction == 'mean':
            return jnp.mean(loss / jnp.maximum(ll.astype(loss.dtype), 1.0))
        return _reduce(loss, reduction)
    return run_op('ctc_loss', fn, lp)
