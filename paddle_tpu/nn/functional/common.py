"""Common functionals: linear, dropout, pad, interpolate, embedding, one_hot…
(reference: python/paddle/nn/functional/common.py + input.py)."""
import numpy as np
import jax
import jax.numpy as jnp

from ...framework.core import Tensor, run_op, wrap_out
from ...framework import random as rng
from ...tensor._helpers import ensure_tensor, shape_arg

__all__ = ['linear', 'dropout', 'dropout2d', 'dropout3d', 'alpha_dropout',
           'pad', 'zeropad2d', 'interpolate', 'upsample', 'one_hot',
           'embedding', 'unfold', 'fold', 'cosine_similarity', 'pixel_shuffle',
           'pixel_unshuffle', 'channel_shuffle', 'label_smooth',
           'class_center_sample', 'bilinear']


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b.  W layout: [in, out] (paddle convention). The matmul is
    the MXU hot path; bias fuses in XLA."""
    x, w = ensure_tensor(x), ensure_tensor(weight)
    if bias is not None:
        return run_op('linear', lambda a, ww, b: jnp.matmul(a, ww) + b,
                      x, w, ensure_tensor(bias))
    return run_op('linear', lambda a, ww: jnp.matmul(a, ww), x, w)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    x = ensure_tensor(x)
    if not training or p == 0:
        if mode == "downscale_in_infer" and not training:
            return run_op('dropout', lambda a: a * (1.0 - p), x)
        return x
    if p == 1:
        return run_op('dropout', lambda a: a * 0.0, x)
    key = rng.next_key()

    def fn(a):
        shape = list(a.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)
    return run_op('dropout', fn, x)


def dropout2d(x, p=0.5, training=True, data_format='NCHW', name=None):
    ax = [0, 1] if data_format == 'NCHW' else [0, 3]
    return dropout(x, p=p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format='NCDHW', name=None):
    ax = [0, 1] if data_format == 'NCDHW' else [0, 4]
    return dropout(x, p=p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = ensure_tensor(x)
    if not training or p == 0:
        return x
    key = rng.next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def fn(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        coef_a = (q + alpha_p ** 2 * q * p) ** -0.5
        coef_b = -coef_a * alpha_p * p
        return coef_a * jnp.where(keep, a, alpha_p) + coef_b
    return run_op('alpha_dropout', fn, x)


def pad(x, pad, mode='constant', value=0.0, data_format='NCHW', name=None):
    x = ensure_tensor(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]
    nd = x.ndim

    if len(pad) == 2 * nd:
        pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle spatial-pad semantics: the list orders from the LAST
        # spatial dim backwards — 4-D NCHW pad=[left, right, top, bottom]
        # pads W with (left, right) and H with (top, bottom)
        n_spatial = len(pad) // 2
        pairs = [(0, 0)] * nd
        if data_format.startswith('NC'):
            spatial_dims = list(range(2, 2 + n_spatial))[::-1]
        else:
            spatial_dims = list(range(1, 1 + n_spatial))[::-1]
        for i, d in enumerate(spatial_dims):
            pairs[d] = (pad[2 * i], pad[2 * i + 1])

    jmode = {'constant': 'constant', 'reflect': 'reflect',
             'replicate': 'edge', 'circular': 'wrap'}[mode]

    def fn(a):
        if jmode == 'constant':
            return jnp.pad(a, pairs, mode='constant', constant_values=value)
        return jnp.pad(a, pairs, mode=jmode)
    return run_op('pad', fn, x)


def zeropad2d(x, padding, data_format='NCHW', name=None):
    return pad(x, padding, mode='constant', value=0.0, data_format=data_format)


def one_hot(x, num_classes, name=None):
    x = ensure_tensor(x)
    return wrap_out(jax.nn.one_hot(x._data, num_classes, dtype=jnp.float32))


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Embedding lookup = gather; on TPU sparse=False always (XLA gathers are
    dense-friendly). padding_idx rows produce zero gradients via masking."""
    idx = ensure_tensor(x)._data
    w = ensure_tensor(weight)

    def fn(ww):
        out = jnp.take(ww, idx, axis=0)
        if padding_idx is not None:
            mask = (idx != padding_idx)[..., None]
            out = out * mask.astype(out.dtype)
        return out
    return run_op('embedding', fn, w)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    x = ensure_tensor(x)

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    k, s = _pair(kernel_sizes), _pair(strides)
    d = _pair(dilations)
    p = paddings
    if isinstance(p, int):
        p = [p, p, p, p]
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]

    def fn(a):
        n, c, h, w = a.shape
        a_p = jnp.pad(a, [(0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])])
        hh = (a_p.shape[2] - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        ww = (a_p.shape[3] - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        patches = []
        for i in range(k[0]):
            for j in range(k[1]):
                sl = a_p[:, :, i * d[0]: i * d[0] + hh * s[0]: s[0],
                         j * d[1]: j * d[1] + ww * s[1]: s[1]]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # N, C, k*k, L...
        return out.reshape(n, c * k[0] * k[1], hh * ww)
    return run_op('unfold', fn, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    x = ensure_tensor(x)

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    o, k, s = _pair(output_sizes), _pair(kernel_sizes), _pair(strides)
    d = _pair(dilations)
    p = paddings
    if isinstance(p, int):
        p = [p, p, p, p]
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]

    def fn(a):
        n, ckk, l = a.shape
        c = ckk // (k[0] * k[1])
        hp, wp = o[0] + p[0] + p[2], o[1] + p[1] + p[3]
        hh = (hp - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        ww = (wp - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        a_r = a.reshape(n, c, k[0], k[1], hh, ww)
        out = jnp.zeros((n, c, hp, wp), a.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                out = out.at[:, :, i * d[0]: i * d[0] + hh * s[0]: s[0],
                             j * d[1]: j * d[1] + ww * s[1]: s[1]].add(
                    a_r[:, :, i, j])
        return out[:, :, p[0]:hp - p[2], p[1]:wp - p[3]]
    return run_op('fold', fn, x)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    x1, x2 = ensure_tensor(x1), ensure_tensor(x2)

    def fn(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.sqrt(jnp.sum(a * a, axis=axis)) * \
            jnp.sqrt(jnp.sum(b * b, axis=axis))
        return num / jnp.maximum(den, eps)
    return run_op('cosine_similarity', fn, x1, x2)


def pixel_shuffle(x, upscale_factor, data_format='NCHW', name=None):
    x = ensure_tensor(x)
    r = upscale_factor

    def fn(a):
        if data_format == 'NCHW':
            n, c, h, w = a.shape
            out = a.reshape(n, c // (r * r), r, r, h, w)
            out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
            return out.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        out = a.reshape(n, h, w, r, r, c // (r * r))
        out = jnp.transpose(out, (0, 1, 3, 2, 4, 5))
        return out.reshape(n, h * r, w * r, c // (r * r))
    return run_op('pixel_shuffle', fn, x)


def pixel_unshuffle(x, downscale_factor, data_format='NCHW', name=None):
    x = ensure_tensor(x)
    r = downscale_factor

    def fn(a):
        if data_format == 'NCHW':
            n, c, h, w = a.shape
            out = a.reshape(n, c, h // r, r, w // r, r)
            out = jnp.transpose(out, (0, 1, 3, 5, 2, 4))
            return out.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        out = a.reshape(n, h // r, r, w // r, r, c)
        out = jnp.transpose(out, (0, 1, 3, 2, 4, 5))
        return out.reshape(n, h // r, w // r, c * r * r)
    return run_op('pixel_unshuffle', fn, x)


def channel_shuffle(x, groups, data_format='NCHW', name=None):
    x = ensure_tensor(x)

    def fn(a):
        if data_format == 'NCHW':
            n, c, h, w = a.shape
            out = a.reshape(n, groups, c // groups, h, w)
            out = jnp.swapaxes(out, 1, 2)
            return out.reshape(n, c, h, w)
        n, h, w, c = a.shape
        out = a.reshape(n, h, w, groups, c // groups)
        out = jnp.swapaxes(out, 3, 4)
        return out.reshape(n, h, w, c)
    return run_op('channel_shuffle', fn, x)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = ensure_tensor(label)

    def fn(l):
        k = l.shape[-1]
        if prior_dist is not None:
            pd = prior_dist._data if isinstance(prior_dist, Tensor) else prior_dist
            return (1 - epsilon) * l + epsilon * pd
        return (1 - epsilon) * l + epsilon / k
    return run_op('label_smooth', fn, label)


def class_center_sample(label, num_classes, num_samples, group=None):
    label_np = ensure_tensor(label).numpy()
    pos = np.unique(label_np)
    if len(pos) >= num_samples:
        sampled = pos[:num_samples]
    else:
        neg = np.setdiff1d(np.arange(num_classes), pos)
        extra = neg[:num_samples - len(pos)]
        sampled = np.concatenate([pos, extra])
    remap = {c: i for i, c in enumerate(sampled)}
    remapped = np.asarray([remap.get(int(v), 0) for v in label_np.reshape(-1)],
                          dtype=np.int64).reshape(label_np.shape)
    return (wrap_out(jnp.asarray(remapped)),
            wrap_out(jnp.asarray(sampled, dtype=jnp.int64)))


def bilinear(x1, x2, weight, bias=None, name=None):
    x1, x2, w = ensure_tensor(x1), ensure_tensor(x2), ensure_tensor(weight)

    def fn(a, b, ww, *mb):
        out = jnp.einsum('bi,oij,bj->bo', a, ww, b)
        if mb:
            out = out + mb[0]
        return out
    if bias is not None:
        return run_op('bilinear', fn, x1, x2, w, ensure_tensor(bias))
    return run_op('bilinear', fn, x1, x2, w)


def interpolate(x, size=None, scale_factor=None, mode='nearest',
                align_corners=False, align_mode=0, data_format='NCHW',
                name=None):
    x = ensure_tensor(x)
    channel_last = data_format in ('NHWC', 'NWC', 'NDHWC', 'NLC')
    nd = x.ndim - 2
    spatial = list(range(1, 1 + nd)) if channel_last else list(range(2, 2 + nd))
    in_sizes = [x.shape[d] for d in spatial]

    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()
        out_sizes = [int(s.item() if isinstance(s, Tensor) else s) for s in
                     (size if isinstance(size, (list, tuple)) else [size])]
    else:
        if isinstance(scale_factor, (list, tuple)):
            out_sizes = [int(i * s) for i, s in zip(in_sizes, scale_factor)]
        else:
            out_sizes = [int(i * scale_factor) for i in in_sizes]

    method = {'nearest': 'nearest', 'bilinear': 'linear', 'linear': 'linear',
              'trilinear': 'linear', 'bicubic': 'cubic', 'area': 'linear'}[mode]

    def fn(a):
        new_shape = list(a.shape)
        for d, s in zip(spatial, out_sizes):
            new_shape[d] = s
        asymmetric = (align_mode == 1 and not align_corners and
                      method == 'linear')
        if method == 'nearest' or (not align_corners and not asymmetric):
            return jax.image.resize(a, tuple(new_shape), method=method)
        # align_corners / align_mode=1: gather with explicit index mapping
        # (reference interpolate: align_mode 1 maps src = dst * in/out with
        # no half-pixel shift, vs jax.image.resize's half-pixel convention)
        out = a
        for d, s in zip(spatial, out_sizes):
            in_s = out.shape[d]
            if s == in_s:
                continue
            if asymmetric:
                pos = jnp.arange(s) * (in_s / s)
                pos = jnp.clip(pos, 0.0, in_s - 1.0)
            else:
                pos = jnp.linspace(0.0, in_s - 1.0, s)
            i0 = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, in_s - 1)
            i1 = jnp.clip(i0 + 1, 0, in_s - 1)
            frac = (pos - i0).astype(a.dtype)
            shape_b = [1] * out.ndim
            shape_b[d] = s
            frac = frac.reshape(shape_b)
            lo = jnp.take(out, i0, axis=d)
            hi = jnp.take(out, i1, axis=d)
            if method == 'nearest':
                out = jnp.where(frac < 0.5, lo, hi)
            else:
                out = lo * (1 - frac) + hi * frac
        return out
    return run_op('interpolate', fn, x)


def upsample(x, size=None, scale_factor=None, mode='nearest',
             align_corners=False, align_mode=0, data_format='NCHW', name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)
