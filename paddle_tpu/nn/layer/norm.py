"""Normalization layers (reference: python/paddle/nn/layer/norm.py).

SyncBatchNorm: on TPU, batch stats inside a pjit'd step are computed over the
global (sharded) batch automatically when the reduction spans the dp axis —
see distributed/meta_parallel/sync_batch_norm for the shard_map variant.
"""
import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ['BatchNorm', 'BatchNorm1D', 'BatchNorm2D', 'BatchNorm3D',
           'LayerNorm', 'GroupNorm', 'InstanceNorm1D', 'InstanceNorm2D',
           'InstanceNorm3D', 'LocalResponseNorm', 'SpectralNorm', 'RMSNorm',
           'SyncBatchNorm']


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format='NCHW',
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            shape=[num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(shape=[num_features], attr=bias_attr,
                                          is_bias=True)
        self.register_buffer('_mean', Tensor(jnp.zeros([num_features])))
        self.register_buffer('_variance', Tensor(jnp.ones([num_features])))

    def forward(self, input):
        return F.batch_norm(
            input, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return 'num_features=%d, momentum=%s, epsilon=%s' % (
            self._num_features, self._momentum, self._epsilon)


class BatchNorm(_BatchNormBase):
    """Legacy paddle.nn.BatchNorm (fluid dygraph BatchNorm) signature."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05,
                 param_attr=None, bias_attr=None, dtype='float32',
                 data_layout='NCHW', in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats if use_global_stats else None)
        self._act = act

    def forward(self, input):
        out = super().forward(input)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format='NCL',
                 use_global_stats=None, name=None):
        fmt = 'NLC' if data_format == 'NLC' else 'NCHW'
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, fmt, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format='NCDHW',
                 use_global_stats=None, name=None):
        fmt = 'NDHWC' if data_format == 'NDHWC' else 'NCHW'
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, fmt, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Inside pjit the batch axis is global, so plain BN
    stats are already synced; kept as a distinct class for API parity
    (reference: python/paddle/nn/layer/norm.py SyncBatchNorm +
    sync_batch_norm_op.cu)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            out = cls(layer._num_features, layer._momentum, layer._epsilon,
                      None, None, layer._data_format)
            out.weight.set_value(layer.weight._data)
            out.bias.set_value(layer.bias._data)
            out._mean.set_value(layer._mean._data)
            out._variance.set_value(layer._variance._data)
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=self._normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, input):
        return F.layer_norm(input, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return 'normalized_shape=%s, epsilon=%s' % (self._normalized_shape,
                                                    self._epsilon)


class RMSNorm(Layer):
    """RMS norm (beyond-reference; standard for modern LLMs)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        from ...framework.core import run_op
        from ...tensor._helpers import ensure_tensor
        eps = self._epsilon
        nd = len(self._normalized_shape)

        def fn(a, w):
            axes = tuple(range(a.ndim - nd, a.ndim))
            var = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=axes,
                           keepdims=True)
            out = a * jax.lax.rsqrt(var + eps).astype(a.dtype)
            return out * w
        return run_op('rms_norm', fn, ensure_tensor(x), self.weight)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format='NCHW',
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0)) if weight_attr is not False else None
        self.bias = self.create_parameter(
            shape=[num_channels], attr=bias_attr,
            is_bias=True) if bias_attr is not False else None

    def forward(self, input):
        return F.group_norm(input, self._num_groups, self._epsilon,
                            self.weight, self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format='NCHW',
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is not False:
            self.scale = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter(shape=[num_features],
                                              attr=bias_attr, is_bias=True)
        else:
            self.scale, self.bias = None, None

    def forward(self, input):
        return F.instance_norm(input, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format='NCHW', name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, input):
        return F.local_response_norm(input, *self.args)


class SpectralNorm(Layer):
    """Spectral norm via power iteration (reference: spectral_norm_op)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype='float32'):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.weight_u = self.create_parameter(
            shape=[h], default_initializer=I.Normal(0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            shape=[w], default_initializer=I.Normal(0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...framework.core import run_op
        from ...tensor._helpers import ensure_tensor
        dim, iters, eps = self._dim, self._power_iters, self._epsilon
        u0, v0 = self.weight_u._data, self.weight_v._data

        def fn(w):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            u, v = u0, v0
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma
        out = run_op('spectral_norm', fn, ensure_tensor(weight))
        return out
