"""Layer base class (reference: python/paddle/fluid/dygraph/layers.py).

Holds Parameters/buffers/sublayers as instance state (mutable, paddle-style)
while staying functionalizable: framework/functional.py can pull the param
pytree out, run forward under a jit trace with tracer-backed params bound in,
and push updated arrays back — that is how the fast path compiles.
"""
import collections

import numpy as np
import jax.numpy as jnp

from ...framework.core import Tensor, Parameter, no_grad_guard
from ...framework import dtype as dtype_mod
from .. import initializer as init_mod

__all__ = ['Layer', 'ParamAttr']


class ParamAttr:
    """paddle.ParamAttr parity (python/paddle/fluid/param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, init_mod.Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return False
        return ParamAttr()


class _HookRemoveHelper:
    def __init__(self, hooks, hid):
        self._hooks, self._hid = hooks, hid

    def remove(self):
        self._hooks.pop(self._hid, None)


_name_counters = collections.defaultdict(int)


class Layer:
    def __init__(self, name_scope=None, dtype='float32'):
        cls = self.__class__.__name__.lower()
        _name_counters[cls] += 1
        self._full_name = "%s_%d" % (name_scope or cls, _name_counters[cls])
        self._dtype = dtype_mod.convert_dtype(dtype) if dtype else 'float32'
        self.training = True
        self._parameters = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers = collections.OrderedDict()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()

    # -- construction -------------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype
        initializer = attr.initializer or default_initializer
        if initializer is None:
            initializer = (init_mod.Constant(0.0) if is_bias
                           else init_mod.XavierNormal())
        data = initializer(shape, dtype)
        p = Parameter(data, name=attr.name, trainable=attr.trainable)
        p.optimize_attr['learning_rate'] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_variable(self, name=None, persistable=False, dtype=None):
        t = Tensor(jnp.zeros((), dtype_mod.to_jax_dtype(dtype or self._dtype)))
        t.persistable = persistable
        return t

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute magic ----------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get('_parameters')
        layers = self.__dict__.get('_sub_layers')
        buffers = self.__dict__.get('_buffers')
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() first")
            params[name] = value
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() first")
            layers[name] = value
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
        elif buffers is not None and name in buffers:
            buffers[name] = value if (value is None or isinstance(value, Tensor)) \
                else Tensor(value)
        else:
            if params is not None:
                params.pop(name, None)
            if layers is not None:
                layers.pop(name, None)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ('_parameters', '_buffers', '_sub_layers'):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError("'%s' object has no attribute '%s'"
                             % (type(self).__name__, name))

    def __delattr__(self, name):
        for store in ('_parameters', '_buffers', '_sub_layers'):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = list(self._parameters) + list(self._buffers) + list(self._sub_layers)
        return sorted(set(super().__dir__() + extra))

    # -- traversal ----------------------------------------------------------
    def named_parameters(self, prefix='', include_sublayers=True):
        seen = set()
        for lname, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (lname + '.' + pname if lname else pname), p

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix='', include_sublayers=True):
        seen = set()
        for lname, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (lname + '.' + bname if lname else bname), b

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix='', include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = prefix + ('.' if prefix else '') + name
            yield from layer.named_sublayers(prefix=sub_prefix, include_self=True,
                                             layers_set=layers_set)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return [l for l in self._sub_layers.values() if l is not None]

    def named_children(self):
        return [(n, l) for n, l in self._sub_layers.items() if l is not None]

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- mode ---------------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix='', use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[structured_name_prefix + name] = p
        for lname, layer in self.named_sublayers(include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                key = structured_name_prefix + \
                    (lname + '.' + bname if lname else bname)
                dest[key] = b
        return dest

    to_static_state_dict = state_dict

    def set_state_dict(self, state_dict, use_structured_name=True):
        missing, unexpected = [], []
        own = self.state_dict()
        if not use_structured_name:
            # reference semantics: checkpoint keys are the parameters'
            # own .name attributes instead of structured paths
            remapped = collections.OrderedDict()
            for key, t in own.items():
                nm = getattr(t, 'name', None) or key
                if nm in remapped:
                    raise ValueError(
                        'set_state_dict(use_structured_name=False): '
                        'duplicate parameter name %r — names must be '
                        'unique to load by name' % nm)
                remapped[nm] = t
            own = remapped
        for key, target in own.items():
            if key in state_dict:
                v = state_dict[key]
                arr = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                target.set_value(arr.reshape(tuple(target.shape)))
            else:
                missing.append(key)
        for key in state_dict:
            if key not in own:
                unexpected.append(key)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- dtype / device motion ---------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._to_dtype(dtype)
        if device is not None:
            self._to_device(device)
        return self

    def _to_device(self, device):
        """Move params/buffers to a device spec ('cpu', 'tpu:3', a Place,
        or a jax.Device — one resolver, shared with set_device). blocking
        is irrelevant: device_put is async and ordered for us by XLA."""
        import jax
        from ...framework.device import resolve_device
        dev = resolve_device(device)
        for _, p in self.named_parameters():
            p._data = jax.device_put(p._data, dev)
        for _, b in self.named_buffers():
            b._data = jax.device_put(b._data, dev)

    def _to_dtype(self, dtype):
        jd = dtype_mod.to_jax_dtype(dtype)
        for _, p in self.named_parameters():
            if jnp.issubdtype(p._data.dtype, jnp.floating):
                p._data = p._data.astype(jd)
        for _, b in self.named_buffers():
            if b is not None and jnp.issubdtype(b._data.dtype, jnp.floating):
                b._data = b._data.astype(jd)
        for l in self.sublayers(include_self=True):
            l._dtype = dtype_mod.convert_dtype(dtype)
        return self

    def astype(self, dtype):
        return self._to_dtype(dtype)

    def float(self):
        return self._to_dtype('float32')

    def half(self):
        return self._to_dtype('float16')

    def bfloat16(self):
        return self._to_dtype('bfloat16')

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        hid = len(self._forward_pre_hooks)
        self._forward_pre_hooks[hid] = hook
        return _HookRemoveHelper(self._forward_pre_hooks, hid)

    def register_forward_post_hook(self, hook):
        hid = len(self._forward_post_hooks)
        self._forward_post_hooks[hid] = hook
        return _HookRemoveHelper(self._forward_post_hooks, hid)

    # -- call ---------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    def full_name(self):
        return self._full_name

    def extra_repr(self):
        return ''

    def __repr__(self):
        extra = self.extra_repr()
        lines = [extra] if extra else []
        for name, layer in self._sub_layers.items():
            rep = repr(layer).split('\n')
            rep = [rep[0]] + ['  ' + r for r in rep[1:]]
            lines.append('(%s): %s' % (name, '\n'.join(rep)))
        main = self.__class__.__name__
        if not lines:
            return main + ('(%s)' % extra if extra else '()')
        return main + '(\n  ' + '\n  '.join(lines) + '\n)'

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()
