"""Distance layers (reference: python/paddle/nn/layer/distance.py)."""
import jax.numpy as jnp

from ...framework.core import run_op
from ...tensor._helpers import ensure_tensor
from .layers import Layer

__all__ = ['PairwiseDistance']


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        p, eps, keep = self.p, self.epsilon, self.keepdim

        def fn(a, b):
            d = jnp.abs(a - b) + eps
            return jnp.power(jnp.sum(jnp.power(d, p), axis=-1, keepdims=keep),
                             1.0 / p)
        return run_op('pairwise_distance', fn, ensure_tensor(x),
                      ensure_tensor(y))
