"""RNN layers (reference: python/paddle/nn/layer/rnn.py; cuDNN rnn_op.h).

TPU-native: the time loop is a lax.scan inside one recorded op, so the whole
sequence compiles to a single fused XLA while-loop instead of per-step ops.
"""
import math

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, run_op
from ...tensor._helpers import ensure_tensor
from .. import initializer as I
from .layers import Layer
from .container import LayerList

__all__ = ['RNNCellBase', 'SimpleRNNCell', 'LSTMCell', 'GRUCell', 'RNN',
           'BiRNN', 'SimpleRNN', 'LSTM', 'GRU']


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype='float32',
                           init_value=0.0, batch_dim_idx=0):
        batch = ensure_tensor(batch_ref).shape[batch_dim_idx]
        state_shape = (batch, self.hidden_size)
        return Tensor(jnp.full(state_shape, init_value, jnp.float32))


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation='tanh',
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == 'tanh' else jax.nn.relu

        def fn(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)
        h = run_op('simple_rnn_cell', fn, ensure_tensor(inputs),
                   ensure_tensor(states), self.weight_ih, self.weight_hh,
                   self.bias_ih, self.bias_hh)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            b = ensure_tensor(inputs).shape[0]
            z = Tensor(jnp.zeros((b, self.hidden_size)))
            states = (z, z)
        h0, c0 = states

        def fn(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new
        h, c = run_op('lstm_cell', fn, ensure_tensor(inputs), ensure_tensor(h0),
                      ensure_tensor(c0), self.weight_ih, self.weight_hh,
                      self.bias_ih, self.bias_hh)
        return h, (h, c)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def fn(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, in_ = jnp.split(gi, 3, axis=-1)
            hr, hz, hn = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(in_ + r * hn)
            return (1 - z) * n + z * h
        h = run_op('gru_cell', fn, ensure_tensor(inputs), ensure_tensor(states),
                   self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class RNN(Layer):
    """Runs a cell over time (single recorded scan op)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        return _run_rnn(self.cell, inputs, initial_states, self.is_reverse,
                        self.time_major, sequence_length)


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw, self.cell_bw = cell_fw, cell_bw
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        fw_st, bw_st = (None, None) if initial_states is None else initial_states
        out_f, st_f = _run_rnn(self.cell_fw, inputs, fw_st, False,
                               self.time_major, sequence_length)
        out_b, st_b = _run_rnn(self.cell_bw, inputs, bw_st, True,
                               self.time_major, sequence_length)
        from ...tensor.manipulation import concat
        return concat([out_f, out_b], axis=-1), (st_f, st_b)


def _cell_params(cell):
    return [cell.weight_ih, cell.weight_hh, cell.bias_ih, cell.bias_hh]


def _run_rnn(cell, inputs, initial_states, is_reverse, time_major,
             sequence_length=None):
    """Scan `cell` over the time axis as ONE recorded op.

    sequence_length (reference rnn.py semantics): steps at t >=
    sequence_length[b] emit zeros and do not advance row b's state; the
    reverse direction reverses only each row's valid prefix."""
    x = ensure_tensor(inputs)
    time_axis = 0 if time_major else 1
    batch = x.shape[1 if time_major else 0]
    hid = cell.hidden_size
    is_lstm = isinstance(cell, LSTMCell)

    if initial_states is None:
        z = jnp.zeros((batch, hid), jnp.float32)
        init = (z, z) if is_lstm else z
    else:
        if is_lstm:
            init = (ensure_tensor(initial_states[0])._data,
                    ensure_tensor(initial_states[1])._data)
        else:
            st = initial_states[0] if isinstance(initial_states, (tuple, list)) \
                else initial_states
            init = ensure_tensor(st)._data

    params = _cell_params(cell)
    act = getattr(cell, 'activation', 'tanh')

    def step_fn(carry, x_t, wi, wh, bi, bh):
        if is_lstm:
            h, c = carry
            gates = x_t @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return (h_new, c_new), h_new
        if isinstance(cell, GRUCell):
            h = carry
            gi = x_t @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, in_ = jnp.split(gi, 3, axis=-1)
            hr, hz, hn = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(in_ + r * hn)
            h_new = (1 - z) * n + z * h
            return h_new, h_new
        h = carry
        a = jnp.tanh if act == 'tanh' else jax.nn.relu
        h_new = a(x_t @ wi.T + bi + h @ wh.T + bh)
        return h_new, h_new

    lens_arr = None
    if sequence_length is not None:
        lens_arr = ensure_tensor(sequence_length)._data.astype(jnp.int32)

    def _rev_within(seq, lens):
        """Reverse each batch row's valid prefix along time (axis 0);
        involution, so it also un-reverses scan outputs."""
        T = seq.shape[0]
        t = jnp.arange(T, dtype=jnp.int32)[:, None]           # [T, 1]
        idx = jnp.where(t < lens[None, :], lens[None, :] - 1 - t, t)
        idx = idx.reshape(idx.shape + (1,) * (seq.ndim - 2))
        return jnp.take_along_axis(
            seq, jnp.broadcast_to(idx, seq.shape).astype(jnp.int32), axis=0)

    def fn(xa, wi, wh, bi, bh, *maybe_lens):
        xs = jnp.moveaxis(xa, time_axis, 0)
        lens = maybe_lens[0] if maybe_lens else None
        if is_reverse:
            xs = _rev_within(xs, lens) if lens is not None \
                else jnp.flip(xs, axis=0)

        if lens is None:
            carry, ys = jax.lax.scan(
                lambda c, x_t: step_fn(c, x_t, wi, wh, bi, bh), init, xs)
        else:
            def masked_step(c_t, inp):
                c, t = c_t
                x_t = inp
                alive = (t < lens)[:, None]                    # [B, 1]
                new_c, y = step_fn(c, x_t, wi, wh, bi, bh)
                if is_lstm:
                    held = (jnp.where(alive, new_c[0], c[0]),
                            jnp.where(alive, new_c[1], c[1]))
                else:
                    held = jnp.where(alive, new_c, c)
                return (held, t + 1), jnp.where(alive, y, 0.0)
            (carry, _), ys = jax.lax.scan(
                masked_step, (init, jnp.zeros((), jnp.int32)), xs)

        if is_reverse:
            ys = _rev_within(ys, lens) if lens is not None \
                else jnp.flip(ys, axis=0)
        out = jnp.moveaxis(ys, 0, time_axis)
        if is_lstm:
            return out, carry[0], carry[1]
        return out, carry

    op_args = (x,) + tuple(params)
    if lens_arr is not None:
        op_args = op_args + (Tensor(lens_arr),)
    outs = run_op('rnn_scan', fn, *op_args)
    if is_lstm:
        out, h, c = outs
        return out, (h, c)
    out, h = outs
    return out, h


class _StackedRNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction='forward', time_major=False, dropout=0.0,
                 activation='tanh', weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size, self.hidden_size = input_size, hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        bidirect = direction in ('bidirect', 'bidirectional')
        self.num_directions = 2 if bidirect else 1

        def make_cell(in_sz):
            if mode == 'LSTM':
                return LSTMCell(in_sz, hidden_size, weight_ih_attr,
                                weight_hh_attr, bias_ih_attr, bias_hh_attr)
            if mode == 'GRU':
                return GRUCell(in_sz, hidden_size, weight_ih_attr,
                               weight_hh_attr, bias_ih_attr, bias_hh_attr)
            return SimpleRNNCell(in_sz, hidden_size, activation,
                                 weight_ih_attr, weight_hh_attr, bias_ih_attr,
                                 bias_hh_attr)

        self._cells = LayerList()
        for layer_i in range(num_layers):
            in_sz = input_size if layer_i == 0 \
                else hidden_size * self.num_directions
            self._cells.append(make_cell(in_sz))
            if bidirect:
                self._cells.append(make_cell(in_sz))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import concat, stack
        from ..functional import dropout as dropout_fn
        out = inputs
        final_h, final_c = [], []
        idx = 0
        for layer_i in range(self.num_layers):
            if self.num_directions == 2:
                cell_f, cell_b = self._cells[idx], self._cells[idx + 1]
                idx += 2
                of, sf = _run_rnn(cell_f, out, None, False, self.time_major,
                                  sequence_length)
                ob, sb = _run_rnn(cell_b, out, None, True, self.time_major,
                                  sequence_length)
                out = concat([of, ob], axis=-1)
                states = [sf, sb]
            else:
                cell = self._cells[idx]
                idx += 1
                out, st = _run_rnn(cell, out, None, False, self.time_major,
                                   sequence_length)
                states = [st]
            for st in states:
                if self.mode == 'LSTM':
                    final_h.append(st[0])
                    final_c.append(st[1])
                else:
                    final_h.append(st)
            if self.dropout > 0 and layer_i < self.num_layers - 1:
                out = dropout_fn(out, self.dropout, training=self.training)
        h = stack(final_h, axis=0)
        if self.mode == 'LSTM':
            c = stack(final_c, axis=0)
            return out, (h, c)
        return out, h


class SimpleRNN(_StackedRNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction='forward', time_major=False, dropout=0.0,
                 activation='tanh', **kwargs):
        super().__init__('RNN', input_size, hidden_size, num_layers,
                         direction, time_major, dropout, activation, **kwargs)


class LSTM(_StackedRNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction='forward', time_major=False, dropout=0.0, **kwargs):
        super().__init__('LSTM', input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class GRU(_StackedRNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction='forward', time_major=False, dropout=0.0, **kwargs):
        super().__init__('GRU', input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)
