"""Gradient clipping (reference: python/paddle/fluid/clip.py).

Clip objects transform (param, grad) lists; optimizers apply them before the
update, matching ClipGradByGlobalNorm et al. semantics.
"""
import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ['ClipGradByValue', 'ClipGradByNorm', 'ClipGradByGlobalNorm',
           'clip_grad_norm_', 'clip_grad_value_']


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._data)))
            scale = jnp.where(norm > self.clip_norm, self.clip_norm / norm, 1.0)
            out.append((p, Tensor(g._data * scale)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name='default_group'):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def __call__(self, params_grads):
        grads = [g._data for p, g in params_grads
                 if g is not None and getattr(p, 'need_clip', True)]
        if not grads:
            return params_grads
        global_norm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                                   for g in grads))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, 'need_clip', True):
                out.append((p, g))
            else:
                out.append((p, Tensor((g._data * scale).astype(g._data.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    params = [p for p in (parameters if isinstance(parameters, (list, tuple))
                          else [parameters]) if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros(()))
    if norm_type == float('inf'):
        total = jnp.max(jnp.asarray([jnp.max(jnp.abs(p.grad._data))
                                     for p in params]))
    else:
        total = jnp.power(sum(jnp.sum(jnp.power(jnp.abs(p.grad._data),
                                                norm_type)) for p in params),
                          1.0 / norm_type)
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        p.grad = Tensor(p.grad._data * scale)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    params = parameters if isinstance(parameters, (list, tuple)) else [parameters]
    for p in params:
        if p.grad is not None:
            p.grad = Tensor(jnp.clip(p.grad._data, -clip_value, clip_value))
