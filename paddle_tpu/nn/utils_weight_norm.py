"""Weight norm reparameterization (reference: python/paddle/nn/utils/weight_norm_hook.py)."""
import jax.numpy as jnp

from ..framework.core import Tensor, Parameter, run_op

__all__ = ['weight_norm', 'remove_weight_norm']


def _norm_except(w, dim):
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(w)))
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(w), axis=axes, keepdims=True))


def weight_norm(layer, name='weight', dim=0):
    w = getattr(layer, name)
    g = Parameter(_norm_except(w._data, dim))
    v = Parameter(w._data)
    layer.add_parameter(name + '_g', g)
    layer.add_parameter(name + '_v', v)
    del layer._parameters[name]

    def hook(lyr, inputs):
        gg, vv = lyr._parameters[name + '_g'], lyr._parameters[name + '_v']

        def fn(gx, vx):
            return vx * (gx / _norm_except(vx, dim))
        w_new = run_op('weight_norm', fn, gg, vv)
        object.__setattr__(lyr, '_wn_cache_' + name, w_new)
        lyr.__dict__[name] = w_new
        return None
    layer._wn_hook = layer.register_forward_pre_hook(hook)
    # materialize once so attribute exists pre-forward
    hook(layer, None)
    return layer


def remove_weight_norm(layer, name='weight'):
    g = layer._parameters.pop(name + '_g')
    v = layer._parameters.pop(name + '_v')
    w = v._data * (g._data / _norm_except(v._data, 0))
    layer.__dict__.pop(name, None)
    layer.add_parameter(name, Parameter(w))
    if hasattr(layer, '_wn_hook'):
        layer._wn_hook.remove()
    return layer
