"""Weight norm reparameterization (reference: python/paddle/nn/utils/weight_norm_hook.py)."""
import jax.numpy as jnp

from ..framework.core import Tensor, Parameter, run_op

__all__ = ['weight_norm', 'remove_weight_norm',
           'spectral_norm', 'remove_spectral_norm']


def _norm_except(w, dim):
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(w)))
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(w), axis=axes, keepdims=True))


def weight_norm(layer, name='weight', dim=0):
    w = getattr(layer, name)
    g = Parameter(_norm_except(w._data, dim))
    v = Parameter(w._data)
    layer.add_parameter(name + '_g', g)
    layer.add_parameter(name + '_v', v)
    del layer._parameters[name]

    def hook(lyr, inputs):
        gg, vv = lyr._parameters[name + '_g'], lyr._parameters[name + '_v']

        def fn(gx, vx):
            return vx * (gx / _norm_except(vx, dim))
        w_new = run_op('weight_norm', fn, gg, vv)
        object.__setattr__(lyr, '_wn_cache_' + name, w_new)
        lyr.__dict__[name] = w_new
        return None
    layer._wn_hook = layer.register_forward_pre_hook(hook)
    # materialize once so attribute exists pre-forward
    hook(layer, None)
    return layer


def remove_weight_norm(layer, name='weight'):
    g = layer._parameters.pop(name + '_g')
    v = layer._parameters.pop(name + '_v')
    w = v._data * (g._data / _norm_except(v._data, 0))
    layer.__dict__.pop(name, None)
    layer.add_parameter(name, Parameter(w))
    if hasattr(layer, '_wn_hook'):
        layer._wn_hook.remove()
    return layer


def _l2_normalize(v, eps=1e-12):
    return v / (jnp.sqrt(jnp.sum(jnp.square(v))) + eps)


def _sn_power_iterate(wmat, u, iters, eps):
    """Shared power-iteration body (also the structure of
    nn.SpectralNorm.forward): returns (u, v) after `iters` rounds."""
    v = None
    for _ in range(iters):
        v = _l2_normalize(wmat.T @ u, eps)
        u = _l2_normalize(wmat @ v, eps)
    return u, v


def spectral_norm(layer, name='weight', n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Spectral-norm reparameterization (reference
    nn/utils/spectral_norm_hook.py): W_sn = W / sigma_max(W). The power
    iteration advances a persistent u buffer; sigma = u^T W v is computed
    INSIDE the recorded op so d(W/sigma)/dW keeps the -W (u v^T)/sigma^2
    term (same structure as nn.SpectralNorm.forward)."""
    if n_power_iterations < 1:
        raise ValueError('n_power_iterations must be >= 1, got %d'
                         % n_power_iterations)
    w = getattr(layer, name)
    if dim is None:
        # reference hook: Linear and the transposed convs keep the output
        # axis at position 1; everything else at 0 (isinstance, so
        # subclasses inherit the right default)
        from .layer.common import Linear as _Linear
        from .layer import conv as _conv
        transposed_classes = tuple(
            getattr(_conv, c) for c in ('Conv1DTranspose', 'Conv2DTranspose',
                                        'Conv3DTranspose')
            if hasattr(_conv, c))
        dim = 1 if isinstance(layer, (_Linear,) + transposed_classes) else 0
    wd = w._data
    h = wd.shape[dim]
    import numpy as _np
    rng = _np.random.RandomState(0)
    u0 = _l2_normalize(jnp.asarray(rng.randn(h).astype(_np.float32)))
    # keep the ORIGINAL Parameter object as _orig so trainable /
    # stop_gradient state survives the reparameterization
    layer.add_parameter(name + '_orig', w)
    del layer._parameters[name]
    layer.register_buffer(name + '_u', Tensor(u0), persistable=True)

    def hook(lyr, inputs):
        import jax
        vv = lyr._parameters[name + '_orig']
        u0_now = lyr._buffers[name + '_u']._data

        def fn(x):
            wmat = jnp.moveaxis(x, dim, 0).reshape(h, -1)
            u, vvec = _sn_power_iterate(wmat, u0_now, n_power_iterations,
                                        eps)
            sigma = u @ (wmat @ vvec)
            return x / sigma, u
        w_new, u_new = run_op('spectral_norm', fn, vv)
        if not isinstance(u_new._data, jax.core.Tracer):
            # eager path: persist the advanced u (computed once, inside
            # the op). Under an outer trace the buffer is left untouched —
            # writing a tracer into persistent state would escape it.
            lyr._buffers[name + '_u']._data = u_new._data
        lyr.__dict__[name] = w_new
        return None
    layer._sn_hook = layer.register_forward_pre_hook(hook)
    hook(layer, None)
    return layer


def remove_spectral_norm(layer, name='weight'):
    v = layer._parameters.pop(name + '_orig')
    layer._buffers.pop(name + '_u', None)
    layer.__dict__.pop(name, None)
    layer.add_parameter(name, v)  # same object: flags preserved
    if hasattr(layer, '_sn_hook'):
        layer._sn_hook.remove()
    return layer
