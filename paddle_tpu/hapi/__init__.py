"""hapi (reference: python/paddle/hapi/)."""
import numpy as np

from .model import Model  # noqa: F401
from . import callbacks  # noqa: F401


def summary(net, input_size=None, dtypes=None, input=None):
    """Model.summary (reference: hapi/model_summary.py). With input_size
    (or a concrete `input`), a probe forward records each sublayer's
    output shape, like the reference's hook-based summary."""
    out_shapes = {}
    if input_size is not None or input is not None:
        from ..framework.core import Tensor
        import jax.numpy as jnp

        if input is None:
            sizes = input_size if isinstance(input_size, (list, tuple)) \
                and input_size and isinstance(input_size[0], (list, tuple)) \
                else [tuple(input_size)]
            if isinstance(dtypes, (list, tuple)):
                dts = list(dtypes) + ['float32'] * (len(sizes) - len(dtypes))
            else:
                dts = [dtypes or 'float32'] * len(sizes)

            def _dim(d):
                # reference _check_shape: None / -1 batch dims become 1
                return 1 if d is None or int(d) < 0 else int(d)
            probes = [Tensor(jnp.zeros(tuple(_dim(d) for d in s),
                                       jnp.dtype(dt)))
                      for s, dt in zip(sizes, dts)]
        else:
            probes = input if isinstance(input, (list, tuple)) else [input]

        removers = []
        for name, layer in net.named_sublayers(include_self=True):
            def hook(lyr, ins, out, _name=name):
                o = out[0] if isinstance(out, (list, tuple)) and out else out
                shape = getattr(o, 'shape', None)
                if shape is not None:
                    out_shapes[_name] = list(shape)
                return None
            removers.append(layer.register_forward_post_hook(hook))
        # snapshot PER-LAYER modes: net.train() would flatten a frozen
        # submodule's eval state
        modes = [(layer, layer.training)
                 for _, layer in net.named_sublayers(include_self=True)]
        try:
            net.eval()
            net(*probes)
        finally:
            for layer, was in modes:
                layer.training = was
            for r in removers:
                r.remove()

    rows = []
    total_params = 0
    trainable = 0
    for name, layer in net.named_sublayers(include_self=True):
        n_params = 0
        for _, p in layer._parameters.items():
            if p is None:
                continue
            n = int(np.prod(p.shape)) if p.shape else 1
            n_params += n
        if layer is not net:
            rows.append((name or layer.__class__.__name__,
                         layer.__class__.__name__,
                         str(out_shapes.get(name, '-')), n_params))
    for _, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total_params += n
        if not p.stop_gradient:
            trainable += n
    lines = ['-' * 80,
             '%-26s %-18s %-20s %10s' % ('Layer (type)', 'Type',
                                         'Output Shape', 'Param #'),
             '=' * 80]
    for name, typ, shape, n in rows:
        lines.append('%-26s %-18s %-20s %10d' % (name[:26], typ[:18],
                                                 shape[:20], n))
    lines += ['=' * 80,
              'Total params: {:,}'.format(total_params),
              'Trainable params: {:,}'.format(trainable),
              'Non-trainable params: {:,}'.format(total_params - trainable),
              '-' * 80]
    print('\n'.join(lines))
    return {'total_params': total_params, 'trainable_params': trainable}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """FLOPs estimate (reference: hapi/dynamic_flops.py) — counts matmul/conv
    macs from layer shapes."""
    from ..nn.layer.common import Linear
    from ..nn.layer.conv import _ConvNd
    total = 0
    spatial = list(input_size[2:]) if len(input_size) > 2 else []
    for _, layer in net.named_sublayers(include_self=True):
        if isinstance(layer, Linear):
            total += 2 * layer._in_features * layer._out_features
        elif isinstance(layer, _ConvNd):
            k = int(np.prod(layer._kernel_size))
            out_spatial = int(np.prod(spatial)) if spatial else 1
            total += 2 * k * layer._in_channels * layer._out_channels * \
                out_spatial // (layer._groups * 4)
    if print_detail:
        print('Estimated FLOPs: {:,}'.format(total))
    return total
