"""hapi (reference: python/paddle/hapi/)."""
import numpy as np

from .model import Model  # noqa: F401
from . import callbacks  # noqa: F401


def summary(net, input_size=None, dtypes=None, input=None):
    """Model.summary (reference: hapi/model_summary.py)."""
    rows = []
    total_params = 0
    trainable = 0
    for name, layer in net.named_sublayers(include_self=True):
        n_params = 0
        for _, p in layer._parameters.items():
            if p is None:
                continue
            n = int(np.prod(p.shape)) if p.shape else 1
            n_params += n
        if layer is not net:
            rows.append((name or layer.__class__.__name__,
                         layer.__class__.__name__, n_params))
    for _, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total_params += n
        if not p.stop_gradient:
            trainable += n
    lines = ['-' * 64,
             '%-30s %-20s %10s' % ('Layer (type)', 'Type', 'Param #'),
             '=' * 64]
    for name, typ, n in rows:
        lines.append('%-30s %-20s %10d' % (name[:30], typ[:20], n))
    lines += ['=' * 64,
              'Total params: {:,}'.format(total_params),
              'Trainable params: {:,}'.format(trainable),
              'Non-trainable params: {:,}'.format(total_params - trainable),
              '-' * 64]
    print('\n'.join(lines))
    return {'total_params': total_params, 'trainable_params': trainable}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """FLOPs estimate (reference: hapi/dynamic_flops.py) — counts matmul/conv
    macs from layer shapes."""
    from ..nn.layer.common import Linear
    from ..nn.layer.conv import _ConvNd
    total = 0
    spatial = list(input_size[2:]) if len(input_size) > 2 else []
    for _, layer in net.named_sublayers(include_self=True):
        if isinstance(layer, Linear):
            total += 2 * layer._in_features * layer._out_features
        elif isinstance(layer, _ConvNd):
            k = int(np.prod(layer._kernel_size))
            out_spatial = int(np.prod(spatial)) if spatial else 1
            total += 2 * k * layer._in_channels * layer._out_channels * \
                out_spatial // (layer._groups * 4)
    if print_detail:
        print('Estimated FLOPs: {:,}'.format(total))
    return total
