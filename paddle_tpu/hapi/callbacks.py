"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""
import os
import time

import numpy as np

__all__ = ['Callback', 'ProgBarLogger', 'ModelCheckpoint', 'LRScheduler',
           'EarlyStopping', 'VisualDL', 'ReduceLROnPlateau',
           'TelemetryCallback', 'config_callbacks']


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = callbacks or []

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith('on_'):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return call
        raise AttributeError(name)


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get('steps', None)
        self._t0 = time.time()
        if self.verbose:
            print('Epoch %d/%d' % (epoch + 1, self.params.get('epochs', 1)))

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            msgs = []
            for k, v in (logs or {}).items():
                if isinstance(v, (list, tuple, np.ndarray)):
                    v = np.asarray(v).reshape(-1)
                    msgs.append('%s: %.4f' % (k, float(v[0])))
                elif isinstance(v, (int, float)):
                    msgs.append('%s: %.4f' % (k, v))
            dt = time.time() - self._t0
            print('step %s/%s - %s - %.0fms/step' % (
                step + 1, self.steps or '?', ' - '.join(msgs),
                1000 * dt / (step + 1)))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            print('Epoch %d done, %.1fs' % (epoch + 1, time.time() - self._t0))


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, 'final'))


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, '_optimizer', None)
        from ..optimizer.lr import LRScheduler as Sched
        if opt and isinstance(opt._lr, Sched):
            return opt._lr
        return None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s:
            s.step()


def _monitor_op(mode, monitor, min_delta):
    """Shared monitor-direction resolution (EarlyStopping /
    ReduceLROnPlateau): returns (op, signed_min_delta)."""
    if mode == 'max' or (mode == 'auto' and 'acc' in monitor):
        return np.greater, abs(min_delta)
    return np.less, -abs(min_delta)


def _monitor_value(logs, monitor):
    v = (logs or {}).get(monitor)
    if v is None:
        return None
    if isinstance(v, (list, tuple, np.ndarray)):
        v = float(np.asarray(v).reshape(-1)[0])
    return v


class EarlyStopping(Callback):
    def __init__(self, monitor='loss', mode='auto', patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        self.monitor_op, self.min_delta = _monitor_op(mode, monitor,
                                                      min_delta)
        self.best = None
        self.wait = 0

    def on_eval_end(self, logs=None):
        current = _monitor_value(logs, self.monitor)
        if current is None:
            return
        if self.best is None or self.monitor_op(current - self.min_delta,
                                                self.best):
            self.best = current
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class VisualDL(Callback):
    """CSV/TSV metric writer (visualdl itself is not in this image; the
    file format is tensorboard-text compatible)."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        self._f = None
        self._step = 0

    def on_train_begin(self, logs=None):
        os.makedirs(self.log_dir, exist_ok=True)
        self._f = open(os.path.join(self.log_dir, 'metrics.tsv'), 'a')

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple, np.ndarray)):
                v = float(np.asarray(v).reshape(-1)[0])
            if isinstance(v, (int, float)):
                self._f.write('%d\t%s\t%.6f\n' % (self._step, k, v))

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode='train'):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    cbk_list = CallbackList(cbks)
    cbk_list.set_model(model)
    params = {'batch_size': batch_size, 'epochs': epochs, 'steps': steps,
              'verbose': verbose, 'metrics': metrics or []}
    cbk_list.set_params(params)
    return cbk_list


class TelemetryCallback(Callback):
    """Feed Model.fit progress into the monitor registry
    (paddle_tpu/monitor) so a training run is scrapeable while it runs:

        model.fit(..., callbacks=[TelemetryCallback()])
        # elsewhere: monitor.MetricsServer().start() and curl /metrics

    Step wall time (histogram), steps/examples counters, examples/s and
    loss gauges, current epoch; optionally one RuntimeSampler capture
    every `sample_every` steps (RSS / live arrays / cache sizes move
    slowly — per-step sampling would cost more than it tells).
    """

    def __init__(self, registry=None, sample_every=50, clock=None):
        super().__init__()
        from ..monitor import RuntimeSampler, default_registry
        r = registry if registry is not None else default_registry()
        self.registry = r
        self.sample_every = int(sample_every)
        self._clock = clock or time.monotonic
        self._t0 = None
        self._seen = 0
        self._sampler = RuntimeSampler(registry=r) if sample_every else None
        self._m_steps = r.counter('train_steps_total', 'train steps run')
        self._m_examples = r.counter('train_examples_total',
                                     'examples consumed')
        # callback-only families come from the single-source schema
        # table (monitor/telemetry.py TRAIN_LOOP_FAMILIES) so the
        # committed metrics baseline covers them
        from ..monitor.telemetry import record_train_loop_schema
        loop = record_train_loop_schema(r)
        self._m_step_time = loop['train_step_duration_seconds']
        self._m_eps = r.gauge('train_examples_per_second',
                              'examples/s of the last step')
        self._m_loss = r.gauge('train_loss', 'loss of the last step')
        self._m_epoch = loop['train_epoch']
        from ..monitor import tracing as _tracing
        self._tracer = _tracing.default_tracer()
        self._epoch_span = None

    def on_epoch_begin(self, epoch, logs=None):
        self._m_epoch.set(epoch)
        self._finish_epoch_span()
        if self._tracer.enabled:
            self._epoch_span = self._tracer.start_span(
                'train.epoch', tags={'epoch': epoch})

    def _finish_epoch_span(self):
        if self._epoch_span is not None:
            self._epoch_span.finish()
            self._epoch_span = None

    def on_epoch_end(self, epoch, logs=None):
        self._finish_epoch_span()

    def on_train_batch_begin(self, step, logs=None):
        self._t0 = self._clock()

    def on_train_batch_end(self, step, logs=None):
        dt = (self._clock() - self._t0) if self._t0 is not None else None
        self._t0 = None
        if dt is not None:
            self._m_step_time.observe(dt)
        self._m_steps.inc()
        batch = self.params.get('batch_size')
        if batch:
            self._m_examples.inc(batch)
            if dt:
                self._m_eps.set(batch / dt)
        loss = _monitor_value(logs, 'loss')
        if loss is not None:
            self._m_loss.set(loss)
        self._seen += 1
        if self._sampler is not None and self._seen % self.sample_every == 0:
            self._sampler.sample_once()

    def on_train_end(self, logs=None):
        self._finish_epoch_span()
        if self._sampler is not None:
            self._sampler.sample_once()


class ReduceLROnPlateau(Callback):
    """Reduce optimizer LR by `factor` after `patience` evals without
    improvement of `monitor` (reference hapi/callbacks.py:956)."""

    def __init__(self, monitor='loss', factor=0.1, patience=10, verbose=1,
                 mode='auto', min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        if factor >= 1.0:
            raise ValueError('ReduceLROnPlateau does not support a factor '
                             '>= 1.0')
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.monitor_op, self.min_delta = _monitor_op(mode, monitor,
                                                      min_delta)
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0

    def on_eval_end(self, logs=None):
        current = _monitor_value(logs, self.monitor)
        if current is None:
            return
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self.best is None or self.monitor_op(current - self.min_delta,
                                                self.best):
            self.best = current
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                opt = getattr(self.model, '_optimizer', None)
                if opt is not None:
                    try:
                        old = float(opt.get_lr())
                        new = max(old * self.factor, self.min_lr)
                        if old - new > 1e-12:
                            opt.set_lr(new)
                            if self.verbose:
                                print('ReduceLROnPlateau: lr %g -> %g'
                                      % (old, new))
                    except RuntimeError:
                        # LR driven by a scheduler: the reference callback
                        # warns and leaves the scheduler in charge
                        if self.verbose:
                            print('ReduceLROnPlateau skipped: optimizer '
                                  'lr is scheduler-driven')
                self.cooldown_counter = self.cooldown
                self.wait = 0
