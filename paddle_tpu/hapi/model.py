"""hapi Model (reference: python/paddle/hapi/model.py:878 Model, fit :1523).

One adapter, not two: the reference needs StaticGraphAdapter + DynamicGraph
Adapter; here train_batch always runs through the jitted TrainStep
(framework/functional.py), which IS the static path — eager fallback only
when the model structure defeats functionalization.
"""
import contextlib
import os

import numpy as np

from ..framework.core import Tensor, no_grad_guard
from ..framework import functional as func_mod
from ..distributed.supervisor import Preempted
from ..metric import Metric
from .callbacks import config_callbacks

__all__ = ['Model']


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._loss = None
        self._optimizer = None
        self._metrics = []
        self._train_step = None
        self._perf_timeline = None    # StepTimeline while fit() runs
        self.stop_training = False
        self.mode = 'train'

    # -- setup --------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        self._train_step = None
        return self

    def _ensure_train_step(self):
        if self._train_step is None:
            loss_fn = self._loss
            if not callable(loss_fn):
                raise ValueError("call prepare(loss=...) first")
            self._train_step = func_mod.TrainStep(self.network, loss_fn,
                                                  self._optimizer)
        return self._train_step

    # -- batch-level API ----------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        tl = self._perf_timeline
        dispatch = tl.phase('host_dispatch') if tl is not None \
            else contextlib.nullcontext()
        block = tl.phase('device_block') if tl is not None \
            else contextlib.nullcontext()
        try:
            step = self._ensure_train_step()
            with dispatch:
                loss = step(inputs, labels)
        except Exception:
            # eager fallback: run unfused (still correct)
            loss = self._eager_train_batch(inputs, labels)
        with block:
            # blocks until the device result is ready — the
            # dispatch-to-materialize gap is the device-bound phase
            loss_np = loss.numpy()
        metrics = self._update_metrics(inputs, labels)
        return [loss_np] if not metrics else ([loss_np], metrics)

    def _eager_train_batch(self, inputs, labels):
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labs = labels if isinstance(labels, (list, tuple)) else [labels]
        outs = self.network(*ins)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        loss = self._loss(*outs, *labs)
        loss.backward()
        self._optimizer.step()
        self._optimizer.clear_grad()
        return loss

    @no_grad_guard()
    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labs = labels if isinstance(labels, (list, tuple)) else [labels]
        outs = self.network(*ins)
        outs_l = outs if isinstance(outs, (list, tuple)) else [outs]
        loss = self._loss(*outs_l, *labs) if self._loss else None
        metrics = []
        for m in self._metrics:
            res = m.compute(*outs_l, *labs)
            m.update(res if not isinstance(res, (list, tuple)) else res[0],
                     *labs)
            metrics.append(m.accumulate())
        out = [loss.numpy()] if loss is not None else []
        return (out, metrics) if metrics else out

    @no_grad_guard()
    def predict_batch(self, inputs):
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outs = self.network(*ins)
        outs_l = outs if isinstance(outs, (list, tuple)) else [outs]
        return [o.numpy() for o in outs_l]

    def _update_metrics(self, inputs, labels):
        if not self._metrics:
            return []
        with no_grad_guard():
            ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
            labs = labels if isinstance(labels, (list, tuple)) else [labels]
            outs = self.network(*ins)
            outs_l = outs if isinstance(outs, (list, tuple)) else [outs]
            accum = []
            for m in self._metrics:
                res = m.compute(*outs_l, *labs)
                m.update(res if not isinstance(res, (list, tuple)) else res[0])
                accum.append(m.accumulate())
        return accum

    # -- loop API -----------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, supervisor=None):
        from ..io import DataLoader, Dataset
        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        if eval_data is not None and isinstance(eval_data, Dataset):
            eval_loader = DataLoader(eval_data, batch_size=batch_size,
                                     num_workers=num_workers)
        else:
            eval_loader = eval_data

        steps = None
        try:
            steps = len(train_loader)
        except TypeError:
            pass
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=steps, log_freq=log_freq,
                                save_freq=save_freq, save_dir=save_dir,
                                verbose=verbose, batch_size=batch_size,
                                metrics=[m.name() for m in self._metrics])
        cbks.on_train_begin()
        self.stop_training = False
        from ..monitor.perf import CompileWatchdog, StepTimeline
        wd = CompileWatchdog(owner=self, name='Model.fit')
        tl = StepTimeline()
        self._perf_timeline = tl
        # everything up to and including the FIRST eval pass may
        # legitimately compile (train step on epoch 0, eval's eager ops
        # on their first shapes); compiles after that barrier are
        # steady-state recompiles
        warmup_epoch = 0 if eval_loader is None \
            else min(eval_freq, epochs) - 1
        it = 0
        cursor = None
        if supervisor is not None:
            # elastic resume: the cursor restores params/optimizer and
            # says how much completed work to skip deterministically
            cursor = supervisor.restore(self)
            if cursor is not None:
                it = cursor.global_step
        logs = {}
        try:
            for epoch in range(epochs):
                if cursor is not None and epoch < cursor.epoch:
                    continue          # fully-trained epoch from before
                for m in self._metrics:
                    m.reset()
                cbks.on_epoch_begin(epoch)
                logs = {}
                if supervisor is not None:
                    supervisor.begin_epoch(epoch)
                if hasattr(train_loader, 'set_epoch'):
                    # pin streaming pipelines (data.IngestPipeline) to
                    # fit's epoch counter so their per-epoch shuffle
                    # tracks the loop, not their own iteration count. A
                    # staged resume cursor overrides this inside iter().
                    train_loader.set_epoch(epoch)
                # pipelines that prefetch overlap producer work with the
                # dispatched step, so raw next() time would under- or
                # over-charge input: take their measured queue-wait
                # instead (the honest data_wait under overlap)
                pipe_wait = hasattr(train_loader, 'last_wait_s')
                data_iter = iter(train_loader)
                step = 0
                if cursor is not None and epoch == cursor.epoch:
                    step = supervisor.fast_forward(data_iter)
                    cursor = None
                while True:
                    try:
                        if pipe_wait:
                            batch = next(data_iter)
                            tl.record('data_wait',
                                      train_loader.last_wait_s)
                        else:
                            with tl.phase('data_wait'):
                                batch = next(data_iter)
                    except StopIteration:
                        tl.discard()
                        break
                    cbks.on_train_batch_begin(step)
                    ins, labs = self._split_batch(batch)
                    res = self.train_batch(ins, labs)
                    tl.end_step()
                    logs = self._pack_logs(res)
                    cbks.on_train_batch_end(step, logs)
                    step += 1
                    it += 1
                    if supervisor is not None:
                        try:
                            supervisor.on_step(self, epoch, step, it)
                        except Preempted:
                            # urgent checkpoint already written; stop as
                            # cleanly as num_iters would
                            self.stop_training = True
                            break
                    if num_iters is not None and it >= num_iters:
                        self.stop_training = True
                        break
                if eval_loader is not None and \
                        (epoch + 1) % eval_freq == 0:
                    eval_logs = self.evaluate(eval_loader, verbose=0,
                                              num_workers=num_workers)
                    logs.update({'eval_' + k: v
                                 for k, v in eval_logs.items()})
                    cbks.on_eval_end(eval_logs)
                if epoch == warmup_epoch:
                    wd.declare_warmup('Model.fit epoch %d done' % epoch)
                cbks.on_epoch_end(epoch, logs)
                if self.stop_training:
                    break
            cbks.on_train_end(logs)
        finally:
            self._perf_timeline = None
            wd.close()
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        from ..io import DataLoader, Dataset
        loader = DataLoader(eval_data, batch_size=batch_size,
                            num_workers=num_workers) \
            if isinstance(eval_data, Dataset) else eval_data
        for m in self._metrics:
            m.reset()
        losses = []
        metrics = []
        for batch in loader:
            ins, labs = self._split_batch(batch)
            res = self.eval_batch(ins, labs)
            if isinstance(res, tuple):
                loss_list, metrics = res
            else:
                loss_list = res
            if loss_list:
                losses.append(np.asarray(loss_list[0]).reshape(-1)[0])
        logs = {}
        if losses:
            logs['loss'] = float(np.mean(losses))
        for m, v in zip(self._metrics, metrics):
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = v if isinstance(v, (list, tuple)) else [v]
            for n, val in zip(names, vals):
                logs[n] = val
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        from ..io import DataLoader, Dataset
        loader = DataLoader(test_data, batch_size=batch_size,
                            num_workers=num_workers) \
            if isinstance(test_data, Dataset) else test_data
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch, predict=True)
            outputs.append(self.predict_batch(ins))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    def _split_batch(self, batch, predict=False):
        if isinstance(batch, (list, tuple)):
            if predict:
                # drop trailing labels the dataset may carry: feed only as
                # many inputs as forward accepts
                import inspect
                try:
                    sig = inspect.signature(self.network.forward)
                    n_in = len([p for p in sig.parameters.values()
                                if p.kind in (p.POSITIONAL_ONLY,
                                              p.POSITIONAL_OR_KEYWORD)
                                and p.default is p.empty])
                    return list(batch[:max(n_in, 1)]), None
                except (TypeError, ValueError):
                    return list(batch), None
            if len(batch) >= 2:
                n_lab = len(self._labels) if self._labels else 1
                return list(batch[:-n_lab]), list(batch[-n_lab:])
            return list(batch), None
        return [batch], None

    def _pack_logs(self, res):
        logs = {}
        if isinstance(res, tuple):
            loss_list, metrics = res
        else:
            loss_list, metrics = res, []
        if loss_list:
            logs['loss'] = np.asarray(loss_list[0]).reshape(-1)
        for m, v in zip(self._metrics, metrics):
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = v if isinstance(v, (list, tuple)) else [v]
            for n, val in zip(names, vals):
                logs[n] = val
        return logs

    # -- persistence --------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io_save import save as _save
        if training:
            _save(self.network.state_dict(), path + '.pdparams')
            if self._optimizer:
                _save(self._optimizer.state_dict(), path + '.pdopt')
        else:
            from .. import jit
            jit.save(self.network, path, input_spec=self._inputs)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io_save import load as _load
        state = _load(path + '.pdparams')
        self.network.set_state_dict(state)
        if not reset_optimizer and self._optimizer and \
                os.path.exists(path + '.pdopt'):
            self._optimizer.set_state_dict(_load(path + '.pdopt'))
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from . import summary as summary_fn
        return summary_fn(self.network, input_size, dtypes=dtype)

    def summary_perf(self, inputs, labels=None, step_seconds=None,
                     registry=None):
        """Cost-model companion to ``summary()``: compile the jitted
        train step for this batch and report analytic FLOPs, bytes
        accessed, arithmetic intensity, roofline bound and ideal step
        time; with a measured ``step_seconds``, also ``mfu_est`` and
        ``roofline_frac``. Publishes the perf gauges as a side effect.
        Requires ``prepare(loss=..., optimizer=...)``; returns None when
        the backend exposes no cost model."""
        from ..monitor.perf import costmodel
        step = self._ensure_train_step()
        compiled = step.compiled_executable(inputs, labels)
        est = costmodel.estimate(compiled, step_seconds=step_seconds)
        if est is not None:
            costmodel.record(est, registry=registry)
        return est
