"""ctypes binding for datafeed.cc (MultiSlot parser) + python fallback.

Parity: framework/data_feed.h:208 MultiSlotDataFeed slot format.
"""
import ctypes

import numpy as np

from . import load_library

__all__ = ['parse_multislot']


def _parse_native(text, slot_types):
    lib = load_library('datafeed')
    lib.df_parse.restype = ctypes.c_void_p
    lib.df_parse.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
                             ctypes.c_void_p]
    lib.df_num_instances.restype = ctypes.c_int64
    lib.df_num_instances.argtypes = [ctypes.c_void_p]
    lib.df_slot_size.restype = ctypes.c_int64
    lib.df_slot_size.argtypes = [ctypes.c_void_p, ctypes.c_int]
    for f in (lib.df_copy_slot_fvals, lib.df_copy_slot_ivals,
              lib.df_copy_slot_offsets):
        f.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p]
    lib.df_free.argtypes = [ctypes.c_void_p]

    data = text.encode() if isinstance(text, str) else text
    types_arr = np.asarray([0 if t == 'float' else 1 for t in slot_types],
                           np.int32)
    h = lib.df_parse(data, len(data), len(slot_types), types_arr.ctypes.data)
    try:
        n_inst = lib.df_num_instances(h)
        slots = []
        for s, t in enumerate(slot_types):
            size = lib.df_slot_size(h, s)
            offsets = np.empty(n_inst + 1, np.int64)
            lib.df_copy_slot_offsets(h, s, offsets.ctypes.data)
            if t == 'float':
                vals = np.empty(size, np.float32)
                lib.df_copy_slot_fvals(h, s, vals.ctypes.data)
            else:
                vals = np.empty(size, np.int64)
                lib.df_copy_slot_ivals(h, s, vals.ctypes.data)
            slots.append((vals, offsets))
        return slots, int(n_inst)
    finally:
        lib.df_free(h)


def _parse_python(text, slot_types):
    n_slots = len(slot_types)
    vals = [[] for _ in range(n_slots)]
    offsets = [[0] for _ in range(n_slots)]
    n_inst = 0
    for line in text.splitlines():
        toks = line.split()
        pos = 0
        row = [[] for _ in range(n_slots)]
        ok = True
        for s in range(n_slots):
            if pos >= len(toks):
                ok = False
                break
            try:
                n = int(toks[pos])
            except ValueError:
                ok = False
                break
            pos += 1
            conv = float if slot_types[s] == 'float' else int
            try:
                row[s] = [conv(t) for t in toks[pos:pos + n]]
            except ValueError:
                ok = False
                break
            if len(row[s]) != n:
                ok = False
                break
            pos += n
        if not ok:
            continue
        for s in range(n_slots):
            vals[s].extend(row[s])
            offsets[s].append(len(vals[s]))
        n_inst += 1
    out = []
    for s, t in enumerate(slot_types):
        dt = np.float32 if t == 'float' else np.int64
        out.append((np.asarray(vals[s], dt), np.asarray(offsets[s], np.int64)))
    return out, n_inst


def parse_multislot(text, slot_types, force_python=False):
    """Parse MultiSlot text -> [(values, csr_offsets)] per slot + count."""
    if not force_python:
        try:
            return _parse_native(text, slot_types)
        except Exception:
            pass
    return _parse_python(text, slot_types)
