// Sharded in-memory graph store with weighted neighbor sampling.
//
// TPU-native counterpart of the reference's distributed graph engine core:
//   paddle/fluid/distributed/table/common_graph_table.{h,cc}  (GraphShard,
//   load_edges/load_nodes, random_sample_neighboors)
//   paddle/fluid/distributed/table/graph/graph_weighted_sampler.cc (alias
//   method weighted sampling)
//
// C API (ctypes-bound from python/native/graph_store.py). Thread-safe per
// shard; alias tables built lazily per node and cached. The RPC layer
// (GraphPyService parity) lives in python — this library is the hot path:
// parsing, storage, sampling.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <random>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct AliasTable {
  // Walker alias method for O(1) weighted sampling.
  std::vector<float> prob;
  std::vector<int32_t> alias;
  void build(const std::vector<float>& w) {
    size_t n = w.size();
    prob.assign(n, 0.f);
    alias.assign(n, 0);
    double sum = 0;
    for (float x : w) sum += x;
    if (sum <= 0) {  // degenerate: uniform
      for (size_t i = 0; i < n; i++) { prob[i] = 1.f; alias[i] = (int32_t)i; }
      return;
    }
    std::vector<double> p(n);
    for (size_t i = 0; i < n; i++) p[i] = w[i] * n / sum;
    std::vector<int32_t> small, large;
    for (size_t i = 0; i < n; i++)
      (p[i] < 1.0 ? small : large).push_back((int32_t)i);
    while (!small.empty() && !large.empty()) {
      int32_t s = small.back(); small.pop_back();
      int32_t l = large.back(); large.pop_back();
      prob[s] = (float)p[s];
      alias[s] = l;
      p[l] = p[l] - (1.0 - p[s]);
      (p[l] < 1.0 ? small : large).push_back(l);
    }
    for (int32_t s : small) { prob[s] = 1.f; alias[s] = s; }
    for (int32_t l : large) { prob[l] = 1.f; alias[l] = l; }
  }
  inline int32_t draw(std::mt19937* rng) const {
    std::uniform_real_distribution<float> uf(0.f, 1.f);
    std::uniform_int_distribution<int32_t> ui(0, (int32_t)prob.size() - 1);
    int32_t i = ui(*rng);
    return uf(*rng) < prob[i] ? i : alias[i];
  }
};

struct Node {
  std::vector<int64_t> nbrs;
  std::vector<float> weights;   // empty => uniform
  std::vector<float> feat;      // optional dense feature
  AliasTable* alias = nullptr;  // lazily built, owned
  ~Node() { delete alias; }
};

struct Shard {
  std::unordered_map<int64_t, Node> nodes;
  std::mutex mu;
};

struct GraphStore {
  std::vector<Shard> shards;
  std::atomic<int64_t> edge_count{0};
  explicit GraphStore(int n) : shards(n) {}
  inline Shard& shard_of(int64_t id) {
    return shards[(uint64_t)id % shards.size()];
  }
};

thread_local std::mt19937 g_rng{std::random_device{}()};

// Append one edge keeping weights consistent when weighted and unweighted
// inserts are mixed for the same node: a missing weight means 1.0, and a
// late first weight backfills 1.0 for all earlier neighbors, so
// weights.size() is always 0 or nbrs.size() (the sampler relies on this).
inline void push_edge(Node& nd, int64_t dst, bool has_w, float w) {
  nd.nbrs.push_back(dst);
  if (has_w) {
    if (nd.weights.size() + 1 < nd.nbrs.size())
      nd.weights.resize(nd.nbrs.size() - 1, 1.f);
    nd.weights.push_back(w);
  } else if (!nd.weights.empty()) {
    nd.weights.push_back(1.f);
  }
  delete nd.alias;
  nd.alias = nullptr;
}

}  // namespace

extern "C" {

void* gs_create(int shard_num) {
  if (shard_num <= 0) shard_num = 16;
  return new GraphStore(shard_num);
}

void gs_free(void* h) { delete static_cast<GraphStore*>(h); }

void gs_seed(uint64_t seed) { g_rng.seed((unsigned)seed); }

int64_t gs_add_edges(void* h, const int64_t* src, const int64_t* dst,
                     const float* weight, int64_t n) {
  auto* gs = static_cast<GraphStore*>(h);
  for (int64_t i = 0; i < n; i++) {
    Shard& sh = gs->shard_of(src[i]);
    std::lock_guard<std::mutex> lk(sh.mu);
    Node& nd = sh.nodes[src[i]];
    push_edge(nd, dst[i], weight != nullptr, weight ? weight[i] : 1.f);
  }
  gs->edge_count += n;
  return n;
}

int64_t gs_add_nodes(void* h, const int64_t* ids, int64_t n) {
  auto* gs = static_cast<GraphStore*>(h);
  for (int64_t i = 0; i < n; i++) {
    Shard& sh = gs->shard_of(ids[i]);
    std::lock_guard<std::mutex> lk(sh.mu);
    sh.nodes[ids[i]];
  }
  return n;
}

// erase nodes and their outgoing edges (reference remove_graph_node)
int64_t gs_remove_nodes(void* h, const int64_t* ids, int64_t n) {
  auto* gs = static_cast<GraphStore*>(h);
  int64_t removed = 0;
  for (int64_t i = 0; i < n; i++) {
    Shard& sh = gs->shard_of(ids[i]);
    std::lock_guard<std::mutex> lk(sh.mu);
    auto it = sh.nodes.find(ids[i]);
    if (it != sh.nodes.end()) {
      gs->edge_count -= (int64_t)it->second.nbrs.size();
      sh.nodes.erase(it);
      removed++;
    }
  }
  return removed;
}

// text file: "src \t dst [\t weight]" per line (reference load_edges format)
int64_t gs_load_edge_file(void* h, const char* path, int reversed) {
  FILE* f = fopen(path, "r");
  if (!f) return -1;
  auto* gs = static_cast<GraphStore*>(h);
  char line[4096];
  int64_t count = 0;
  while (fgets(line, sizeof(line), f)) {
    int64_t a, b;
    float w = 1.f;
    int got = sscanf(line, "%ld%ld%f", &a, &b, &w);
    if (got < 2) continue;
    int64_t s = reversed ? b : a, d = reversed ? a : b;
    Shard& sh = gs->shard_of(s);
    std::lock_guard<std::mutex> lk(sh.mu);
    Node& nd = sh.nodes[s];
    push_edge(nd, d, got >= 3, w);
    count++;
  }
  fclose(f);
  gs->edge_count += count;
  return count;
}

int64_t gs_node_count(void* h) {
  auto* gs = static_cast<GraphStore*>(h);
  int64_t n = 0;
  for (auto& sh : gs->shards) n += (int64_t)sh.nodes.size();
  return n;
}

int64_t gs_edge_count(void* h) {
  return static_cast<GraphStore*>(h)->edge_count.load();
}

int64_t gs_get_degree(void* h, const int64_t* ids, int64_t n, int64_t* out) {
  auto* gs = static_cast<GraphStore*>(h);
  for (int64_t i = 0; i < n; i++) {
    Shard& sh = gs->shard_of(ids[i]);
    std::lock_guard<std::mutex> lk(sh.mu);
    auto it = sh.nodes.find(ids[i]);
    out[i] = it == sh.nodes.end() ? 0 : (int64_t)it->second.nbrs.size();
  }
  return n;
}

// weighted (alias) or uniform sampling WITH replacement; pad = fill value
// for nodes with no neighbors. out is [n, k] row-major.
int64_t gs_sample_neighbors(void* h, const int64_t* ids, int64_t n, int k,
                            int64_t* out, int64_t pad) {
  auto* gs = static_cast<GraphStore*>(h);
  for (int64_t i = 0; i < n; i++) {
    Shard& sh = gs->shard_of(ids[i]);
    std::lock_guard<std::mutex> lk(sh.mu);
    auto it = sh.nodes.find(ids[i]);
    if (it == sh.nodes.end() || it->second.nbrs.empty()) {
      for (int j = 0; j < k; j++) out[i * k + j] = pad;
      continue;
    }
    Node& nd = it->second;
    if (!nd.weights.empty()) {
      if (!nd.alias) {
        nd.alias = new AliasTable();
        nd.alias->build(nd.weights);
      }
      for (int j = 0; j < k; j++)
        out[i * k + j] = nd.nbrs[nd.alias->draw(&g_rng)];
    } else {
      std::uniform_int_distribution<size_t> ui(0, nd.nbrs.size() - 1);
      for (int j = 0; j < k; j++) out[i * k + j] = nd.nbrs[ui(g_rng)];
    }
  }
  return n;
}

// sample `k` distinct node ids from the store (reference
// random_sample_nodes): reservoir over shards.
int64_t gs_random_sample_nodes(void* h, int64_t k, int64_t* out) {
  auto* gs = static_cast<GraphStore*>(h);
  int64_t seen = 0;
  for (auto& sh : gs->shards) {
    std::lock_guard<std::mutex> lk(sh.mu);
    for (auto& kv : sh.nodes) {
      if (seen < k) {
        out[seen] = kv.first;
      } else {
        std::uniform_int_distribution<int64_t> ui(0, seen);
        int64_t j = ui(g_rng);
        if (j < k) out[j] = kv.first;
      }
      seen++;
    }
  }
  return seen < k ? seen : k;
}

// batched node iteration (reference pull_graph_list): writes up to cap ids
// from a shard starting at cursor; returns count.
int64_t gs_pull_graph_list(void* h, int shard, int64_t cursor, int64_t cap,
                           int64_t* out) {
  auto* gs = static_cast<GraphStore*>(h);
  if (shard < 0 || shard >= (int)gs->shards.size()) return 0;
  Shard& sh = gs->shards[shard];
  std::lock_guard<std::mutex> lk(sh.mu);
  int64_t idx = 0, written = 0;
  for (auto& kv : sh.nodes) {
    if (idx++ < cursor) continue;
    if (written >= cap) break;
    out[written++] = kv.first;
  }
  return written;
}

int gs_set_node_feat(void* h, int64_t id, const float* feat, int dim) {
  auto* gs = static_cast<GraphStore*>(h);
  Shard& sh = gs->shard_of(id);
  std::lock_guard<std::mutex> lk(sh.mu);
  Node& nd = sh.nodes[id];
  nd.feat.assign(feat, feat + dim);
  return 0;
}

int gs_get_node_feat(void* h, const int64_t* ids, int64_t n, int dim,
                     float* out) {
  auto* gs = static_cast<GraphStore*>(h);
  for (int64_t i = 0; i < n; i++) {
    Shard& sh = gs->shard_of(ids[i]);
    std::lock_guard<std::mutex> lk(sh.mu);
    auto it = sh.nodes.find(ids[i]);
    if (it != sh.nodes.end() && (int)it->second.feat.size() == dim) {
      memcpy(out + i * dim, it->second.feat.data(), dim * sizeof(float));
    } else {
      memset(out + i * dim, 0, dim * sizeof(float));
    }
  }
  return 0;
}

}  // extern "C"
