"""ctypes binding for graph_store.cc + pure-python fallback.

API parity target: distributed/table/common_graph_table.h:64-130 (GraphTable
ops: load edges/nodes, random_sample_neighboors, random_sample_nodes,
pull_graph_list, get/set_node_feat).
"""
import ctypes
import os

import numpy as np

from . import load_library

__all__ = ['GraphStore']


class _NativeStore:
    def __init__(self, shard_num=16, seed=None):
        self._lib = load_library('graph_store')
        lib = self._lib
        lib.gs_create.restype = ctypes.c_void_p
        lib.gs_create.argtypes = [ctypes.c_int]
        lib.gs_free.argtypes = [ctypes.c_void_p]
        lib.gs_add_edges.restype = ctypes.c_int64
        lib.gs_add_edges.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_int64]
        lib.gs_add_nodes.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_int64]
        lib.gs_remove_nodes.restype = ctypes.c_int64
        lib.gs_remove_nodes.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                        ctypes.c_int64]
        lib.gs_load_edge_file.restype = ctypes.c_int64
        lib.gs_load_edge_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_int]
        lib.gs_node_count.restype = ctypes.c_int64
        lib.gs_node_count.argtypes = [ctypes.c_void_p]
        lib.gs_edge_count.restype = ctypes.c_int64
        lib.gs_edge_count.argtypes = [ctypes.c_void_p]
        lib.gs_get_degree.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_int64, ctypes.c_void_p]
        lib.gs_sample_neighbors.restype = ctypes.c_int64
        lib.gs_sample_neighbors.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                            ctypes.c_int64, ctypes.c_int,
                                            ctypes.c_void_p, ctypes.c_int64]
        lib.gs_random_sample_nodes.restype = ctypes.c_int64
        lib.gs_random_sample_nodes.argtypes = [ctypes.c_void_p,
                                               ctypes.c_int64,
                                               ctypes.c_void_p]
        lib.gs_pull_graph_list.restype = ctypes.c_int64
        lib.gs_pull_graph_list.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                           ctypes.c_int64, ctypes.c_int64,
                                           ctypes.c_void_p]
        lib.gs_set_node_feat.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                         ctypes.c_void_p, ctypes.c_int]
        lib.gs_get_node_feat.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                         ctypes.c_int64, ctypes.c_int,
                                         ctypes.c_void_p]
        lib.gs_seed.argtypes = [ctypes.c_uint64]
        self._h = lib.gs_create(shard_num)
        self.shard_num = shard_num
        if seed is not None:
            lib.gs_seed(seed)

    def __del__(self):
        if getattr(self, '_h', None):
            self._lib.gs_free(self._h)
            self._h = None

    @staticmethod
    def _i64(a):
        return np.ascontiguousarray(np.asarray(a, dtype=np.int64))

    def add_edges(self, src, dst, weight=None):
        src = self._i64(src)
        dst = self._i64(dst)
        w = np.ascontiguousarray(np.asarray(weight, np.float32)) \
            if weight is not None else None
        return self._lib.gs_add_edges(
            self._h, src.ctypes.data, dst.ctypes.data,
            w.ctypes.data if w is not None else None, len(src))

    def add_nodes(self, ids):
        ids = self._i64(ids)
        return self._lib.gs_add_nodes(self._h, ids.ctypes.data, len(ids))

    def remove_nodes(self, ids):
        ids = self._i64(ids)
        return self._lib.gs_remove_nodes(self._h, ids.ctypes.data,
                                         len(ids))

    def load_edge_file(self, path, reversed=False):
        return self._lib.gs_load_edge_file(self._h, path.encode(),
                                           1 if reversed else 0)

    def node_count(self):
        return self._lib.gs_node_count(self._h)

    def edge_count(self):
        return self._lib.gs_edge_count(self._h)

    def degree(self, ids):
        ids = self._i64(ids)
        out = np.empty(len(ids), np.int64)
        self._lib.gs_get_degree(self._h, ids.ctypes.data, len(ids),
                                out.ctypes.data)
        return out

    def sample_neighbors(self, ids, sample_size, pad=-1):
        ids = self._i64(ids)
        out = np.empty((len(ids), sample_size), np.int64)
        self._lib.gs_sample_neighbors(self._h, ids.ctypes.data, len(ids),
                                      sample_size, out.ctypes.data, pad)
        return out

    def random_sample_nodes(self, k):
        out = np.empty(k, np.int64)
        n = self._lib.gs_random_sample_nodes(self._h, k, out.ctypes.data)
        return out[:n]

    def pull_graph_list(self, shard, cursor, cap):
        out = np.empty(cap, np.int64)
        n = self._lib.gs_pull_graph_list(self._h, shard, cursor, cap,
                                         out.ctypes.data)
        return out[:n]

    def set_node_feat(self, node_id, feat):
        feat = np.ascontiguousarray(np.asarray(feat, np.float32))
        self._lib.gs_set_node_feat(self._h, int(node_id), feat.ctypes.data,
                                   len(feat))

    def get_node_feat(self, ids, dim):
        ids = self._i64(ids)
        out = np.zeros((len(ids), dim), np.float32)
        self._lib.gs_get_node_feat(self._h, ids.ctypes.data, len(ids), dim,
                                   out.ctypes.data)
        return out


class _PythonStore:
    """Fallback with identical semantics (uniform/weighted sampling)."""

    def __init__(self, shard_num=16, seed=None):
        self.shard_num = shard_num
        self._nbrs = {}
        self._weights = {}
        self._feat = {}
        self._rng = np.random.RandomState(seed)

    def _push(self, s, d, w):
        # mirror native push_edge: a missing weight means 1.0, and a late
        # first weight backfills 1.0, so weights is always absent or
        # len(nbrs) long
        nbrs = self._nbrs.setdefault(s, [])
        nbrs.append(d)
        ws = self._weights.get(s)
        if w is not None:
            if ws is None:
                ws = self._weights.setdefault(s, [])
            if len(ws) + 1 < len(nbrs):
                ws.extend([1.0] * (len(nbrs) - 1 - len(ws)))
            ws.append(float(w))
        elif ws is not None:
            ws.append(1.0)

    def add_edges(self, src, dst, weight=None):
        for i, (s, d) in enumerate(zip(np.asarray(src), np.asarray(dst))):
            self._push(int(s), int(d),
                       float(weight[i]) if weight is not None else None)
        return len(src)

    def add_nodes(self, ids):
        for i in ids:
            self._nbrs.setdefault(int(i), [])
        return len(ids)

    def remove_nodes(self, ids):
        removed = 0
        for i in ids:
            k = int(i)
            # a node may exist with only a feature (set_node_feat creates
            # it in the native store) — treat either presence as a node
            if k in self._nbrs or k in self._feat:
                self._nbrs.pop(k, None)
                self._weights.pop(k, None)
                self._feat.pop(k, None)
                removed += 1
        return removed

    def load_edge_file(self, path, reversed=False):
        n = 0
        with open(path) as f:
            for line in f:
                parts = line.split()
                if len(parts) < 2:
                    continue
                a, b = int(parts[0]), int(parts[1])
                if reversed:
                    a, b = b, a
                self._push(a, b, float(parts[2]) if len(parts) >= 3 else None)
                n += 1
        return n

    def node_count(self):
        return len(self._nbrs)

    def edge_count(self):
        return sum(len(v) for v in self._nbrs.values())

    def degree(self, ids):
        return np.asarray([len(self._nbrs.get(int(i), [])) for i in ids],
                          np.int64)

    def sample_neighbors(self, ids, sample_size, pad=-1):
        out = np.full((len(ids), sample_size), pad, np.int64)
        for r, i in enumerate(np.asarray(ids)):
            nbrs = self._nbrs.get(int(i), [])
            if not nbrs:
                continue
            w = self._weights.get(int(i))
            if w:
                p = np.asarray(w) / np.sum(w)
                out[r] = self._rng.choice(nbrs, sample_size, p=p)
            else:
                out[r] = self._rng.choice(nbrs, sample_size)
        return out

    def random_sample_nodes(self, k):
        keys = np.asarray(list(self._nbrs.keys()), np.int64)
        if len(keys) <= k:
            return keys
        return self._rng.choice(keys, k, replace=False)

    def pull_graph_list(self, shard, cursor, cap):
        keys = [i for i in self._nbrs if i % self.shard_num == shard]
        return np.asarray(keys[cursor:cursor + cap], np.int64)

    def set_node_feat(self, node_id, feat):
        self._feat[int(node_id)] = np.asarray(feat, np.float32)

    def get_node_feat(self, ids, dim):
        out = np.zeros((len(ids), dim), np.float32)
        for r, i in enumerate(np.asarray(ids)):
            f = self._feat.get(int(i))
            if f is not None and len(f) == dim:
                out[r] = f
        return out


def GraphStore(shard_num=16, seed=None, force_python=False):
    if not force_python:
        try:
            return _NativeStore(shard_num, seed)
        except Exception:
            pass
    return _PythonStore(shard_num, seed)
