"""ctypes wrapper for the native sparse-embedding table (embedding_table.cc).

Drop-in for the common EmbeddingTable configs (uniform/zeros init,
sgd/adagrad server optimizer, no admission policy): same
pull/push/push_delta/save/load surface, so EmbeddingServer can host it
via table_kwargs backend='native'.
"""
import ctypes
import os

import numpy as np

from . import load_library

_OPTS = {'sgd': 0, 'adagrad': 1}
_INITS = {'uniform': 0, 'zeros': 1}


def _lib():
    lib = load_library('embedding_table')
    if not getattr(lib, '_emb_typed', False):
        lib.emb_create.restype = ctypes.c_void_p
        lib.emb_create.argtypes = [ctypes.c_int, ctypes.c_int,
                                   ctypes.c_float, ctypes.c_int,
                                   ctypes.c_float, ctypes.c_uint64]
        lib.emb_free.argtypes = [ctypes.c_void_p]
        lib.emb_size.restype = ctypes.c_int64
        lib.emb_size.argtypes = [ctypes.c_void_p]
        p_i64 = np.ctypeslib.ndpointer(np.int64, flags='C_CONTIGUOUS')
        p_f32 = np.ctypeslib.ndpointer(np.float32, flags='C_CONTIGUOUS')
        lib.emb_pull.argtypes = [ctypes.c_void_p, p_i64, ctypes.c_int64,
                                 p_f32, ctypes.c_int]
        lib.emb_push.argtypes = [ctypes.c_void_p, p_i64, ctypes.c_int64,
                                 p_f32, ctypes.c_float]
        lib.emb_push_delta.argtypes = [ctypes.c_void_p, p_i64,
                                       ctypes.c_int64, p_f32]
        lib.emb_export.restype = ctypes.c_int64
        lib.emb_export.argtypes = [ctypes.c_void_p, p_i64, p_f32, p_f32,
                                   ctypes.c_int64]
        lib.emb_clear.argtypes = [ctypes.c_void_p]
        lib.emb_import.argtypes = [ctypes.c_void_p, p_i64, ctypes.c_int64,
                                   p_f32, p_f32]
        lib._emb_typed = True
    return lib


class NativeEmbeddingTable:
    """One shard, rows + optimizer slots in a C++ arena (reference
    common_sparse_table.cc shard). Thread-safe (C++ mutex); row init is
    deterministic per id (splitmix64), so rebuilt shards agree."""

    def __init__(self, dim, initializer='uniform', init_scale=0.01,
                 optimizer='sgd', lr=0.01, seed=0, entry=None,
                 epsilon=1e-8, eps=None):
        if entry is not None:
            raise ValueError('NativeEmbeddingTable does not run admission '
                             'policies; use the python EmbeddingTable for '
                             'entry-gated tables')
        if optimizer not in _OPTS:
            raise ValueError('native table supports %s, got %r'
                             % (sorted(_OPTS), optimizer))
        if initializer not in _INITS:
            raise ValueError('initializer must be uniform or zeros')
        self.dim = int(dim)
        # epsilon matches the python _SparseOptimizer default (1e-8) so a
        # backend swap does not change adagrad updates; eps= kept as alias
        self._eps = float(eps if eps is not None else epsilon)
        self._optimizer = optimizer
        self._lib = _lib()
        self._ptr = self._lib.emb_create(
            self.dim, _OPTS[optimizer], ctypes.c_float(lr),
            _INITS[initializer], ctypes.c_float(init_scale),
            ctypes.c_uint64(seed))

    def __del__(self):
        ptr = getattr(self, '_ptr', None)
        if ptr:
            self._lib.emb_free(ptr)
            self._ptr = None

    def __len__(self):
        return int(self._lib.emb_size(self._ptr))

    def _ids(self, ids):
        return np.ascontiguousarray(np.asarray(ids, np.int64).reshape(-1))

    def pull(self, ids, create=True):
        ids = self._ids(ids)
        out = np.empty((len(ids), self.dim), np.float32)
        self._lib.emb_pull(self._ptr, ids, len(ids), out, int(create))
        return out

    def push(self, ids, grads):
        ids = self._ids(ids)
        grads = np.ascontiguousarray(np.asarray(grads, np.float32)
                                     .reshape(len(ids), self.dim))
        self._lib.emb_push(self._ptr, ids, len(ids), grads,
                           ctypes.c_float(self._eps))

    def push_delta(self, ids, deltas):
        ids = self._ids(ids)
        deltas = np.ascontiguousarray(np.asarray(deltas, np.float32)
                                      .reshape(len(ids), self.dim))
        self._lib.emb_push_delta(self._ptr, ids, len(ids), deltas)

    def export(self):
        # the table can grow between sizing and exporting (threaded
        # server); emb_export clamps to our capacity and reports the
        # true size under its own lock, so grow-and-retry is race-free
        cap = max(len(self), 1)
        while True:
            keys = np.zeros(cap, np.int64)
            rows = np.zeros((cap, self.dim), np.float32)
            slots = np.zeros((cap, self.dim), np.float32)
            total = int(self._lib.emb_export(self._ptr, keys, rows, slots,
                                             cap))
            if total <= cap:
                return keys[:total], rows[:total], slots[:total]
            cap = total + 1024

    def save(self, path):
        os.makedirs(path, exist_ok=True)
        keys, rows, slots = self.export()
        np.savez(os.path.join(path, 'shard.npz'), keys=keys, vals=rows,
                 slots=slots, optimizer=self._optimizer)

    def load(self, path):
        """Replace the table contents with the checkpoint (python
        EmbeddingTable.load semantics: prior rows are discarded)."""
        data = np.load(os.path.join(path, 'shard.npz'))
        saved_opt = str(data['optimizer']) if 'optimizer' in data else None
        if saved_opt is not None and saved_opt != self._optimizer:
            raise ValueError('checkpoint was written by a %r table; this '
                             'table runs %r' % (saved_opt, self._optimizer))
        keys = np.ascontiguousarray(data['keys'].astype(np.int64))
        rows = np.ascontiguousarray(data['vals'].astype(np.float32))
        slots = np.ascontiguousarray(
            data['slots'].astype(np.float32)) if 'slots' in data else \
            np.zeros_like(rows)
        self._lib.emb_clear(self._ptr)
        if len(keys):
            self._lib.emb_import(self._ptr, keys, len(keys), rows, slots)
