// Native sparse-embedding table: the host-side hot loop of the parameter
// server (reference: paddle/fluid/distributed/table/common_sparse_table.cc
// — brpc-served shard with per-id rows + optimizer slots). The python
// EmbeddingTable walks a dict row-by-row per RPC; this arena-backed
// open-hash table does batched pull/push in C++ so a shard can hold
// hundreds of millions of ids without python-loop cost.
//
// C ABI only (ctypes-loaded; no pybind in this image).
#include <cmath>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

// splitmix64: deterministic per-(key, column) init so a row's value does
// not depend on arrival order (python's shared-RNG rows do; determinism
// here is strictly better for shard rebuilds).
static inline uint64_t mix(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

struct Table {
  int dim = 0;
  int n_slots = 0;       // 0 sgd, 1 adagrad
  int opt = 0;           // 0 sgd, 1 adagrad
  int init_mode = 0;     // 0 uniform(-s, s), 1 zeros
  float lr = 0.01f;
  float init_scale = 0.01f;
  uint64_t seed = 0;
  std::mutex mu;
  std::unordered_map<int64_t, size_t> index;  // id -> arena offset
  std::vector<float> arena;                   // stride = dim * (1 + n_slots)

  size_t stride() const { return static_cast<size_t>(dim) * (1 + n_slots); }

  float* row(int64_t id, bool create) {
    auto it = index.find(id);
    if (it != index.end()) return arena.data() + it->second;
    if (!create) return nullptr;
    size_t off = arena.size();
    arena.resize(off + stride(), 0.0f);
    float* r = arena.data() + off;
    if (init_mode == 0) {
      for (int j = 0; j < dim; ++j) {
        uint64_t h = mix(static_cast<uint64_t>(id) * 0x100000001b3ULL + j +
                         seed * 0x9e3779b9ULL);
        // map to [-init_scale, init_scale)
        float u = static_cast<float>(h >> 11) * (1.0f / 9007199254740992.0f);
        r[j] = (2.0f * u - 1.0f) * init_scale;
      }
    }
    index.emplace(id, off);
    return r;
  }
};

}  // namespace

extern "C" {

void* emb_create(int dim, int opt, float lr, int init_mode, float init_scale,
                 uint64_t seed) {
  Table* t = new Table();
  t->dim = dim;
  t->opt = opt;
  t->n_slots = (opt == 1) ? 1 : 0;
  t->lr = lr;
  t->init_mode = init_mode;
  t->init_scale = init_scale;
  t->seed = seed;
  return t;
}

void emb_free(void* p) { delete static_cast<Table*>(p); }

int64_t emb_size(void* p) {
  Table* t = static_cast<Table*>(p);
  std::lock_guard<std::mutex> g(t->mu);
  return static_cast<int64_t>(t->index.size());
}

// pull with on-demand init (create=1) or zero-fill for misses (create=0)
void emb_pull(void* p, const int64_t* ids, int64_t n, float* out,
              int create) {
  Table* t = static_cast<Table*>(p);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    float* r = t->row(ids[i], create != 0);
    if (r)
      std::memcpy(out + i * t->dim, r, sizeof(float) * t->dim);
    else
      std::memset(out + i * t->dim, 0, sizeof(float) * t->dim);
  }
}

// batched optimizer push; ignores ids never pulled (reference semantics:
// push to a non-existent row is dropped)
void emb_push(void* p, const int64_t* ids, int64_t n, const float* grads,
              float eps) {
  Table* t = static_cast<Table*>(p);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    auto it = t->index.find(ids[i]);
    if (it == t->index.end()) continue;
    float* r = t->arena.data() + it->second;
    const float* gr = grads + i * t->dim;
    if (t->opt == 0) {  // sgd
      for (int j = 0; j < t->dim; ++j) r[j] -= t->lr * gr[j];
    } else {            // adagrad
      float* acc = r + t->dim;
      for (int j = 0; j < t->dim; ++j) {
        acc[j] += gr[j] * gr[j];
        r[j] -= t->lr * gr[j] / (std::sqrt(acc[j]) + eps);
      }
    }
  }
}

void emb_push_delta(void* p, const int64_t* ids, int64_t n,
                    const float* deltas) {
  Table* t = static_cast<Table*>(p);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    auto it = t->index.find(ids[i]);
    if (it == t->index.end()) continue;
    float* r = t->arena.data() + it->second;
    const float* d = deltas + i * t->dim;
    for (int j = 0; j < t->dim; ++j) r[j] += d[j];
  }
}

// export for save. Writes at most `cap` entries and returns the table's
// TOTAL size under the same lock — the caller grows its buffers and
// retries when total > cap (a concurrent pull may have created rows
// between the caller's sizing call and this one).
int64_t emb_export(void* p, int64_t* keys, float* rows, float* slots,
                   int64_t cap) {
  Table* t = static_cast<Table*>(p);
  std::lock_guard<std::mutex> g(t->mu);
  int64_t i = 0;
  for (const auto& kv : t->index) {
    if (i >= cap) break;
    keys[i] = kv.first;
    const float* r = t->arena.data() + kv.second;
    std::memcpy(rows + i * t->dim, r, sizeof(float) * t->dim);
    if (t->n_slots)
      std::memcpy(slots + i * t->dim, r + t->dim, sizeof(float) * t->dim);
    ++i;
  }
  return static_cast<int64_t>(t->index.size());
}

void emb_clear(void* p) {
  Table* t = static_cast<Table*>(p);
  std::lock_guard<std::mutex> g(t->mu);
  t->index.clear();
  t->arena.clear();
}

// bulk import for load: overwrites/creates the given ids
void emb_import(void* p, const int64_t* keys, int64_t n, const float* rows,
                const float* slots) {
  Table* t = static_cast<Table*>(p);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    float* r = t->row(keys[i], true);
    std::memcpy(r, rows + i * t->dim, sizeof(float) * t->dim);
    if (t->n_slots && slots)
      std::memcpy(r + t->dim, slots + i * t->dim, sizeof(float) * t->dim);
  }
}

}  // extern "C"
