// MultiSlot data-feed parser: the reference's high-throughput ingestion
// format (paddle/fluid/framework/data_feed.cc MultiSlotDataFeed).
//
// Line format (reference data_feed.proto / MultiSlotDataFeed::ParseOneInstance):
//   <num><sp><v1>..<vnum>  repeated per slot, e.g.
//   "2 0.5 0.6 3 1 2 3"  = slot0: two floats, slot1: three ints
//
// C API parses a whole text buffer into flat per-slot value/offset arrays
// (CSR layout), which python wraps as ragged batches. This is the hot loop
// of PS-style training ingestion; the channel/queueing stays in python.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

struct SlotBuf {
  std::vector<float> fvals;
  std::vector<int64_t> ivals;
  std::vector<int64_t> offsets;  // per-instance offsets (CSR), starts with 0
  int is_float = 1;
};

struct ParseResult {
  std::vector<SlotBuf> slots;
  int64_t instances = 0;
};

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) p++;
  return p;
}

}  // namespace

extern "C" {

// slot_types: 0=float, 1=int64 per slot.
void* df_parse(const char* buf, int64_t len, int num_slots,
               const int* slot_types) {
  auto* res = new ParseResult();
  res->slots.resize(num_slots);
  for (int s = 0; s < num_slots; s++) {
    res->slots[s].is_float = slot_types[s] == 0;
    res->slots[s].offsets.push_back(0);
  }
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    const char* line_end = (const char*)memchr(p, '\n', end - p);
    if (!line_end) line_end = end;
    const char* q = p;
    bool ok = true;
    // parse one instance: num_slots groups of "<n> v..."
    std::vector<std::pair<int64_t, const char*>> starts;
    for (int s = 0; s < num_slots && ok; s++) {
      q = skip_ws(q, line_end);
      // strto* skip '\n' themselves, so an exhausted line would silently
      // consume tokens from the NEXT line; bound every parse by line_end.
      if (q >= line_end) { ok = false; break; }
      char* next = nullptr;
      long n = strtol(q, &next, 10);
      if (next == q || n < 0) { ok = false; break; }
      q = next;
      SlotBuf& sb = res->slots[s];
      for (long i = 0; i < n; i++) {
        q = skip_ws(q, line_end);
        if (q >= line_end) { ok = false; break; }
        if (sb.is_float) {
          float v = strtof(q, &next);
          if (next == q) { ok = false; break; }
          sb.fvals.push_back(v);
        } else {
          long long v = strtoll(q, &next, 10);
          if (next == q) { ok = false; break; }
          sb.ivals.push_back((int64_t)v);
        }
        q = next;
      }
    }
    if (ok) {
      for (int s = 0; s < num_slots; s++) {
        SlotBuf& sb = res->slots[s];
        sb.offsets.push_back(sb.is_float ? (int64_t)sb.fvals.size()
                                         : (int64_t)sb.ivals.size());
      }
      res->instances++;
    } else {
      // roll back partial pushes for this line
      for (int s = 0; s < num_slots; s++) {
        SlotBuf& sb = res->slots[s];
        int64_t keep = sb.offsets.back();
        if (sb.is_float) sb.fvals.resize(keep);
        else sb.ivals.resize(keep);
      }
    }
    p = line_end < end ? line_end + 1 : end;
  }
  return res;
}

int64_t df_num_instances(void* h) {
  return static_cast<ParseResult*>(h)->instances;
}

int64_t df_slot_size(void* h, int slot) {
  auto& sb = static_cast<ParseResult*>(h)->slots[slot];
  return sb.is_float ? (int64_t)sb.fvals.size() : (int64_t)sb.ivals.size();
}

void df_copy_slot_fvals(void* h, int slot, float* out) {
  auto& sb = static_cast<ParseResult*>(h)->slots[slot];
  memcpy(out, sb.fvals.data(), sb.fvals.size() * sizeof(float));
}

void df_copy_slot_ivals(void* h, int slot, int64_t* out) {
  auto& sb = static_cast<ParseResult*>(h)->slots[slot];
  memcpy(out, sb.ivals.data(), sb.ivals.size() * sizeof(int64_t));
}

void df_copy_slot_offsets(void* h, int slot, int64_t* out) {
  auto& sb = static_cast<ParseResult*>(h)->slots[slot];
  memcpy(out, sb.offsets.data(), sb.offsets.size() * sizeof(int64_t));
}

void df_free(void* h) { delete static_cast<ParseResult*>(h); }

}  // extern "C"
