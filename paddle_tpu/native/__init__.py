"""Native (C++) runtime components, built on demand with g++.

The reference implements its graph engine / data feed / allocator in C++
(SURVEY.md §2.1); the TPU build keeps the hot host-side paths native too:
  graph_store.cc — sharded graph + alias-method sampling (GNN engine core)
  datafeed.cc    — MultiSlot format parser (PS ingestion hot loop)
Compute stays in XLA; these are host subsystems where python would be the
bottleneck.
"""
import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_LOCK = threading.Lock()
_LIBS = {}


def _build(name, force=False):
    src = os.path.join(_DIR, name + '.cc')
    out = os.path.join(_DIR, 'lib%s.so' % name)
    if not force and os.path.exists(out) \
            and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    cmd = ['g++', '-O2', '-shared', '-fPIC', '-std=c++17', '-o', out, src,
           '-pthread']
    subprocess.run(cmd, check=True, capture_output=True)
    return out


def load_library(name):
    """Compile (cached) and dlopen a native helper; raises on failure so the
    caller can fall back to a python implementation."""
    with _BUILD_LOCK:
        if name not in _LIBS:
            try:
                _LIBS[name] = ctypes.CDLL(_build(name))
            except OSError:
                # existing .so not loadable on this platform — rebuild
                _LIBS[name] = ctypes.CDLL(_build(name, force=True))
        return _LIBS[name]


def available(name):
    try:
        load_library(name)
        return True
    except Exception:
        return False
