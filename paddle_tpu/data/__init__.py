"""Streaming ingestion plane: splittable shard format + deterministic
sharded readers + window shuffle + async double-buffered host->device
prefetch (docs/data.md).

``shards`` is the storage layer (ShardWriter/ShardReader, canonical
interleave arithmetic); ``pipeline`` composes it into the checkpointable
``IngestPipeline`` that Model.fit accepts wherever a DataLoader is.
"""
from . import shards
from .shards import (ShardWriter, ShardReader, ShardCorruptError,
                     write_shards, list_shards, read_index)
from .pipeline import (IngestPipeline, IngestCursor, ShardInterleave,
                       window_shuffle)

__all__ = ['shards', 'ShardWriter', 'ShardReader', 'ShardCorruptError',
           'write_shards', 'list_shards', 'read_index', 'IngestPipeline',
           'IngestCursor', 'ShardInterleave', 'window_shuffle']
