"""Streaming ingestion pipeline: sharded readers -> window shuffle ->
collation -> async double-buffered host->device prefetch.

The stages compose into ONE deterministic stream per ``(seed, epoch)``:

* ``ShardInterleave`` merges per-shard readers in canonical record-level
  round robin. The order is pure arithmetic over the shard record counts
  (``shards.interleave_locate``), so reader threads can race on IO while
  the merged order never moves, and a resume cursor can SEEK every
  reader to its exact record instead of draining the trained prefix.
* ``window_shuffle`` permutes fixed windows of the canonical stream with
  an RNG derived from ``(seed, epoch, window)`` — reproducible, bounded
  memory (one window of decoded samples), and resumable: emitted
  position ``r`` lives in window ``r // window`` whose permutation (and
  pre-draw RNG state, which the cursor checkpoints) is re-derivable
  without replaying anything before the window.
* ``_Prefetcher`` runs the whole producer chain (read + decode + shuffle
  + collate + ``jax.device_put``) on a background thread behind a
  bounded queue (default depth 2 = double buffering): batch k+1 is
  decoded and already on device while the dispatched step k runs, so the
  consumer's ``data_wait`` collapses to a queue pop. Backpressure
  (producer blocked on a full queue) and consumer wait both land in the
  ``ingest_*`` metric families.

``IngestPipeline`` is the user-facing object: iterable like a
``DataLoader`` (one epoch per ``__iter__``, ``len()`` in batches),
accepted by ``Model.fit`` anywhere a loader is, and checkpointable —
``cursor()`` / ``restore()`` round-trip the exact stream position
through the elastic supervisor's ``ResumeCursor`` (docs/data.md).
"""
import hashlib
import queue
import threading
import time

import numpy as np

from ..monitor.registry import default_registry
from ..monitor.telemetry import record_ingest_schema
from . import shards as _shards

__all__ = ['ShardInterleave', 'window_shuffle', 'IngestCursor',
           'IngestPipeline']

_CURSOR_FORMAT = 1


class ShardInterleave:
    """Deterministic record-level round-robin merge over a shard set,
    starting at canonical stream position ``start``.

    ``reader_threads > 0`` assigns shards round robin to that many
    reader threads (shard i -> thread i % K), each filling its shards'
    bounded queues one record per round; the merge consumes the queues
    in canonical order, so thread timing never changes the stream. With
    0 threads the merge reads inline (the prefetch stage already runs
    the whole chain off the consumer thread).

    ``trace`` (a list, test hook) records every (shard_index,
    record_index) in merge order — the record-access log the resume
    determinism tests pin. ``bytes_read`` returns payload bytes consumed
    so far (feeds ``ingest_bytes_read_total``).
    """

    def __init__(self, paths, start=0, reader_threads=0, queue_records=64,
                 trace=None):
        self.paths = list(paths)
        if not self.paths:
            raise ValueError('ShardInterleave needs at least one shard')
        self.readers = [_shards.ShardReader(p) for p in self.paths]
        self.counts = [r.records for r in self.readers]
        self.total = _shards.interleave_total(self.counts)
        self.start = int(start)
        self.reader_threads = max(int(reader_threads), 0)
        self.queue_records = max(int(queue_records), 1)
        self.trace = trace
        self._bytes = 0

    def bytes_read(self):
        return self._bytes

    def _start_state(self):
        """Per-shard start record + first round/slot for stream position
        ``start`` — pure arithmetic, no IO."""
        if self.start >= self.total:
            return None
        shard0, round0 = _shards.interleave_locate(self.counts, self.start)
        offsets = []
        for s, c in enumerate(self.counts):
            if c > round0:
                offsets.append(round0 + (1 if s < shard0 else 0))
            else:
                offsets.append(c)
        return offsets, round0, shard0

    def __iter__(self):
        state = self._start_state()
        if state is None:
            return
        offsets, round0, shard0 = state
        if self.reader_threads:
            sources = self._threaded_sources(offsets)
        else:
            sources = [iter(r.iter_from(off))
                       for r, off in zip(self.readers, offsets)]
        try:
            r = round0
            max_count = max(self.counts)
            first = True
            while r < max_count:
                for s, c in enumerate(self.counts):
                    if c <= r:
                        continue
                    if first and s < shard0:
                        continue        # consumed before the start position
                    first = False
                    payload = next(sources[s])
                    self._bytes += len(payload)
                    if self.trace is not None:
                        self.trace.append((s, r))
                    yield payload
                if first:
                    # start round had no shard at/after shard0 (can't
                    # happen — locate() guarantees shard0 is active)
                    first = False
                r += 1
        finally:
            for src in sources:
                close = getattr(src, 'close', None)
                if close is not None:
                    close()

    # -- threaded readers ---------------------------------------------------
    def _threaded_sources(self, offsets):
        """One bounded queue per shard, filled by reader_threads threads
        (shard i -> thread i % K, each thread round-robining its own
        shards one record per round so no queue can starve another)."""
        stop = threading.Event()
        queues = [queue.Queue(maxsize=self.queue_records)
                  for _ in self.counts]

        def _fill(shard_ids):
            its = {s: self.readers[s].iter_from(offsets[s])
                   for s in shard_ids}
            remaining = {s: self.counts[s] - offsets[s] for s in shard_ids}
            while its and not stop.is_set():
                for s in list(its):
                    if remaining[s] <= 0:
                        del its[s]
                        continue
                    payload = next(its[s])
                    remaining[s] -= 1
                    while not stop.is_set():
                        try:
                            queues[s].put(payload, timeout=0.1)
                            break
                        except queue.Full:
                            continue

        threads = []
        for t in range(min(self.reader_threads, len(self.counts))):
            ids = list(range(t, len(self.counts), self.reader_threads))
            th = threading.Thread(target=_fill, args=(ids,), daemon=True,
                                  name='ingest-reader-%d' % t)
            th.start()
            threads.append(th)

        class _Source:
            def __init__(self, q):
                self._q = q

            def __next__(self):
                return self._q.get()

            def close(self):
                stop.set()
                # drain so blocked producers can observe the stop flag
                try:
                    while True:
                        self._q.get_nowait()
                except queue.Empty:
                    pass

        return [_Source(q) for q in queues]


def _window_rng(seed, epoch, window):
    """The shuffle RNG for one window — re-derivable from coordinates,
    checkpointable as a state dict (np.random.Generator over PCG64)."""
    ss = np.random.SeedSequence([0x1D6E57 & 0xFFFFFF, int(seed) & (2**63 - 1),
                                 int(epoch), int(window)])
    return np.random.Generator(np.random.PCG64(ss))


def window_shuffle(stream, total, window, seed, epoch, start=0,
                   rng_state=None):
    """Permute fixed windows of `stream` reproducibly per
    ``(seed, epoch)``. `stream` must already be positioned at the first
    record of window ``start // window``; the first ``start % window``
    entries of that window's permutation are skipped (they were emitted
    before the checkpoint). ``rng_state`` (cursor-checkpointed pre-draw
    state of the resumed window) overrides the derived RNG for the first
    window when given."""
    window = int(window)
    if window <= 0:
        for item in stream:
            yield item
        return
    w = int(start) // window
    skip = int(start) % window
    pos = w * window
    it = iter(stream)
    while pos < total:
        size = min(window, total - pos)
        buf = []
        for _ in range(size):
            buf.append(next(it))
        if rng_state is not None:
            rng = np.random.Generator(np.random.PCG64())
            rng.bit_generator.state = rng_state
            rng_state = None
        else:
            rng = _window_rng(seed, epoch, w)
        for i in rng.permutation(size)[skip:]:
            yield buf[i]
        skip = 0
        pos += size
        w += 1


class IngestCursor:
    """Exact stream position of an ``IngestPipeline``: epoch, records
    and batches DELIVERED to the consumer, the pre-draw RNG state of the
    shuffle window the position lives in, and a fingerprint of the shard
    set so a cursor can never silently replay against different data."""

    def __init__(self, epoch=0, records=0, batches=0, rng_state=None,
                 fingerprint=None):
        self.epoch = int(epoch)
        self.records = int(records)
        self.batches = int(batches)
        self.rng_state = rng_state
        self.fingerprint = fingerprint

    def to_state(self):
        return {'format': _CURSOR_FORMAT, 'epoch': self.epoch,
                'records': self.records, 'batches': self.batches,
                'rng_state': self.rng_state,
                'fingerprint': self.fingerprint}

    @classmethod
    def from_state(cls, state):
        return cls(epoch=state['epoch'], records=state['records'],
                   batches=state.get('batches', 0),
                   rng_state=state.get('rng_state'),
                   fingerprint=state.get('fingerprint'))

    def __repr__(self):
        return ('IngestCursor(epoch=%d, records=%d, batches=%d)'
                % (self.epoch, self.records, self.batches))


class _Halt(Exception):
    """Producer-side stop signal (consumer closed the epoch early)."""


class _Prefetcher:
    """Bounded hand-off queue between the producer chain (background
    thread) and the consumer. Depth 2 is double buffering: one batch in
    the consumer's hands, one staged on device, producer working on the
    third. Exceptions cross the queue and re-raise at the consumer."""

    _DONE = object()

    def __init__(self, producer_iter, depth, fams):
        self._q = queue.Queue(maxsize=max(int(depth), 1))
        self._stop = threading.Event()
        self._fams = fams
        self._thread = threading.Thread(target=self._run,
                                        args=(producer_iter,),
                                        daemon=True, name='ingest-prefetch')
        self._thread.start()

    def _run(self, it):
        backpressure = self._fams['ingest_backpressure_seconds_total']
        try:
            for item in it:
                self._put(('item', item), backpressure)
            self._put(('done', None), backpressure)
        except _Halt:
            pass
        except BaseException as e:                 # noqa: BLE001
            try:
                self._put(('error', e), backpressure)
            except _Halt:
                pass

    def _put(self, msg, backpressure):
        t0 = None
        while not self._stop.is_set():
            try:
                self._q.put(msg, timeout=0.05)
                if t0 is not None:
                    backpressure.inc(time.monotonic() - t0)
                return
            except queue.Full:
                if t0 is None:
                    t0 = time.monotonic()
        raise _Halt()

    def get(self):
        """(kind, payload) — blocks until the producer delivers."""
        msg = self._q.get()
        self._fams['ingest_queue_depth'].set(self._q.qsize())
        return msg

    def close(self):
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)


def _np_stack_collate(batch):
    from ..io.dataloader import _np_collate
    return _np_collate(batch)


def _tensorize(tree, device_put):
    """numpy tree -> Tensor tree, optionally staging arrays on device in
    the producer thread (so the consumer's step never pays the
    host->device copy)."""
    from ..framework.core import Tensor
    if isinstance(tree, np.ndarray):
        if device_put:
            import jax
            return Tensor(jax.device_put(tree))
        return Tensor(tree)
    if isinstance(tree, list):
        return [_tensorize(t, device_put) for t in tree]
    if isinstance(tree, tuple):
        return tuple(_tensorize(t, device_put) for t in tree)
    if isinstance(tree, dict):
        return {k: _tensorize(v, device_put) for k, v in tree.items()}
    return tree


class IngestPipeline:
    """High-throughput streaming loader over a shard set.

    Parameters mirror the stages: ``shuffle_window`` (records; 0 = no
    shuffle) and ``seed`` drive the reproducible window shuffle,
    ``prefetch`` is the hand-off queue depth (0 = fully synchronous —
    the baseline the bench rung compares against), ``device_put`` stages
    batches on device from the producer thread, ``reader_threads``
    parallelizes shard IO, ``decode`` turns record bytes into a sample
    (default: the pickle codec ``shards.decode_sample``).

    One epoch per ``__iter__`` (the ``DataLoader`` contract). After each
    full epoch the pipeline advances its epoch counter, so consecutive
    iterations see different shuffles; ``set_epoch`` pins it (elastic
    schedulers, evaluation replays).
    """

    def __init__(self, shard_paths, batch_size=1, shuffle_window=0,
                 seed=0, drop_last=False, collate_fn=None, decode=None,
                 prefetch=2, device_put=True, reader_threads=0,
                 registry=None, record_trace=None):
        if isinstance(shard_paths, str):
            shard_paths = _shards.list_shards(shard_paths)
        self.paths = list(shard_paths)
        if not self.paths:
            raise ValueError('IngestPipeline needs at least one shard')
        self.counts = [int(_shards.read_index(p)['records'])
                       for p in self.paths]
        self.total = sum(self.counts)
        self.batch_size = int(batch_size)
        self.shuffle_window = int(shuffle_window)
        self.seed = int(seed)
        self.drop_last = bool(drop_last)
        self.collate_fn = collate_fn
        self.decode = decode if decode is not None \
            else _shards.decode_sample
        self.prefetch = max(int(prefetch), 0)
        self.device_put = bool(device_put)
        self.reader_threads = max(int(reader_threads), 0)
        self.record_trace = record_trace
        self._fams = record_ingest_schema(
            registry if registry is not None else default_registry())
        self._epoch = 0
        self._delivered_records = 0
        self._delivered_batches = 0
        self._resume = None
        self.last_wait_s = 0.0
        self.last_epoch_stats = None

    # -- identity ----------------------------------------------------------
    def fingerprint(self):
        h = hashlib.sha1()
        for p, c in zip(self.paths, self.counts):
            h.update(('%s:%d|' % (p.rsplit('/', 1)[-1], c)).encode())
        return h.hexdigest()

    def __len__(self):
        if self.drop_last:
            return self.total // self.batch_size
        return -(-self.total // self.batch_size)

    @property
    def epoch(self):
        return self._epoch

    def set_epoch(self, epoch):
        self._epoch = int(epoch)

    # -- checkpointing ------------------------------------------------------
    def cursor(self):
        """Exact position AFTER the last batch the consumer took from
        ``__iter__``. The RNG state is the pre-draw generator state of
        the shuffle window the next record lives in — checkpointed so a
        restore replays the identical permutation even if RNG-derivation
        details drift."""
        rng_state = None
        if self.shuffle_window > 0 and self._delivered_records < self.total:
            w = self._delivered_records // self.shuffle_window
            rng_state = _window_rng(self.seed, self._epoch,
                                    w).bit_generator.state
        return IngestCursor(epoch=self._epoch,
                            records=self._delivered_records,
                            batches=self._delivered_batches,
                            rng_state=rng_state,
                            fingerprint=self.fingerprint())

    def restore(self, cursor):
        """Stage a cursor (or its ``to_state()`` dict): the NEXT
        ``__iter__`` seeks to the exact stream position instead of
        starting the epoch from the top."""
        if isinstance(cursor, dict):
            cursor = IngestCursor.from_state(cursor)
        if cursor.fingerprint and cursor.fingerprint != self.fingerprint():
            raise ValueError(
                'ingest cursor fingerprint %s does not match this shard '
                'set (%s) — refusing to resume against different data'
                % (cursor.fingerprint[:12], self.fingerprint()[:12]))
        if not 0 <= cursor.records <= self.total:
            raise ValueError('cursor records %d out of range (total %d)'
                             % (cursor.records, self.total))
        self._resume = cursor
        return cursor

    # -- the stream ---------------------------------------------------------
    def _producer(self, epoch, start_records, trace):
        """Decoded-sample stream -> batches -> collate -> tensorize.
        Runs entirely on the producer side of the hand-off queue."""
        if self.shuffle_window > 0:
            stream_start = (start_records // self.shuffle_window) \
                * self.shuffle_window
        else:
            stream_start = start_records
        rng_state = None
        if self._resume_rng_state is not None:
            rng_state = self._resume_rng_state
            self._resume_rng_state = None
        inter = ShardInterleave(self.paths, start=stream_start,
                                reader_threads=self.reader_threads,
                                trace=trace)
        records = window_shuffle(inter, self.total, self.shuffle_window,
                                 self.seed, epoch, start=start_records,
                                 rng_state=rng_state)
        bytes_fam = self._fams['ingest_bytes_read_total']
        batch, seen_bytes = [], 0
        for payload in records:
            batch.append(self.decode(payload))
            if len(batch) == self.batch_size:
                yield self._finish_batch(batch)
                batch = []
                nb = inter.bytes_read()
                bytes_fam.inc(nb - seen_bytes)
                seen_bytes = nb
        if batch and not self.drop_last:
            yield self._finish_batch(batch)
        bytes_fam.inc(inter.bytes_read() - seen_bytes)

    def _finish_batch(self, samples):
        n = len(samples)
        if self.collate_fn is not None:
            return n, self.collate_fn(samples)
        return n, _tensorize(_np_stack_collate(samples), self.device_put)

    def __iter__(self):
        cursor, self._resume = self._resume, None
        start_records = 0
        self._resume_rng_state = None
        if cursor is not None:
            self._epoch = cursor.epoch
            start_records = cursor.records
            self._resume_rng_state = cursor.rng_state
            self._fams['ingest_resumes_total'].inc()
        epoch = self._epoch
        self._delivered_records = start_records
        self._delivered_batches = cursor.batches if cursor is not None \
            else 0
        trace = self.record_trace
        producer = self._producer(epoch, start_records, trace)
        rec_fam = self._fams['ingest_records_total']
        batch_fam = self._fams['ingest_batches_total']
        wait_fam = self._fams['ingest_wait_seconds_total']
        prefetcher = _Prefetcher(producer, self.prefetch, self._fams) \
            if self.prefetch else None
        wait_s = 0.0
        t_epoch = time.monotonic()
        try:
            while True:
                t0 = time.monotonic()
                if prefetcher is not None:
                    kind, payload = prefetcher.get()
                    if kind == 'done':
                        break
                    if kind == 'error':
                        raise payload
                    n, batch = payload
                else:
                    try:
                        n, batch = next(producer)
                    except StopIteration:
                        break
                dt = time.monotonic() - t0
                self.last_wait_s = dt
                wait_s += dt
                wait_fam.inc(dt)
                self._delivered_records += n
                self._delivered_batches += 1
                rec_fam.inc(n)
                batch_fam.inc()
                yield batch
            # epoch completed in full: advance and publish epoch stats
            wall = time.monotonic() - t_epoch
            delivered = self._delivered_records - start_records
            self.last_epoch_stats = {
                'epoch': epoch, 'records': delivered,
                'batches': self._delivered_batches,
                'wall_s': wall, 'wait_s': wait_s,
                'data_wait_frac': (wait_s / wall) if wall > 0 else 0.0,
                'examples_per_sec': (delivered / wall) if wall > 0
                else 0.0,
            }
            self._fams['ingest_examples_per_second'].set(
                self.last_epoch_stats['examples_per_sec'])
            self._fams['ingest_epochs_total'].inc()
            self._epoch = epoch + 1
            self._delivered_records = 0
            self._delivered_batches = 0
        finally:
            if prefetcher is not None:
                prefetcher.close()
