"""Splittable on-disk shard format for the streaming ingestion plane.

A shard is a flat file of length-prefixed records:

    magic b'PTSHARD1' | u32 count-placeholder | records...
    record = u32 LE payload length | payload bytes

plus a small JSON index sidecar (``<path>.idx``) written through
``framework.io_save.write_bytes_atomic`` (write-temp + fsync + rename,
so a preempted writer never tears a sidecar a reader trusts). The
sidecar carries the record count, total payload bytes, a CRC32 of the
data file and byte offsets every ``index_stride`` records — enough to
seek a reader to ANY record in O(stride) without scanning the file,
which is what makes shards splittable across workers and resumable from
a checkpointed ``(shard, record)`` cursor.

The shard file itself is also renamed into place atomically: readers
only ever see complete shards. Records are raw bytes; ``encode_sample``
/ ``decode_sample`` are the default pickle codec for structured samples
(numpy-tree-safe), and callers with fixed-layout records (the bench
rung's raw float32 rows) pass their own ``decode=``.
"""
import glob
import json
import os
import pickle
import struct
import zlib

from ..framework.io_save import write_bytes_atomic

__all__ = ['MAGIC', 'ShardWriter', 'ShardReader', 'ShardCorruptError',
           'encode_sample', 'decode_sample', 'index_path', 'read_index',
           'list_shards', 'write_shards', 'interleave_total',
           'interleave_locate']

MAGIC = b'PTSHARD1'
_LEN = struct.Struct('<I')
_INDEX_FORMAT = 1


class ShardCorruptError(IOError):
    """Shard bytes disagree with the index sidecar (truncated / torn /
    bit-flipped shard)."""


def encode_sample(sample):
    """Default record codec: pickle with numpy leaves (io_save's wire
    shape, minus the Tensor wrapping — samples are host data)."""
    return pickle.dumps(sample, protocol=4)


def decode_sample(record):
    return pickle.loads(record)


def index_path(path):
    return path + '.idx'


class ShardWriter:
    """Append records, then ``close()`` (or use as a context manager) to
    rename the shard into place and publish its index sidecar. Nothing
    is visible at `path` until close — a died writer leaves only temp
    droppings, never a half-shard."""

    def __init__(self, path, index_stride=128):
        self.path = path
        self.index_stride = max(int(index_stride), 1)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._tmp = '%s.tmp.%d' % (path, os.getpid())
        self._f = open(self._tmp, 'wb')
        self._f.write(MAGIC)
        self._offsets = []            # byte offset of records 0, S, 2S...
        self._count = 0
        self._payload_bytes = 0
        self._crc = 0
        self._closed = False

    def append(self, record):
        """Append one record. Bytes pass through; anything else goes
        through encode_sample."""
        if self._closed:
            raise ValueError('ShardWriter already closed')
        if not isinstance(record, (bytes, bytearray, memoryview)):
            record = encode_sample(record)
        record = bytes(record)
        if self._count % self.index_stride == 0:
            self._offsets.append(self._f.tell())
        header = _LEN.pack(len(record))
        self._f.write(header)
        self._f.write(record)
        self._crc = zlib.crc32(record, zlib.crc32(header, self._crc))
        self._count += 1
        self._payload_bytes += len(record)
        return self._count - 1

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        # data first, then index: a crash between the two leaves a shard
        # without a sidecar, which readers refuse by default — the
        # conservative outcome (same ordering rule as io_save.save).
        os.replace(self._tmp, self.path)
        index = {'format': _INDEX_FORMAT,
                 'records': self._count,
                 'payload_bytes': self._payload_bytes,
                 'crc32': self._crc & 0xFFFFFFFF,
                 'index_stride': self.index_stride,
                 'offsets': self._offsets}
        write_bytes_atomic(index_path(self.path),
                           json.dumps(index, sort_keys=True).encode())

    def abort(self):
        """Drop the in-progress shard without publishing it."""
        if self._closed:
            return
        self._closed = True
        self._f.close()
        try:
            os.remove(self._tmp)
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.close()
        else:
            self.abort()

    def __len__(self):
        return self._count


def read_index(path, verify=False):
    """The shard's index sidecar dict. ``verify=True`` additionally
    CRCs the record stream against it (full file read — restore-time
    paranoia, not per-iterator overhead)."""
    try:
        with open(index_path(path)) as f:
            index = json.load(f)
    except (OSError, ValueError) as e:
        raise ShardCorruptError('shard %s has no readable index sidecar '
                                '(%s) — writer died before publishing, '
                                'or a foreign file' % (path, e))
    if verify:
        crc = 0
        with open(path, 'rb') as f:
            if f.read(len(MAGIC)) != MAGIC:
                raise ShardCorruptError('%s: bad magic' % path)
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                crc = zlib.crc32(chunk, crc)
        if crc & 0xFFFFFFFF != index.get('crc32'):
            raise ShardCorruptError('%s does not match its index CRC — '
                                    'truncated or torn shard' % path)
    return index


class ShardReader:
    """Sequential + seekable reader over one shard.

    ``iter_from(record)`` seeks via the strided offset table (O(stride)
    skip, no scan) — the door the resume cursor and worker splits use.
    """

    def __init__(self, path, decode=None):
        self.path = path
        self.decode = decode
        self.index = read_index(path)
        self.records = int(self.index['records'])
        self._stride = int(self.index.get('index_stride') or 1)
        self._offsets = self.index.get('offsets') or []
        self._rf = None               # lazy persistent handle for at()

    def __len__(self):
        return self.records

    def _open(self):
        f = open(self.path, 'rb')
        if f.read(len(MAGIC)) != MAGIC:
            f.close()
            raise ShardCorruptError('%s: bad magic' % self.path)
        return f

    def _read_record(self, f):
        header = f.read(_LEN.size)
        if len(header) < _LEN.size:
            raise ShardCorruptError('%s: truncated record header'
                                    % self.path)
        (n,) = _LEN.unpack(header)
        payload = f.read(n)
        if len(payload) < n:
            raise ShardCorruptError('%s: truncated record payload'
                                    % self.path)
        return payload

    def iter_from(self, record=0):
        """Yield records starting at index `record` (decoded when the
        reader has a codec)."""
        record = int(record)
        if record >= self.records:
            return
        with self._open() as f:
            if self._offsets:
                slot = min(record // self._stride, len(self._offsets) - 1)
                f.seek(self._offsets[slot])
                skip = record - slot * self._stride
            else:
                skip = record
            for _ in range(skip):
                self._read_record(f)
            for _ in range(record, self.records):
                payload = self._read_record(f)
                yield self.decode(payload) if self.decode else payload

    def __iter__(self):
        return self.iter_from(0)

    def read(self, record):
        """One record by index."""
        for payload in self.iter_from(record):
            return payload
        raise IndexError('record %d out of range (shard has %d)'
                         % (record, self.records))

    def at(self, record):
        """Random-access one record through a lazily-opened persistent
        handle: seek to the strided offset, skip to the record, read.
        This is what sampler-driven random access over a record stream
        costs — O(stride/2) records skipped per call, the read
        amplification the streaming interleave exists to avoid."""
        record = int(record)
        if not 0 <= record < self.records:
            raise IndexError('record %d out of range (shard has %d)'
                             % (record, self.records))
        if self._rf is None:
            self._rf = self._open()
        f = self._rf
        if self._offsets:
            slot = min(record // self._stride, len(self._offsets) - 1)
            f.seek(self._offsets[slot])
            skip = record - slot * self._stride
        else:
            f.seek(len(MAGIC))
            skip = record
        for _ in range(skip):
            self._read_record(f)
        payload = self._read_record(f)
        return self.decode(payload) if self.decode else payload

    def close(self):
        f, self._rf = self._rf, None
        if f is not None:
            f.close()


def list_shards(pattern_or_dir):
    """Sorted shard paths from a directory (every *.shard with a
    sidecar) or a glob pattern."""
    if os.path.isdir(pattern_or_dir):
        pattern = os.path.join(pattern_or_dir, '*.shard')
    else:
        pattern = pattern_or_dir
    out = []
    for p in sorted(glob.glob(pattern)):
        if os.path.exists(index_path(p)):
            out.append(p)
    return out


def write_shards(samples, directory, num_shards, prefix='part',
                 index_stride=128):
    """Split an in-memory iterable round-robin across `num_shards` shard
    files (the same record-level round robin ShardInterleave reads back,
    so write-then-stream round-trips in order). Returns the paths."""
    num_shards = max(int(num_shards), 1)
    paths = [os.path.join(directory, '%s-%05d-of-%05d.shard'
                          % (prefix, i, num_shards))
             for i in range(num_shards)]
    writers = [ShardWriter(p, index_stride=index_stride) for p in paths]
    try:
        for i, sample in enumerate(samples):
            writers[i % num_shards].append(sample)
        for w in writers:
            w.close()
    except BaseException:
        for w in writers:
            w.abort()
        raise
    return paths


# -- canonical interleave arithmetic -----------------------------------------
#
# The pipeline's canonical stream order over a shard set is record-level
# round robin in shard order: round r takes one record from every shard
# that still has more than r records. The order is a pure function of
# the per-shard record counts, so "global position p" maps to a concrete
# (shard, record) without reading anything — that is what lets a resume
# cursor seek instead of draining, and lets reader threads fill
# per-shard queues in any timing while the merge stays deterministic.

def interleave_total(counts):
    return int(sum(counts))


def _consumed_before_round(counts, r):
    """Records emitted by all rounds strictly before round r."""
    return int(sum(min(int(c), r) for c in counts))


def interleave_locate(counts, position):
    """(shard_index, record_index) of canonical stream `position` for a
    shard set with per-shard record `counts`."""
    position = int(position)
    total = interleave_total(counts)
    if not 0 <= position < total:
        raise IndexError('position %d out of range (total %d)'
                         % (position, total))
    # binary search the round: largest r with consumed_before(r) <= position
    lo, hi = 0, max(int(c) for c in counts)
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if _consumed_before_round(counts, mid) <= position:
            lo = mid
        else:
            hi = mid - 1
    r = lo
    within = position - _consumed_before_round(counts, r)
    for shard, c in enumerate(counts):
        if int(c) > r:
            if within == 0:
                return shard, r
            within -= 1
    raise AssertionError('interleave_locate arithmetic broke: '
                         'position=%d counts=%r' % (position, counts))
