"""Request queue + admission/prefill policy for continuous batching.

Policy (Orca-style iteration-level scheduling, FIFO within a step):

  1. ADMIT:  while a slot is free and a request is queued, bind the
     oldest request to the lowest free slot (deterministic layout).
  2. PREFILL: every resident request still consuming its prompt advances
     by exactly ONE fixed-size chunk per step — chunking bounds the
     latency bubble a long prompt injects between decode steps, the
     reason Sarathi/vLLM interleave prefill rather than running it to
     completion on arrival.
  3. DECODE: all slots whose prompt is fully consumed take one decode
     burst together (engine-side); finished sequences retire and their
     slots return to the free list the same step.

Everything here is host-side bookkeeping with plain Python ints (plus
host numpy block tables for the paged variant) — the scheduler never
touches device arrays, so it cannot cause a retrace.
"""
import itertools
import threading
from collections import deque

import numpy as np

from .kv_cache import SCRATCH_PAGE

__all__ = ['Request', 'Scheduler', 'PagedScheduler']

_req_ids = itertools.count()

# request lifecycle states
QUEUED, PREFILL, DECODE, DONE = 'queued', 'prefill', 'decode', 'done'


class Request:
    """One generation request plus its accumulated output.

    Sampling params mirror GPTForCausalLM.generate() exactly — same
    greedy/temperature/top-k semantics, same per-request PRNG stream
    seeded from `seed` — so engine output is comparable token-for-token
    against a sequential generate() of the same prompt.
    """

    def __init__(self, prompt, max_new_tokens=32, temperature=1.0,
                 top_k=0, do_sample=False, seed=0, tenant=None,
                 priority=0, model=None):
        self.id = next(_req_ids)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.do_sample = bool(do_sample)
        self.seed = int(seed)
        self.tenant = tenant      # attribution dimension (opaque string)
        self.model = model        # target model name (multi-model hosts)
        self.priority = int(priority)   # higher preempts lower; FIFO ties
        self.outcome = None       # terminal outcome, set at retirement
        self.tokens = []          # generated ids (prompt NOT included)
        self.state = QUEUED
        # wide-event lifecycle fields (monitor/events.py): the engine
        # stamps the timestamps on its metrics clock; the scheduler owns
        # the KV holding window on the allocator's integral clock
        self.kv_page_seconds = 0.0
        self._arrival_t = None
        self._admit_t = None
        self._first_token_t = None
        self._finish_t = None
        self._prefill_chunks = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._kv_hold_t = None    # allocator timestamp at reservation
        self.slot = None          # bound while resident
        self._key = None          # PRNG key, set at admission
        self._consumed = 0        # prompt tokens already prefilled
        self._prefix_hit = 0      # prompt tokens served by the prefix
        #                           cache (paged engine; 0 elsewhere)
        self._published = 0       # prompt blocks already in the cache
        self._seq = None          # submission order, set by the scheduler
        self._preempts = 0        # times this request lost its KV pages
        self._replay = 0          # already-delivered tokens to swallow
        #                           while regenerating after a preemption
        self._kv_acc = 0.0        # page·seconds from closed-out holding
        #                           windows (accumulated at preemption)
        self._span = None         # 'serving.request' lifecycle span
        self._phase = None        # current prefill/decode child span
        self._finished = threading.Event()
        # engine.stream() consumers read tokens from here; None until the
        # first stream() call so non-streamed requests pay nothing
        self._stream_q = None

    @property
    def done(self):
        return self.state == DONE

    def wait(self, timeout=None):
        """Block until the request finishes (thread-safe front door)."""
        return self._finished.wait(timeout)

    def __repr__(self):
        return ('Request(id=%d, state=%s, prompt_len=%d, generated=%d/%d)'
                % (self.id, self.state, len(self.prompt), len(self.tokens),
                   self.max_new_tokens))


class Scheduler:
    """Admission + chunked-prefill planner over a SlotAllocator."""

    def __init__(self, allocator, max_len, prefill_chunk):
        if prefill_chunk < 1:
            raise ValueError('prefill_chunk must be >= 1')
        self.allocator = allocator
        self.max_len = int(max_len)
        self.prefill_chunk = int(prefill_chunk)
        self.queue = deque()
        self.resident = {}        # slot -> Request (PREFILL or DECODE)
        self._submit_seq = itertools.count()

    def submit(self, req):
        """Validate capacity and enqueue. Raises on impossible requests —
        a request that can never fit must fail at the front door, not
        wedge the queue forever."""
        n0 = len(req.prompt)
        if n0 < 1:
            raise ValueError('empty prompt')
        if req.max_new_tokens < 1:
            raise ValueError('max_new_tokens must be >= 1')
        c = self.prefill_chunk
        padded = ((n0 + c - 1) // c) * c
        # two capacity constraints: the final sequence must fit, and the
        # PADDED last prefill chunk must land inside the buffer (a
        # clamped dynamic_update_slice would silently shift the write)
        need = max(n0 + req.max_new_tokens - 1, padded)
        if need > self.max_len:
            raise ValueError(
                'request needs %d cache rows (prompt %d + %d new tokens, '
                'prefill padding to %d) but slots hold %d'
                % (need, n0, req.max_new_tokens, padded, self.max_len))
        req._seq = next(self._submit_seq)
        self.queue.append(req)

    def _pick_index(self):
        """Index of the next request to admit: highest priority first,
        submission order (_seq) within a class — so with uniform
        priorities this is index 0, the exact historical FIFO, and a
        preempted request (which keeps its original _seq) resumes ahead
        of later arrivals of its own class."""
        best = 0
        for i in range(1, len(self.queue)):
            r, b = self.queue[i], self.queue[best]
            if (r.priority, -r._seq) > (b.priority, -b._seq):
                best = i
        return best

    def admit(self):
        """Bind queued requests to free slots; returns [(slot, req)]."""
        admitted = []
        while self.queue and self.allocator.available:
            i = self._pick_index()
            req = self.queue[i]
            del self.queue[i]
            slot = self.allocator.alloc(req.id)
            req.slot = slot
            req.state = PREFILL
            req._consumed = 0
            # holding window opens on the allocator's own advance
            # timestamp, so per-request durations sum exactly to the
            # pool-occupancy integral
            req._kv_hold_t = self.allocator.held_since(slot)
            self.resident[slot] = req
            admitted.append((slot, req))
        return admitted

    def prefill_plan(self):
        """One chunk per prefilling request: [(req, start, ids, valid,
        final)] where ids is exactly prefill_chunk tokens (zero-padded
        past `valid`) so the jitted chunk program has one shape."""
        plan = []
        c = self.prefill_chunk
        for slot in sorted(self.resident):
            req = self.resident[slot]
            if req.state != PREFILL:
                continue
            start = req._consumed
            valid = min(c, len(req.prompt) - start)
            ids = req.prompt[start:start + valid] + [0] * (c - valid)
            plan.append((req, start, ids, valid,
                         start + valid >= len(req.prompt)))
        return plan

    def mark_prefilled(self, req, consumed):
        req._consumed = consumed
        req._prefill_chunks += 1
        if req._consumed >= len(req.prompt):
            req.state = DECODE

    def decode_slots(self):
        return [s for s in sorted(self.resident)
                if self.resident[s].state == DECODE]

    def retire(self, req):
        """Release a finished request's slot and wake any waiters."""
        slot = req.slot
        del self.resident[slot]
        # one slot is the allocation granule: page·seconds == slot·seconds
        req.kv_page_seconds = self.allocator.free(slot)
        req.state = DONE
        req.slot = None
        if req._stream_q is not None:
            req._stream_q.put(None)   # stream sentinel: end of tokens
        req._finished.set()

    @property
    def pending(self):
        """Requests not yet DONE anywhere in the system."""
        return len(self.queue) + len(self.resident)


class PagedScheduler(Scheduler):
    """Page-aware admission over a PageAllocator + optional PrefixCache.

    Same FIFO iteration-level policy as Scheduler, with two additions:

    - ADMIT reserves the request's ENTIRE page need up front (prefix-hit
      blocks are shared via incref, the rest freshly allocated). Because
      every resident request already holds everything it will ever
      write, residents always run to completion — no mid-flight
      allocation failure, no deadlock. When the HEAD request cannot get
      its pages (even after evicting idle prefix-cache entries)
      admission stops for the step rather than skipping ahead: FIFO
      order is what makes waiting bounded.
    - A prefix-cache hit fast-forwards `_consumed` to the shared length,
      so prefill work is paid only for the unshared tail.
    - With `preempt_enabled` (the engine's `preempt=True`), a blocked
      head may EVICT a strictly-lower-priority resident: the victim's
      pages decref back to the pool, its slot frees, and it requeues
      with its original submission order. Its run-to-completion
      guarantee is deliberately traded away — that is the QoS deal for
      low priority. Resumption re-admits it like any queued request;
      its own published prompt blocks usually fast-forward the
      re-prefill through the prefix cache, and the engine regenerates
      the already-delivered tokens deterministically (same prompt,
      sampling, seed — the gateway-failover invariant), swallowing them
      via Request._replay so the caller-visible stream has no duplicate
      and no gap.

    Block tables live here as one host numpy array [num_slots,
    max_blocks] (int32 page ids, SCRATCH_PAGE where unmapped); the
    engine hands rows of it to the jitted programs verbatim.
    """

    def __init__(self, allocator, pages, max_len, prefill_chunk,
                 page_size, prefix_cache=None):
        super().__init__(allocator, max_len, prefill_chunk)
        if page_size < 1:
            raise ValueError('page_size must be >= 1')
        self.pages = pages
        self.page_size = int(page_size)
        self.prefix = prefix_cache
        self.num_blocks = -(-self.max_len // self.page_size)
        self.block_tables = np.full(
            (allocator.num_slots, self.num_blocks), SCRATCH_PAGE, np.int32)
        self._nblocks = {}        # slot -> mapped block count
        self.preempt_enabled = False
        self.max_preempts = None  # per-request eviction budget (None: ∞)
        # engine hook, called with (slot, req, dropped) after the pages
        # and slot are released: clears per-slot engine state; `dropped`
        # means the request burned its preemption budget and is terminal
        self.on_preempt = None
        self.preempted = 0        # evictions (monotonic, for reports)

    def submit(self, req):
        """Front-door capacity check, page-aware: the worst padded
        prefill end over any possible prefix-hit length is n0 + chunk -
        1 (a hit mid-chunk shifts the chunk grid right), and the cache
        contents at admission time are unknowable here — so validate
        against that bound, not today's cache."""
        n0 = len(req.prompt)
        if n0 < 1:
            raise ValueError('empty prompt')
        if req.max_new_tokens < 1:
            raise ValueError('max_new_tokens must be >= 1')
        need = max(n0 + req.max_new_tokens - 1,
                   n0 + self.prefill_chunk - 1)
        if need > self.max_len:
            raise ValueError(
                'request needs up to %d cache rows (prompt %d + %d new '
                'tokens, worst-case prefill padding) but sequences hold '
                '%d' % (need, n0, req.max_new_tokens, self.max_len))
        total = self.pages.num_pages - 1       # minus the scratch page
        if -(-need // self.page_size) > total:
            raise ValueError(
                'request needs %d pages but the pool only has %d'
                % (-(-need // self.page_size), total))
        req._seq = next(self._submit_seq)
        self.queue.append(req)

    def admit(self):
        admitted = []
        while self.queue:
            i = self._pick_index()
            req = self.queue[i]
            if not self.allocator.available:
                # every SLOT is held: a high-priority head may still
                # enter by evicting a strictly-lower-priority resident
                # (which also returns its pages); otherwise stop
                if not (self.preempt_enabled and self._preempt_for(req)):
                    break
            plan = self._reserve(req)
            if plan is None and self.preempt_enabled:
                # the head is blocked on PAGES: evict strictly-lower-
                # priority residents until it fits or none are left
                while plan is None and self._preempt_for(req):
                    plan = self._reserve(req)
            if plan is None:
                break                          # head blocked => stop: FIFO
            del self.queue[i]
            pages, hit_len = plan
            # the request's page-holding window opens here (shared
            # prefix pages were increfed inside _reserve moments ago)
            req._kv_hold_t = self.pages.touch()
            slot = self.allocator.alloc(req.id)
            row = self.block_tables[slot]
            row[:] = SCRATCH_PAGE
            row[:len(pages)] = pages
            self._nblocks[slot] = len(pages)
            req.slot = slot
            req.state = PREFILL
            req._consumed = hit_len            # shared prefix: already
            req._prefix_hit = hit_len          # prefilled, skip it
            req._published = hit_len // self.page_size
            self.resident[slot] = req
            admitted.append((slot, req))
        return admitted

    def _reserve(self, req):
        """All pages for `req` up front: [pages], hit_len — or None when
        the pool cannot cover it this step."""
        P, c, n0 = self.page_size, self.prefill_chunk, len(req.prompt)
        # (`is not None`, not truthiness — an empty PrefixCache has
        # __len__ 0 and still must count its misses)
        hit_pages = (self.prefix.match(req.prompt)
                     if self.prefix is not None else [])
        # hold the hits BEFORE any eviction: a matched page at cache-
        # refcount 1 must not be evicted out from under this reservation
        for p in hit_pages:
            self.pages.incref(p)
        hit_len = len(hit_pages) * P
        need = max(n0 + req.max_new_tokens - 1,
                   hit_len + -(-(n0 - hit_len) // c) * c)
        want = -(-need // P) - len(hit_pages)
        short = want - self.pages.available
        if short > 0 and self.prefix is not None:
            self.prefix.evict(short)
        if want > self.pages.available:
            for p in hit_pages:
                self.pages.decref(p)
            return None
        return hit_pages + [self.pages.alloc() for _ in range(want)], \
            hit_len

    def _preempt_for(self, req):
        """Evict ONE resident strictly below req's priority; False when
        none exists. Victim choice: lowest priority first, and within a
        class the most recently admitted (largest holding-window start)
        — the resident with the least sunk work."""
        victim = None
        for r in self.resident.values():
            if r.priority >= req.priority:
                continue
            if victim is None or \
                    (r.priority, -(r._kv_hold_t or 0.0)) < \
                    (victim.priority, -(victim._kv_hold_t or 0.0)):
                victim = r
        if victim is None:
            return False
        self.preempt(victim)
        return True

    def preempt(self, req):
        """Evict a resident request: close its page·seconds billing
        window, decref every mapped page back to the pool (its own
        published prompt blocks survive under the prefix cache's ref —
        the fast-forward on resume), free the slot, and requeue it with
        its original submission order — or, past `max_preempts`, finish
        it terminally (the engine hook emits outcome='preempted').
        Returns True when requeued, False when dropped."""
        slot = req.slot
        row = self.block_tables[slot]
        nblocks = self._nblocks.pop(slot, 0)
        now = self.pages.touch()
        held = (now - req._kv_hold_t) if req._kv_hold_t is not None \
            else 0.0
        req._kv_acc += nblocks * held
        for b in range(nblocks):
            if row[b] != SCRATCH_PAGE:
                self.pages.decref(int(row[b]))
        row[:] = SCRATCH_PAGE
        del self.resident[slot]
        self.allocator.free(slot)
        req.slot = None
        req._kv_hold_t = None
        req._preempts += 1
        self.preempted += 1
        dropped = self.max_preempts is not None and \
            req._preempts > self.max_preempts
        if dropped:
            req.kv_page_seconds = req._kv_acc
            req.state = DONE
        else:
            # regeneration restarts from the prompt; the ledger
            # (req.tokens) is what the caller already saw, so exactly
            # that many regenerated tokens get swallowed on resume
            req.state = QUEUED
            req._consumed = 0
            req._prefix_hit = 0
            req._published = 0
            req._replay = len(req.tokens)
            self.queue.append(req)
        if self.on_preempt is not None:
            self.on_preempt(slot, req, dropped)
        if dropped:
            if req._stream_q is not None:
                req._stream_q.put(None)
            req._finished.set()
        return not dropped

    def mark_prefilled(self, req, consumed):
        super().mark_prefilled(req, consumed)
        if self.prefix is None:
            return
        # publish every prompt block this chunk completed: its page now
        # holds final, immutable K/V that any later request may share
        P = self.page_size
        row = self.block_tables[req.slot]
        done = min(consumed, len(req.prompt)) // P
        for b in range(req._published, done):
            self.prefix.publish(req.prompt, b, int(row[b]))
        req._published = max(req._published, done)

    def retire(self, req):
        slot = req.slot
        row = self.block_tables[slot]
        nblocks = self._nblocks.pop(slot, 0)
        now = self.pages.touch()
        held = (now - req._kv_hold_t) if req._kv_hold_t is not None \
            else 0.0
        for b in range(nblocks):
            if row[b] != SCRATCH_PAGE:
                self.pages.decref(int(row[b]))
        row[:] = SCRATCH_PAGE
        super().retire(req)
        # super() set the SLOT holding time; this engine bills PAGES:
        # every reserved page, shared prefix hits included (the tenant
        # pinned them for its whole residency even if another tenant
        # also mapped them — see PageAllocator._advance for why the
        # per-request sum can exceed the pool integral under sharing).
        # _kv_acc carries windows closed out by earlier preemptions.
        req.kv_page_seconds = req._kv_acc + nblocks * held
