"""Continuous-batching engine: two jitted programs + a thread-safe door.

The whole engine compiles exactly TWO programs, each with one static
shape, so request admit/retire churn can never retrace:

  prefill chunk  — [1, C] prompt tokens into ONE slot's cache rows
                   (slot sliced out, forwarded, written back; the slot
                   index / row offset / valid count are traced scalars);
  decode burst   — K cached decode steps for ALL slots in one dispatch
                   (lax.scan; per-step `step_active` masking freezes
                   finished or still-prefilling slots in-program, so the
                   burst length never depends on occupancy).

Correctness relies on the GPTSlotCache invariants (text/models/gpt.py):
rows at/beyond a slot's length are unreachable garbage, attention writes
at the pre-step offsets and the ENGINE advances lengths — prefill
write-back sets `start + valid` (padding rows stay invalid), the decode
burst adds `step_active` per step.

Greedy output is token-identical to sequential generate(): the masked
slot attention contributes exact zeros for invalid rows (scores hit
-1e9 and underflow to 0.0 after the f32 softmax), and sampling mirrors
generate()'s per-request PRNG stream (one split at prefill, one per
decode step, advanced only on active steps).

`_EngineBase` holds everything that is NOT about the cache layout — the
thread-safe front door, the scheduler glue, metrics, shutdown — so the
paged engine (serving/paged_engine.py) shares it verbatim and differs
only in its compiled programs and page bookkeeping.
"""
import queue as _queue
import threading

import numpy as np

import jax
import jax.numpy as jnp

from ..framework import functional as _fm
from ..framework.core import Tensor, no_grad_guard
from ..monitor import events as _events
from ..monitor import tracing as _tracing
from ..monitor.perf import CompileWatchdog, StepTimeline
from ..monitor.perf import costmodel as _costmodel
from ..text.models.gpt import GPTSlotCache
from .kv_cache import SlotAllocator, build_slot_caches
from .metrics import ServingMetrics
from .scheduler import Request, Scheduler

__all__ = ['ContinuousBatchingEngine']


def _kv_row_bytes(model):
    """Bytes one KV-cache row (all layers, K+V) costs for `model` —
    the conversion factor between page·seconds and byte·seconds for
    per-tenant billing."""
    config = model.config
    head_dim = config.hidden_size // config.num_heads
    dtype = str(model.gpt.wte.weight.dtype).replace('paddle.', '')
    itemsize = {'bfloat16': 2, 'float16': 2, 'int8': 1}.get(
        dtype) or np.dtype(dtype).itemsize
    return 2 * len(model.gpt.h) * config.num_heads * head_dim * itemsize


def _pick_token(lg, key, temp, topk, sample):
    """Next token for ONE row of logits — generate()'s pick, per slot.

    All branches execute and select (jit-safe): greedy argmax vs
    temperature/top-k categorical, chosen by the `sample` flag. topk==0
    means full vocab (threshold -inf), same as generate().
    """
    lg = lg.astype(jnp.float32)
    greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
    lt = lg / jnp.maximum(temp, 1e-6)
    v = lt.shape[-1]
    srt = jnp.sort(lt, axis=-1)                    # ascending
    kth = srt[jnp.clip(v - topk, 0, v - 1)]        # the top-k'th value
    thr = jnp.where(topk > 0, kth, -jnp.inf)
    lt = jnp.where(lt >= thr, lt, -1e30)
    sampled = jax.random.categorical(key, lt).astype(jnp.int32)
    return jnp.where(sample, sampled, greedy)


class _EngineBase:
    """Cache-layout-agnostic half of a continuous-batching engine.

    Front door (`add_request` / `step` / `run` / `stream` / `generate`)
    is thread-safe: any number of threads may submit and drive; an RLock
    serializes scheduler state and device dispatches while `Request.wait`
    and stream consumption stay lock-free. Subclasses own the compiled
    programs: they set `self.allocator` / `self.scheduler` and implement
    `_prefill_step` / `_decode_step` (and may hook `_bind` /
    `_on_step_metrics`).
    """

    # traced-body counter keys, one per compiled program; the zero-
    # retrace assertion is `trace_counts` staying all-ones across an
    # arbitrary admit/retire workload
    _programs = ('prefill', 'decode')

    def __init__(self, model, num_slots, max_len):
        model.eval()
        self._model = model
        self.num_slots = int(num_slots)
        self.max_len = int(max_len or model.config.max_position_embeddings)
        self.metrics = ServingMetrics()
        self._params = _fm.extract_params(model)
        self._bufs = _fm.extract_buffers(model)
        # per-slot control state lives HOST-side as numpy: admission and
        # retirement mutate it in place for free instead of dispatching
        # an eager .at[].set() per field (the jitted calls accept numpy
        # operands directly). Only the KV caches stay device-resident.
        s = self.num_slots
        self._last = np.zeros((s, 1), np.int32)       # token fed next step
        self._gen = np.zeros((s,), np.int32)          # tokens generated
        self._budgets = np.zeros((s,), np.int32)      # max_new_tokens
        self._active = np.zeros((s,), bool)           # slot decodes?
        self._keys = np.zeros((s, 2), np.uint32)      # per-slot PRNG
        self._temps = np.ones((s,), np.float32)
        self._topks = np.zeros((s,), np.int32)
        self._sample = np.zeros((s,), bool)
        self._requests = {}                           # slot -> Request
        self._lock = threading.RLock()
        self._closed = False
        # cached at construction (like the registry): swap the default
        # tracer BEFORE building the engine under test
        self._tracer = _tracing.default_tracer()
        # wide-event request log, same caching rule; subclasses set the
        # page->bytes factor once their cache layout is known
        self.events = _events.default_request_log()
        self._kv_page_bytes = 0
        self.trace_counts = {k: 0 for k in self._programs}
        # scrape-visible retrace canary: flat at 1 per program == the
        # bounded-compilation contract holds in production, not just
        # under the test
        trace_gauge = self.metrics.registry.gauge(
            'serving_trace_count',
            'times each serving program has been traced '
            '(flat == zero retrace)', ('program',))
        self._m_trace = {k: trace_gauge.labels(k)
                         for k in self.trace_counts}
        # performance introspection (monitor/perf): the watchdog turns
        # the "exactly one program per key" invariant from a test
        # assertion into a production watch — once every program this
        # engine will run has traced, step() declares the warmup
        # barrier and any further compile on THIS engine's stack is a
        # counted, attributed recompile (hard-fail under
        # PADDLE_TPU_COMPILE_STRICT=1). The timeline splits each decode
        # burst into host-dispatch vs device-blocked time.
        self.perf = CompileWatchdog(registry=self.metrics.registry,
                                    tracer=self._tracer, owner=self,
                                    name=type(self).__name__)
        self.timeline = StepTimeline(registry=self.metrics.registry,
                                     tracer=self._tracer)
        self._decode_args = None

    # ---- front door ---------------------------------------------------

    def add_request(self, prompt, max_new_tokens=32, temperature=1.0,
                    top_k=0, do_sample=False, seed=0, stream=False,
                    tenant=None, priority=0, model=None, emit_event=True):
        """Queue a generation request; returns the Request handle.

        `tenant` is the attribution dimension: it rides the request into
        the per-tenant metric families and the wide event. `model` is
        the second attribution dimension (multi-model gateways route on
        it; a single-model engine just records it). `priority` (int,
        higher wins) orders admission and — on the paged engine with
        preempt=True — marks lower-priority residents evictable.
        `emit_event=False` suppresses this engine's wide event — the
        gateway sets it so a failed-over request still produces exactly
        ONE canonical record (the gateway's, which knows the failover
        history)."""
        req = Request(prompt, max_new_tokens=max_new_tokens,
                      temperature=temperature, top_k=top_k,
                      do_sample=do_sample, seed=seed, tenant=tenant,
                      priority=priority, model=model)
        req._emit_event = bool(emit_event)
        if stream:
            req._stream_q = _queue.Queue()
        return self.enqueue(req)

    def enqueue(self, req):
        """Admit a pre-built scheduler.Request through the front door —
        the ModelHost path: a multi-model host constructs the Request at
        submission (stamping its arrival time), parks it while weights
        load, then enqueues it here without re-timestamping. All
        validation, metrics and tracing of add_request happen here."""
        req._emit_event = getattr(req, '_emit_event', True)
        req._tenant_label = self.metrics.tenant_label(req.tenant)
        req._model_label = self.metrics.model_label(
            getattr(req, 'model', None))
        # front-door guard, shared by BOTH engines (the paged subclass
        # overrides _validate without chaining): a request whose worst
        # case — prompt plus every generated token but the last — cannot
        # fit the cache would sit at the queue head forever, wedging
        # admission for everyone behind it. Fail loud at submission.
        worst = len(req.prompt) + req.max_new_tokens - 1
        if len(req.prompt) and worst > self.max_len:
            raise ValueError(
                'request cannot ever be admitted: prompt of %d tokens + '
                'max_new_tokens=%d needs %d cache rows but max_len=%d'
                % (len(req.prompt), req.max_new_tokens, worst,
                   self.max_len))
        with self._lock:
            if self._closed:
                raise RuntimeError(
                    'engine is shut down — it no longer admits requests')
            self._validate(req)
            self.scheduler.submit(req)
            t = self.metrics.now()
            if req._arrival_t is None:
                req._arrival_t = t
            self.metrics.on_arrival(req.id, req._arrival_t)
            tr = self._tracer
            if tr.enabled:
                tags = {'request_id': req.id,
                        'prompt_len': len(req.prompt),
                        'max_new_tokens': req.max_new_tokens}
                if req.tenant is not None:
                    tags['tenant'] = req._tenant_label
                if getattr(req, 'model', None) is not None:
                    tags['model'] = req._model_label
                # root=True: the request owns its trace even when
                # submitted inside a gateway routing/failover span —
                # tail retention decides at THIS span's finish, and the
                # wide event's trace_id joins to exactly this tree
                req._span = tr.start_span('serving.request', tags=tags,
                                          root=True)
                req._span.add_event('queued',
                                    queue_depth=len(self.scheduler.queue))
        return req

    def _validate(self, req):
        """Subclass hook: extra front-door checks (lock held)."""

    def shutdown(self):
        """Refuse all future add_request calls. In-flight requests may
        still be driven to completion with step()/run(); shutdown only
        closes the front door."""
        with self._lock:
            self._closed = True
            self.perf.close()

    def step(self):
        """One scheduler iteration: admit → prefill chunks → decode
        burst → retire. Returns the number of requests still pending."""
        with self._lock, no_grad_guard():
            self._admit()
            self._prefill_step()
            self._decode_step()
            self.metrics.on_step(self.allocator.in_use, self.num_slots)
            self.metrics.on_queue_depth(len(self.scheduler.queue))
            self._on_step_metrics()
            for prog, child in self._m_trace.items():
                child.set(self.trace_counts[prog])
            if not self.perf.armed and all(
                    self.trace_counts[p] > 0
                    for p in self._warm_programs()):
                self.perf.declare_warmup(
                    '%s steady state' % type(self).__name__)
            return self.scheduler.pending

    def run(self):
        """Drive until every submitted request has finished."""
        while True:
            with self._lock:
                if not self.scheduler.pending:
                    return
                self.step()

    def generate(self, prompts, **sampling):
        """Blocking batch door: submit all, drive to completion, return
        generated ids per prompt (prompt not included) in order."""
        reqs = [self.add_request(p, **sampling) for p in prompts]
        self.run()
        return [r.tokens for r in reqs]

    def stream(self, req):
        """Yield req's tokens as they are produced. Cooperative: if no
        other thread is driving the engine, this one steps it."""
        q = req._stream_q
        if q is None:
            raise ValueError('request was not added with stream=True')
        while True:
            try:
                tok = q.get_nowait()
            except _queue.Empty:
                if req.done:
                    return         # sentinel already consumed
                self.step()
                continue
            if tok is None:
                return
            yield tok

    def compiled_sizes(self):
        """Times each program has been traced — the no-retrace metric."""
        return dict(self.trace_counts)

    def _warm_programs(self):
        """Programs that must trace before the watchdog's warmup
        barrier can be declared (subclasses drop conditional ones)."""
        return self._programs

    def rebind_perf(self, registry):
        """Move the perf instrumentation onto `registry` (the gateway
        replica pattern: engine metrics live on a private per-replica
        registry so counters stay per-replica honest). The fresh
        watchdog starts disarmed; the next step() re-declares warmup
        once the trace counts check out."""
        self.perf.close()
        self.perf = CompileWatchdog(registry=registry,
                                    tracer=self._tracer, owner=self,
                                    name=type(self).__name__)
        self.timeline = StepTimeline(registry=registry,
                                     tracer=self._tracer)
        return self

    def _perf_target(self):
        """(jitted_fn, last-dispatch args) for the steady-state program
        the cost model should price — the decode program by default
        (the spec-decode engine overrides with its verify program)."""
        return self._decode_jit, self._decode_args

    def perf_estimate(self, bursts=None, wall_seconds=None):
        """Cost-model estimate of the steady-state program (the
        dollar spender): analytic flops/bytes, roofline bound, warm
        compile seconds — plus mfu_est when told how many decode bursts
        ran over a measured wall. None before the first burst dispatch.

        The deliberate lower+compile here is watchdog-suspended (it is
        a measurement, not a retrace) and reuses the exact arrays of
        the last dispatch, so the traced avals match and the program's
        trace count stays flat."""
        jit_fn, args = self._perf_target()
        if args is None:
            return None
        with self._lock, self.perf.suspended():
            import time as _time
            t0 = _time.monotonic()
            compiled = jit_fn.lower(*args).compile()
            warm_s = _time.monotonic() - t0
        step_s = None
        if bursts and wall_seconds and bursts > 0:
            step_s = wall_seconds / float(bursts)
        est = _costmodel.estimate(compiled, step_seconds=step_s)
        if est is None:
            return None
        est['compile_s_warm'] = warm_s
        return est

    @property
    def occupancy(self):
        return self.allocator.occupancy

    # ---- scheduler glue (lock held) -----------------------------------

    def _admit(self):
        for slot, req in self.scheduler.admit():
            req._admit_t = self.metrics.now()
            self.metrics.on_admitted(req.id)
            if req._preempts:
                # a previously preempted request coming back: the
                # regenerated prefix is swallowed via req._replay, so
                # the caller-visible stream resumes where it stopped
                self.metrics.on_resumed(req._tenant_label)
                if req._span is not None:
                    req._span.add_event('resumed', preempts=req._preempts)
            if req._span is not None:
                req._span.add_event('admitted', slot=slot)
                req._phase = self._tracer.start_span(
                    'serving.prefill', parent=req._span,
                    tags={'slot': slot})
            self._requests[slot] = req
            self._budgets[slot] = req.max_new_tokens
            self._temps[slot] = req.temperature
            self._topks[slot] = req.top_k
            self._sample[slot] = req.do_sample
            # generate()'s stream: key = PRNGKey(seed), split once at
            # prefill end — created here, advanced by the final chunk
            req._key = np.asarray(jax.random.PRNGKey(req.seed))
            # no cache reset needed: the first prefill chunk writes from
            # the occupant's own offset and its write-back length
            # unreaches the previous occupant's rows
            self._bind(slot, req)

    def _bind(self, slot, req):
        """Subclass hook: extra per-admission state (lock held)."""

    def _on_step_metrics(self):
        """Subclass hook: extra per-step gauges (lock held)."""

    def _trace_prefill(self, req, start, valid, final):
        """Annotate the request's prefill phase span with one chunk; the
        final chunk closes it and opens the decode phase (lock held)."""
        if req._phase is None:
            return
        req._phase.add_event('prefill_chunk', start=start, valid=valid)
        if final:
            req._phase.finish()
            req._phase = self._tracer.start_span(
                'serving.decode', parent=req._span,
                tags={'slot': req.slot})

    def _emit(self, req, tokens):
        if req._replay:
            # post-preemption regeneration: the first _replay tokens
            # were already delivered before the eviction; determinism
            # (same prompt, sampling, seed) makes the regenerated ones
            # identical, so swallow them — no duplicates, no double
            # counting in the token metrics
            drop = min(req._replay, len(tokens))
            req._replay -= drop
            tokens = tokens[drop:]
        if not tokens:
            return
        req.tokens.extend(tokens)
        if req._stream_q is not None:
            for t in tokens:
                req._stream_q.put(t)
        if req._first_token_t is None:
            req._first_token_t = self.metrics.now()
            if req._arrival_t is not None:
                self.metrics.on_tenant_ttft(
                    req._tenant_label, req._first_token_t - req._arrival_t)
        self.metrics.on_tenant_tokens(req._tenant_label, len(tokens))
        self.metrics.on_tokens(
            req.id, len(tokens),
            trace_id=None if req._span is None else req._span.trace_id)

    def _retire(self, req, outcome='ok'):
        req.outcome = outcome
        slot = req.slot
        self._active[slot] = False
        del self._requests[slot]
        self.scheduler.retire(req)     # sets req.kv_page_seconds
        req._finish_t = self.metrics.now()
        self.metrics.on_retired(req.id)
        self.metrics.on_tenant_retired(
            req._tenant_label, req.kv_page_seconds * self._kv_page_bytes)
        if req._phase is not None:
            req._phase.finish()
            req._phase = None
        if req._span is not None:
            req._span.set_tag('tokens', len(req.tokens))
            req._span.add_event('retired')
            req._span.finish()
        self._emit_wide_event(req, outcome)

    def _emit_wide_event(self, req, outcome):
        """THE canonical per-request record (monitor/events.py). One
        load + branch when the log is disabled; skipped entirely for
        gateway-managed requests (the gateway emits the canonical one,
        with the failover history only it knows)."""
        log = self.events
        if not log.enabled or not req._emit_event:
            return
        wait = (req._admit_t - req._arrival_t) \
            if req._admit_t is not None and req._arrival_t is not None \
            else None
        log.emit(
            request_id=req.id,
            tenant=req._tenant_label,
            model=getattr(req, '_model_label', None),
            priority=req.priority,
            trace_id=None if req._span is None else req._span.trace_id,
            arrival_t=req._arrival_t,
            admit_t=req._admit_t,
            first_token_t=req._first_token_t,
            finish_t=req._finish_t,
            queue_wait_s=wait,
            prefill_chunks=req._prefill_chunks,
            prompt_tokens=len(req.prompt),
            output_tokens=len(req.tokens),
            prefix_hit_tokens=req._prefix_hit,
            spec_proposed=req._spec_proposed,
            spec_accepted=req._spec_accepted,
            kv_page_seconds=req.kv_page_seconds,
            failovers=0,
            replicas=[],
            outcome=outcome)


class ContinuousBatchingEngine(_EngineBase):
    """Slot-based continuous batching over a GPTForCausalLM.

    Every slot reserves `max_len` KV rows (GPTSlotCache); see
    PagedContinuousBatchingEngine for the page-granular variant with
    prefix sharing and speculative decoding.
    """

    def __init__(self, model, num_slots=8, max_len=None, prefill_chunk=16,
                 decode_block=4, donate=None):
        super().__init__(model, num_slots, max_len)
        self.decode_block = int(decode_block)
        if self.decode_block < 1:
            raise ValueError('decode_block must be >= 1')
        self._caches = build_slot_caches(model, self.num_slots, self.max_len)
        self.allocator = SlotAllocator(self.num_slots)
        self.scheduler = Scheduler(self.allocator, self.max_len,
                                   prefill_chunk)
        # billing unit for kv_byte_seconds: a slot reserves max_len rows
        self._kv_page_bytes = _kv_row_bytes(model) * self.max_len
        if donate is None:
            # cache buffers dominate engine memory; donating them lets
            # XLA update in place. CPU donation is a no-op that warns.
            donate = jax.default_backend() in ('tpu', 'gpu')
        dn = (2,) if donate else ()
        self._prefill_jit = jax.jit(self._prefill_fn, donate_argnums=dn)
        self._decode_jit = jax.jit(self._decode_fn, donate_argnums=dn)

    # ---- the two compiled programs ------------------------------------

    def _prefill_fn(self, params, bufs, caches, slot, ids, start, valid,
                    key, temp, topk, sample):
        """One [1, C] prompt chunk into slot `slot` at row `start`.

        Only `valid` of the C tokens are real; padded rows write garbage
        K/V beyond the valid length, which the write-back length
        (`start + valid`) keeps unreachable (the next chunk or decode
        step overwrites row start+valid before it becomes visible).
        Returns the updated caches, the post-chunk logits' pick (only
        meaningful on the final chunk) and the advanced PRNG key.
        """
        self.trace_counts['prefill'] += 1
        small = []
        for c in caches:
            ks = jax.lax.dynamic_slice_in_dim(c.k._data, slot, 1, axis=0)
            vs = jax.lax.dynamic_slice_in_dim(c.v._data, slot, 1, axis=0)
            small.append(GPTSlotCache(Tensor(ks), Tensor(vs),
                                      jnp.full((1,), start, jnp.int32)))
        (lg, small2), _ = _fm.functional_call(
            self._model, params, bufs, args=(Tensor(ids),),
            kwargs={'caches': small}, training=False)
        new_caches = []
        for c, s2 in zip(caches, small2):
            kb = jax.lax.dynamic_update_slice(
                c.k._data, s2.k._data, (slot, 0, 0, 0))
            vb = jax.lax.dynamic_update_slice(
                c.v._data, s2.v._data, (slot, 0, 0, 0))
            new_caches.append(GPTSlotCache(
                Tensor(kb), Tensor(vb),
                c.lengths.at[slot].set(start + valid)))
        last = jax.lax.dynamic_index_in_dim(lg[0], valid - 1, axis=0,
                                            keepdims=False)
        key2, sub = jax.random.split(key)
        tok = _pick_token(last, sub, temp, topk, sample)
        return new_caches, tok, key2

    def _decode_fn(self, params, bufs, caches, tok, gen, budgets, active,
                   keys, temps, topks, sample):
        """K cached decode steps for all slots in one dispatch.

        `step_active` freezes slots that are unoccupied, mid-prefill, or
        out of budget: their lengths / gen counts / keys do not advance
        and their fed token repeats, so a frozen slot's garbage logits
        never leak into state. The scan length is the FIXED decode_block
        — a finishing slot idles for the burst's remainder rather than
        shortening it (a variable length would recompile)."""
        self.trace_counts['decode'] += 1

        def body(carry, _):
            caches, tok, gen, keys = carry
            step_active = active & (gen < budgets)
            (lg, new_cs), _ = _fm.functional_call(
                self._model, params, bufs, args=(Tensor(tok),),
                kwargs={'caches': caches}, training=False)
            inc = step_active.astype(jnp.int32)
            new_cs = [GPTSlotCache(c.k, c.v, c.lengths + inc)
                      for c in new_cs]
            ks = jax.vmap(jax.random.split)(keys)       # [S, 2, 2]
            subs = ks[:, 1]
            keys2 = jnp.where(step_active[:, None], ks[:, 0], keys)
            nxt = jax.vmap(_pick_token)(lg[:, -1], subs, temps, topks,
                                        sample)
            tok2 = jnp.where(step_active, nxt, tok[:, 0])[:, None]
            return (new_cs, tok2, gen + inc, keys2), (tok2[:, 0],
                                                      step_active)

        carry, (toks, actives) = jax.lax.scan(
            body, (caches, tok, gen, keys), None, length=self.decode_block)
        new_caches, tok2, gen2, keys2 = carry
        return new_caches, tok2, gen2, keys2, toks, actives

    # ---- per-step dispatches (lock held) ------------------------------

    def _prefill_step(self):
        for req, start, ids, valid, final in self.scheduler.prefill_plan():
            slot = req.slot
            # mid chunks receive (and discard) the request key so only
            # the final chunk's split advances the sampling stream
            self._caches, tok, key2 = self._prefill_jit(
                self._params, self._bufs, self._caches,
                np.int32(slot),
                np.asarray(ids, np.int32)[None, :],
                np.int32(start), np.int32(valid), req._key,
                np.float32(req.temperature), np.int32(req.top_k),
                np.asarray(req.do_sample))
            self.metrics.on_prefill_tokens(valid)
            self.scheduler.mark_prefilled(req, start + valid)
            self._trace_prefill(req, start, valid, final)
            if not final:
                continue
            tok = int(tok)
            self._last[slot, 0] = tok
            self._gen[slot] = 1
            self._keys[slot] = np.asarray(key2)
            self._active[slot] = True
            self._emit(req, [tok])
            if len(req.tokens) >= req.max_new_tokens:
                self._retire(req)

    def _decode_step(self):
        slots = self.scheduler.decode_slots()
        if not slots:
            return
        # span covers dispatch AND the device_get sync — the burst's
        # actual wall time, not just the async enqueue. The timeline
        # splits the same window: host_dispatch (enqueue returns) vs
        # device_block (results ready). Dispatch args are stashed for
        # perf_estimate's cost-model lowering (same avals, no retrace).
        args = (self._params, self._bufs, self._caches, self._last,
                self._gen, self._budgets, self._active, self._keys,
                self._temps, self._topks, self._sample)
        self._decode_args = args
        with self._tracer.start_span('serving.decode_burst',
                                     tags={'rows': len(slots),
                                           'block': self.decode_block}):
            with self.timeline.phase('host_dispatch'):
                (self._caches, last, gen, keys, toks,
                 actives) = self._decode_jit(*args)
            with self.timeline.phase('device_block'):
                last, gen, keys, toks, actives = jax.device_get(
                    (last, gen, keys, toks, actives))
        self.timeline.end_step()
        # device_get can hand back read-only views; these three are
        # mutated in place at prefill/retire
        self._last = np.array(last)
        self._gen = np.array(gen)
        self._keys = np.array(keys)
        for slot in slots:
            req = self._requests[slot]
            new = [int(toks[k, slot]) for k in range(toks.shape[0])
                   if actives[k, slot]]
            self._emit(req, new)
            if len(req.tokens) >= req.max_new_tokens:
                self._retire(req)
