"""Slot bookkeeping for the continuous-batching KV cache.

The device side is `GPTSlotCache` (text/models/gpt.py): per layer, fixed
[num_slots, max_len, H, Dh] buffers plus a per-slot valid-length vector.
This module owns the HOST side: which slots are free, which request owns
which slot, and construction of the per-layer cache pool for a model.

Slot reuse needs no buffer clearing: a new occupant's chunked prefill
writes from offset 0 and the validity mask never lets a query see rows
at/beyond the slot's current length, so the previous occupant's rows are
unreachable the moment lengths[slot] resets (the engine's first prefill
chunk writes back `start + valid` = the new occupant's own length).
"""
import heapq

__all__ = ['SlotAllocator', 'build_slot_caches']


class SlotAllocator:
    """Free-list over a fixed number of KV-cache slots.

    Lowest-index-first allocation (a heap, not a LIFO stack) keeps slot
    assignment deterministic for a given arrival order — parity tests
    replay the same workload and must see the same slot layout.
    """

    def __init__(self, num_slots):
        if num_slots < 1:
            raise ValueError('num_slots must be >= 1, got %d' % num_slots)
        self.num_slots = num_slots
        self._free = list(range(num_slots))
        heapq.heapify(self._free)
        self._owner = {}  # slot -> opaque owner (request id)

    def alloc(self, owner):
        """Claim the lowest free slot for `owner`; None when full."""
        if not self._free:
            return None
        slot = heapq.heappop(self._free)
        self._owner[slot] = owner
        return slot

    def free(self, slot):
        if slot not in self._owner:
            raise ValueError('slot %d is not allocated' % slot)
        del self._owner[slot]
        heapq.heappush(self._free, slot)

    def owner_of(self, slot):
        return self._owner.get(slot)

    @property
    def in_use(self):
        return len(self._owner)

    @property
    def available(self):
        return len(self._free)

    @property
    def occupancy(self):
        """Fraction of slots occupied, the per-step utilization metric."""
        return len(self._owner) / float(self.num_slots)


def build_slot_caches(model, num_slots, max_len):
    """One GPTSlotCache per transformer layer of a GPTForCausalLM.

    dtype follows the token embedding (bf16 on TPU serving), matching
    what GPTForCausalLM.generate() does for its static cache.
    """
    from ..text.models.gpt import GPTSlotCache
    config = model.config
    if max_len > config.max_position_embeddings:
        raise ValueError(
            'slot capacity %d exceeds max_position_embeddings %d'
            % (max_len, config.max_position_embeddings))
    dtype = str(model.gpt.wte.weight.dtype).replace('paddle.', '')
    head_dim = config.hidden_size // config.num_heads
    return [GPTSlotCache.empty(num_slots, max_len, config.num_heads,
                               head_dim, dtype=dtype)
            for _ in model.gpt.h]
