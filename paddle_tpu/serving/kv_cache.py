"""Slot and page bookkeeping for the serving KV caches.

Two device layouts share this host module:

- `GPTSlotCache` (text/models/gpt.py): per layer, fixed
  [num_slots, max_len, H, Dh] buffers plus a per-slot valid-length
  vector — every slot reserves `max_len` rows. `SlotAllocator` owns
  which slots are free and who holds them.
- `GPTPagedCache`: per layer, a pool of [num_pages, page_size, H, Dh]
  pages addressed through per-sequence block tables — a sequence only
  holds the pages it needs, and sequences sharing a prompt prefix map
  their leading block-table entries to the SAME physical page.
  `PageAllocator` (refcounted free list) and `PrefixCache` (block-hash
  -> page, LRU) own the host side.

Neither layout needs buffer clearing on reuse: a new occupant's prefill
writes from its own offset 0 and the validity mask never lets a query
see rows at/beyond the owning sequence's current length, so a previous
occupant's rows are unreachable the moment the length resets (the
engine's first prefill chunk writes back the new occupant's own length).
"""
import heapq
import time
from collections import OrderedDict

__all__ = ['SlotAllocator', 'build_slot_caches', 'PageAllocator',
           'PrefixCache', 'build_paged_pools', 'SCRATCH_PAGE']


class SlotAllocator:
    """Free-list over a fixed number of KV-cache slots.

    Lowest-index-first allocation (a heap, not a LIFO stack) keeps slot
    assignment deterministic for a given arrival order — parity tests
    replay the same workload and must see the same slot layout.
    """

    def __init__(self, num_slots, clock=None):
        if num_slots < 1:
            raise ValueError('num_slots must be >= 1, got %d' % num_slots)
        self.num_slots = num_slots
        self.clock = clock or time.monotonic
        self._free = list(range(num_slots))
        heapq.heapify(self._free)
        self._owner = {}  # slot -> opaque owner (request id)
        self._held_since = {}  # slot -> advance timestamp at alloc
        self._integral = 0.0   # integral of in_use over time (slot*s)
        self._last_t = self.clock()

    def _advance(self):
        """Accrue the occupancy integral up to now; returns now. Every
        state change routes through here, so per-request holding times
        measured from the SAME timestamps sum exactly to the pool
        integral (the billing cross-check in bench/request_report)."""
        now = self.clock()
        self._integral += len(self._owner) * (now - self._last_t)
        self._last_t = now
        return now

    def touch(self):
        """Public advance: accrue the integral and return the shared
        timestamp (schedulers stamp request holding windows with it)."""
        return self._advance()

    def page_seconds(self):
        """The pool-occupancy integral: sum over time of slots held, in
        slot·seconds (one slot == the allocation granule == one 'page'
        for attribution purposes)."""
        self._advance()
        return self._integral

    def alloc(self, owner):
        """Claim the lowest free slot for `owner`; None when full."""
        if not self._free:
            return None
        now = self._advance()
        slot = heapq.heappop(self._free)
        self._owner[slot] = owner
        self._held_since[slot] = now
        return slot

    def held_since(self, slot):
        """The integral timestamp at which `slot` was allocated."""
        return self._held_since.get(slot)

    def free(self, slot):
        """Release `slot` back to the free list; returns the seconds it
        was held (measured on the integral's own timestamps).

        Freeing a slot that is not currently allocated — including a
        second free of the same slot — raises: a silent double-free here
        would put one slot on the free list twice and hand the SAME KV
        rows to two requests, which corrupts outputs rather than
        crashing. The page allocator below enforces the same rule.
        """
        if slot not in self._owner:
            raise ValueError(
                'slot %r is not allocated (double-free, or never '
                'allocated)' % (slot,))
        now = self._advance()
        del self._owner[slot]
        heapq.heappush(self._free, slot)
        return now - self._held_since.pop(slot)

    def owner_of(self, slot):
        return self._owner.get(slot)

    @property
    def in_use(self):
        return len(self._owner)

    @property
    def available(self):
        return len(self._free)

    @property
    def occupancy(self):
        """Fraction of slots occupied, the per-step utilization metric."""
        return len(self._owner) / float(self.num_slots)


# physical page 0 is never handed out: frozen/retired sequence rows keep
# their block-table entries pointed here so in-program garbage writes
# (padded prefill tails, masked decode lanes) land on rows nobody reads
SCRATCH_PAGE = 0


class PageAllocator:
    """Refcounted free list over the physical pages of a paged KV pool.

    Lowest-index-first allocation (heap) keeps page layout deterministic
    for a given workload, like SlotAllocator. Refcounts exist because a
    page can be held by several owners at once — every sequence whose
    block table maps to it, plus the prefix cache itself. `alloc` hands
    out a page at refcount 1; `incref`/`decref` move it up and down;
    the page returns to the free list only at refcount 0.
    """

    def __init__(self, num_pages, clock=None):
        if num_pages < 2:
            raise ValueError('num_pages must be >= 2 (page 0 is the '
                             'reserved scratch page), got %d' % num_pages)
        self.num_pages = num_pages
        self.clock = clock or time.monotonic
        self._free = list(range(1, num_pages))
        heapq.heapify(self._free)
        self._refs = {}  # page -> refcount (> 0)
        self._integral = 0.0  # integral of in_use over time (page*s)
        self._last_t = self.clock()

    def _advance(self):
        """Accrue the occupancy integral (distinct pages referenced x
        elapsed time) up to now; returns now. Shared pages count ONCE
        here no matter how many sequences map them — per-request
        attribution can therefore exceed the pool integral exactly when
        prefix sharing saves pool space."""
        now = self.clock()
        self._integral += len(self._refs) * (now - self._last_t)
        self._last_t = now
        return now

    def touch(self):
        """Public advance: accrue the integral and return the shared
        timestamp (schedulers stamp request holding windows with it)."""
        return self._advance()

    def page_seconds(self):
        """The pool-occupancy integral in page·seconds."""
        self._advance()
        return self._integral

    def alloc(self):
        """Claim the lowest free page at refcount 1; None when empty."""
        if not self._free:
            return None
        self._advance()
        page = heapq.heappop(self._free)
        self._refs[page] = 1
        return page

    def incref(self, page):
        if page not in self._refs:
            raise ValueError('page %r is not allocated' % (page,))
        self._refs[page] += 1

    def decref(self, page):
        """Drop one reference; frees the page at zero. Mirrors
        SlotAllocator.free's strictness: decref of an unallocated page
        (double-free included) raises instead of silently re-listing a
        page two owners would then share."""
        if page == SCRATCH_PAGE:
            raise ValueError('page 0 is the reserved scratch page')
        if page not in self._refs:
            raise ValueError(
                'page %r is not allocated (double-free, or never '
                'allocated)' % (page,))
        self._refs[page] -= 1
        if self._refs[page] == 0:
            self._advance()
            del self._refs[page]
            heapq.heappush(self._free, page)
            return True
        return False

    # free == "I was the only owner and I'm done" — intent-revealing
    # alias used by non-sharing call sites
    free = decref

    def refcount(self, page):
        return self._refs.get(page, 0)

    @property
    def in_use(self):
        return len(self._refs)

    @property
    def available(self):
        return len(self._free)

    @property
    def occupancy(self):
        """Fraction of allocatable pages currently referenced."""
        return len(self._refs) / float(self.num_pages - 1)


class PrefixCache:
    """Block-aligned prompt-prefix cache: chain-hash of token blocks ->
    the physical page already holding that block's K/V.

    Hashing is a CHAIN (each block's key folds in the previous block's
    key), so a hit on block b proves the entire prefix [0, (b+1)*P)
    matches — not just block b's own tokens. Only FULL blocks are ever
    cached, and `match` never covers a whole prompt (at least one token
    must remain to prefill, because the final chunk's logits seed the
    first generated token). Divergence inside a block therefore needs no
    page copy: the shared pages are immutable full blocks, and the
    divergent tail is prefilled into the requester's own private pages —
    copy-on-write degenerates to fill-on-write.

    The cache holds one allocator reference per entry, so published
    pages survive their publisher's retirement. `evict` drops
    least-recently-matched entries whose page nobody else references.
    """

    def __init__(self, page_size, allocator):
        if page_size < 1:
            raise ValueError('page_size must be >= 1')
        self.page_size = int(page_size)
        self.allocator = allocator
        self._pages = OrderedDict()   # chain hash -> page (LRU order)
        self.hits = 0                 # full blocks served from cache
        self.misses = 0               # full blocks that had to prefill

    @staticmethod
    def _chain(prev, block_tokens):
        return hash((prev, tuple(block_tokens)))

    def match(self, prompt):
        """Longest cached chain of full blocks covering at most
        len(prompt)-1 tokens: returns the page list (no refs taken —
        the caller increfs what it keeps)."""
        P = self.page_size
        nfull = (len(prompt) - 1) // P
        pages, h = [], None
        for b in range(nfull):
            h = self._chain(h, prompt[b * P:(b + 1) * P])
            page = self._pages.get(h)
            if page is None:
                self.misses += nfull - b
                break
            self._pages.move_to_end(h)
            pages.append(page)
            self.hits += 1
        return pages

    def publish(self, prompt, block_idx, page):
        """Register `page` as holding prompt block `block_idx` (all of
        whose tokens must already be prefilled into it). Takes one
        allocator reference. No-op (False) when the chain is already
        cached — the existing entry wins and the duplicate page stays
        private to its sequence."""
        P = self.page_size
        h = None
        for b in range(block_idx + 1):
            h = self._chain(h, prompt[b * P:(b + 1) * P])
        if h in self._pages:
            return False
        self.allocator.incref(page)
        self._pages[h] = page
        return True

    def evict(self, need):
        """Drop least-recently-matched entries whose page only the
        cache still references, until `need` pages were freed (or the
        candidates run out). Returns pages freed. Entries whose page a
        resident sequence still maps are skipped — eviction must never
        pull a page out from under a live block table."""
        freed = 0
        for h in list(self._pages):
            if freed >= need:
                break
            page = self._pages[h]
            if self.allocator.refcount(page) == 1:
                del self._pages[h]
                self.allocator.decref(page)
                freed += 1
        return freed

    def clear(self):
        """Drop every entry (each releases its cache reference)."""
        for h, page in list(self._pages.items()):
            del self._pages[h]
            self.allocator.decref(page)

    def __len__(self):
        return len(self._pages)


def build_paged_pools(model, num_pages, page_size):
    """One (k_pool, v_pool) jnp pair per transformer layer: the device
    arrays behind GPTPagedCache. Block tables / lengths stay host-side
    (the engine passes them per dispatch); only the pools are persistent
    device state. dtype follows the token embedding, like
    build_slot_caches."""
    import jax.numpy as jnp
    config = model.config
    dtype = str(model.gpt.wte.weight.dtype).replace('paddle.', '')
    head_dim = config.hidden_size // config.num_heads
    shape = (num_pages, page_size, config.num_heads, head_dim)
    return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in model.gpt.h]


def build_slot_caches(model, num_slots, max_len):
    """One GPTSlotCache per transformer layer of a GPTForCausalLM.

    dtype follows the token embedding (bf16 on TPU serving), matching
    what GPTForCausalLM.generate() does for its static cache.
    """
    from ..text.models.gpt import GPTSlotCache
    config = model.config
    if max_len > config.max_position_embeddings:
        raise ValueError(
            'slot capacity %d exceeds max_position_embeddings %d'
            % (max_len, config.max_position_embeddings))
    dtype = str(model.gpt.wte.weight.dtype).replace('paddle.', '')
    head_dim = config.hidden_size // config.num_heads
    return [GPTSlotCache.empty(num_slots, max_len, config.num_heads,
                               head_dim, dtype=dtype)
            for _ in model.gpt.h]
