"""Routing policies: order the replica pool for one placement attempt.

A router never *commits* a placement — it ranks. The gateway walks the
ranked candidates and submits to the first one whose transport accepts,
so a policy stays a pure function of observable replica state and the
failover path (try the next candidate) needs no policy cooperation.

Load is read from the live serving gauges each replica already exports
(`serving_queue_depth`, `serving_occupancy` — serving/metrics.py), not
from gateway-side shadow accounting: whatever a scrape of the replica
would show is exactly what the router balances on.
"""

__all__ = ['LeastLoadedRouter', 'ModelAffinityRouter', 'RoundRobinRouter']


class LeastLoadedRouter:
    """Rank routable replicas by live load, ties broken by index.

    load = queue_depth + occupancy * num_slots: queued requests and
    occupied slots cost the same one unit, so an idle replica beats a
    full one even when nothing is queued anywhere.
    """

    name = 'least_loaded'

    def candidates(self, pool):
        rs = [r for r in pool if r.routable()]
        rs.sort(key=lambda r: (r.load(), r.index))
        return rs


class ModelAffinityRouter(LeastLoadedRouter):
    """LeastLoaded with a model-residency tier in front: replicas whose
    engine already hosts the requested model's weights rank before ones
    that would have to page them in, least-loaded within each tier.

    The gateway calls `candidates_for(pool, model)` when a request names
    a model; requests without one (and single-model pools) fall through
    to the plain least-loaded ranking. Residency is read through the
    engine's `hosts_model` when it exists (registry.ModelHost); an
    ordinary engine has no residency notion and ranks in the cold tier —
    harmless, since a single-model pool never names models.
    """

    name = 'model_affinity'

    def candidates_for(self, pool, model):
        def hosts(r):
            fn = getattr(r.engine, 'hosts_model', None)
            return bool(fn(model)) if fn is not None else False
        rs = [r for r in pool if r.routable()]
        rs.sort(key=lambda r: (0 if hosts(r) else 1, r.load(), r.index))
        return rs


class RoundRobinRouter:
    """Rotate over routable replicas, blind to load — the baseline
    policy benches compare against (and the fallback when a deployment
    scrapes gauges too coarsely to trust them)."""

    name = 'round_robin'

    def __init__(self):
        self._next = 0

    def candidates(self, pool):
        rs = [r for r in pool if r.routable()]
        if not rs:
            return rs
        k = self._next % len(rs)
        self._next += 1
        return rs[k:] + rs[:k]
