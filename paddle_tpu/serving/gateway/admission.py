"""Front-door admission control for the gateway (QoS enforcement).

The policy objects live in `paddle_tpu.capacity.qos` — pure
stdlib/virtual-time classes (the AutoscalePolicy discipline: no clock,
no locks, caller owns time) — so the capacity simulator and the
offline tools (`tools/capacity_report.py --qos-policy`) sweep the
EXACT code path the gateway enforces, not a reimplementation. This
module is the serving-side door: it re-exports the policy vocabulary
and documents the contract the gateway holds it to.

Contract (`ServingGateway(admission=QosPolicy(...))`):

- `admit(now, tenant_label)` is called once per submit under the
  gateway lock, with the bounded TenantLabeler label — policy state
  cardinality is bounded by construction, like the tenant metric
  families.
- a rejection (`'rate'`/`'quota'`, or the gateway's own
  `'queue_full'`/`'deadline'` queue sheds) finishes the request
  immediately with outcome='rejected': one wide event, `error` set,
  stream sentinel delivered, no engine traffic. Callers see a finished
  handle, never an exception — overload is data, not a crash.
- `finish(tenant_label)` releases the concurrency slot exactly once
  per admitted request at any terminal outcome (delivered, errored,
  shed from the queue).
- `priority_of(tenant)` supplies the default `priority=` for tenants
  that did not pass one explicitly; priorities thread
  gateway -> engines in the sampling dict exactly like `tenant=`, so
  failover re-submits keep them.
"""
from ...capacity.qos import (REJECT_REASONS, QosPolicy, TenantClass,
                             TokenBucket)

__all__ = ['REJECT_REASONS', 'QosPolicy', 'TenantClass', 'TokenBucket']
