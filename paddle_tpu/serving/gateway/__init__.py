"""Multi-replica serving gateway: routing, failover, autoscaling.

Turns one continuous-batching engine into a self-healing pool:

    gw = ServingGateway(lambda: ContinuousBatchingEngine(model, ...),
                        replicas=2,
                        autoscaler=AutoscalePolicy(slo_ttft_s=0.5))
    gw.start()
    req = gw.submit(prompt, max_new_tokens=32)
    req.wait(); req.tokens      # token-identical to a single engine

Layering: replica.py wraps one engine as an endpoint-addressable worker
(chaos hook points, circuit breaker, private metric registry);
router.py ranks replicas on the live serving gauges; autoscaler.py is
the pure SLO-burn policy; gateway.py composes them behind one lock.
See docs/serving.md#gateway.
"""
from .admission import QosPolicy, TenantClass, TokenBucket
from .autoscaler import AutoscalePolicy, Decision, slo_burn_rate
from .gateway import GatewayRequest, ServingGateway
from .replica import InprocReplica
from .router import (LeastLoadedRouter, ModelAffinityRouter,
                     RoundRobinRouter)

__all__ = ['ServingGateway', 'GatewayRequest', 'InprocReplica',
           'LeastLoadedRouter', 'ModelAffinityRouter', 'RoundRobinRouter',
           'AutoscalePolicy', 'Decision', 'slo_burn_rate', 'QosPolicy',
           'TenantClass', 'TokenBucket']
