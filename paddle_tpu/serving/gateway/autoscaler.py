"""Elastic autoscaling policy: pure functions of (clock, observations).

The policy owns NO threads and reads NO globals — the gateway feeds it
`now` plus the current burn rate / occupancy / queue depth and applies
whatever Decision comes back. That makes every scaling behaviour (scale
up under sustained SLO burn, scale down when idle, hysteresis against
flapping) unit-testable with a fake clock and hand-picked observations,
the same injectable-clock discipline as monitor/registry.py.

Burn rate is computed from the gateway's TTFT samples, not from means:
the SLO is "p(TTFT > slo_ttft_s) stays low", so the signal is the
fraction of windowed requests over the target — a direct read of the
`gateway_ttft_seconds` histogram's tail.
"""
import collections

__all__ = ['Decision', 'AutoscalePolicy', 'slo_burn_rate']

Decision = collections.namedtuple('Decision', 'delta reason')


def slo_burn_rate(samples, now, slo_ttft_s, window_s):
    """Fraction of TTFT samples in the trailing window over the SLO.

    `samples` is an iterable of (t, ttft_seconds). No samples in the
    window means no evidence of burn — 0.0, never NaN.

    The snapshot below is load-bearing: the gateway hands its live
    `_ttfts` deque here, and driver threads append to it concurrently.
    Appends on a maxlen deque evict from the left, and iterating a
    deque while another thread mutates it raises RuntimeError — so
    iterate a tuple copy, never the live object.
    """
    samples = tuple(samples)
    recent = [ttft for (t, ttft) in samples if now - t <= window_s]
    if not recent:
        return 0.0
    over = sum(1 for ttft in recent if ttft > slo_ttft_s)
    return over / float(len(recent))


class AutoscalePolicy:
    """Hysteretic scale-up/down policy over SLO burn rate.

    Scale up when burn_rate >= burn_threshold has held for sustain_s;
    scale down when the pool has been demonstrably idle (zero burn,
    occupancy <= idle_occupancy, empty queue) for sustain_s. Both edges
    are suppressed by a shared cooldown_s after any action, and a signal
    that flaps resets its sustain timer — two mechanisms, one goal: a
    noisy burn series near the threshold must not saw the pool.
    """

    def __init__(self, slo_ttft_s, min_replicas=1, max_replicas=8,
                 burn_threshold=0.5, idle_occupancy=0.25, sustain_s=3.0,
                 cooldown_s=15.0, window_s=30.0, premium_tenants=()):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError('need 1 <= min_replicas <= max_replicas')
        self.slo_ttft_s = float(slo_ttft_s)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.burn_threshold = float(burn_threshold)
        self.idle_occupancy = float(idle_occupancy)
        self.sustain_s = float(sustain_s)
        self.cooldown_s = float(cooldown_s)
        self.window_s = float(window_s)
        # tenants whose PRIVATE burn triggers scale-up even while the
        # aggregate looks healthy: a small premium tenant drowned by a
        # large batch tenant's fast requests never moves the pool-wide
        # burn fraction, so the aggregate alone under-scales exactly
        # when the highest-value SLO is burning
        self.premium_tenants = tuple(premium_tenants)
        self._burn_since = None
        self._idle_since = None
        self._last_action_t = None

    def decide(self, now, burn_rate, occupancy, queue_depth, replicas,
               tenant_burns=None):
        """One policy evaluation; returns Decision(delta in {-1, 0, +1},
        reason). The caller applies the delta (and may refuse — the
        policy's own min/max clamp already makes refusal rare).
        `tenant_burns` (label -> burn fraction, optional) feeds the
        premium_tenants early trigger; the gateway passes it only when
        premium tenants are configured."""
        hot = burn_rate >= self.burn_threshold
        hot_tenant = None
        if tenant_burns and self.premium_tenants:
            for t in self.premium_tenants:
                if tenant_burns.get(t, 0.0) >= self.burn_threshold:
                    hot_tenant = t
                    hot = True
                    break
        idle = (not hot and burn_rate == 0.0
                and occupancy <= self.idle_occupancy
                and queue_depth == 0)
        if hot:
            if self._burn_since is None:
                self._burn_since = now
        else:
            self._burn_since = None
        if idle:
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None
        cooling = (self._last_action_t is not None
                   and now - self._last_action_t < self.cooldown_s)
        if hot and now - self._burn_since >= self.sustain_s:
            if cooling:
                return Decision(0, 'hot but cooling down')
            if replicas >= self.max_replicas:
                return Decision(0, 'hot but at max_replicas=%d'
                                % self.max_replicas)
            self._last_action_t = now
            self._burn_since = None
            if hot_tenant is not None:
                return Decision(+1, 'premium tenant %r burn %.2f >= '
                                '%.2f for %.1fs'
                                % (hot_tenant,
                                   tenant_burns.get(hot_tenant, 0.0),
                                   self.burn_threshold, self.sustain_s))
            return Decision(+1, 'burn %.2f >= %.2f for %.1fs'
                            % (burn_rate, self.burn_threshold,
                               self.sustain_s))
        if idle and now - self._idle_since >= self.sustain_s:
            if cooling:
                return Decision(0, 'idle but cooling down')
            if replicas <= self.min_replicas:
                return Decision(0, 'idle but at min_replicas=%d'
                                % self.min_replicas)
            self._last_action_t = now
            self._idle_since = None
            return Decision(-1, 'idle (occupancy %.2f, empty queue) '
                            'for %.1fs' % (occupancy, self.sustain_s))
        return Decision(0, 'hold')
