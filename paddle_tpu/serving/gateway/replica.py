"""One engine replica behind the gateway: transport shim + lifecycle.

An InprocReplica wraps a ContinuousBatchingEngine (or the paged
variant) running in this process and gives it the same *shape* as a
remote worker:

- an endpoint string ('inproc://gw-replica-N') that chaos injectors
  scope to — every submission fires the resilience 'send' hook and
  every completed step fires 'recv', so `chaos.partition(endpoint)`
  black-holes this replica exactly as it would a socket peer;
- a per-endpoint CircuitBreaker (distributed/resilience.py) with
  in-proc defaults: one transport failure means partitioned-or-dead,
  not a blip, so a single strike opens the breaker and the gateway
  replaces rather than retries;
- its OWN MetricRegistry. Engines on the shared default registry would
  collide on the unlabeled serving gauges (last-writer-wins); a private
  registry per replica keeps `serving_queue_depth` / `serving_occupancy`
  honest, which is exactly what the router load-balances on — and what
  `metrics_server()` exposes for a real per-replica scrape.

Lifecycle: READY -> DRAINING (no new admissions, in-flight decode
finishes) -> STOPPED, or -> DEAD on transport loss. The gateway owns
all transitions except DRAINING -> STOPPED, which the driver thread
takes when the drained engine runs empty.
"""
import threading

from ...distributed.resilience import CircuitBreaker, fire_fault_points
from ...monitor.registry import MetricRegistry
from ..metrics import ServingMetrics

__all__ = ['InprocReplica', 'READY', 'DRAINING', 'DEAD', 'STOPPED',
           'STATE_CODES']

READY = 'ready'
DRAINING = 'draining'
DEAD = 'dead'
STOPPED = 'stopped'

# Replicas commonly share ONE model object (decode_gateway clones the
# engine, not the artifact). Compiled dispatches are re-entrant, but
# TRACING is not: functional_call swaps params through the shared
# module while jax traces, so two replicas' first steps racing each
# other leak tracers. One process-wide lock, held only while a replica
# still has untraced programs, serializes warmup and costs steady-state
# nothing.
_TRACE_LOCK = threading.Lock()

# gauge encoding for gateway_replica_state (docs/observability.md)
STATE_CODES = {READY: 0, DRAINING: 1, DEAD: 2, STOPPED: 3}


class InprocReplica:

    def __init__(self, index, engine, breaker=None, registry=None):
        self.index = int(index)
        self.engine = engine
        self.endpoint = 'inproc://gw-replica-%d' % self.index
        self.registry = registry if registry is not None \
            else MetricRegistry()
        # rebind the engine's metrics onto the private registry (the
        # bench-established pattern for multi-engine processes); the
        # construction-time trace gauge stays on the old registry, which
        # is fine — it is per-program, not per-replica
        engine.metrics = ServingMetrics(registry=self.registry)
        # the perf watchdog/timeline follow the metrics registry; the
        # rebind also re-keys the watchdog's owner filter so replica A's
        # armed watchdog ignores replica B's first-compile events
        engine.rebind_perf(self.registry)
        if breaker is None:
            breaker = CircuitBreaker(failure_threshold=1,
                                     reset_timeout=3600.0)
        breaker.bind_name(self.endpoint)
        self.breaker = breaker
        self.state = READY
        # GatewayRequest -> engine Request; guarded by the GATEWAY lock
        # (never touched by the driver thread directly)
        self.assigned = {}
        self._cv = threading.Condition()
        self._thread = None

    # ---- transport (chaos hook points fire around every engine op) ----

    def submit(self, prompt, **sampling):
        """Submit one request to the wrapped engine. Fires the 'send'
        hook first: a partitioned replica rejects the submission before
        the engine sees it, like a dead socket."""
        fire_fault_points('send', self.endpoint)
        # emit_event=False: the GATEWAY emits the one canonical wide
        # event per request (it alone knows the failover history); an
        # engine-level event per placement would double-count failovers
        eng_req = self.engine.add_request(prompt, emit_event=False,
                                          **sampling)
        # refresh the queue gauge immediately so the router's next
        # ranking sees this submission without waiting for a step
        self.engine.metrics.on_queue_depth(
            len(self.engine.scheduler.queue))
        return eng_req

    def step(self):
        """One engine step. Fires 'recv' after: a partition that lands
        mid-burst surfaces as a failed token delivery, which is the case
        failover must re-admit (tokens were generated but never made it
        back to the caller)."""
        if self._untraced():
            with _TRACE_LOCK:
                n = self.engine.step()
        else:
            n = self.engine.step()
        fire_fault_points('recv', self.endpoint)
        return n

    def _untraced(self):
        """Any program this engine will certainly trace still untraced?
        ('verify' only traces when speculation is on.)"""
        eng = self.engine
        skip = () if getattr(eng, 'spec_k', 0) else ('verify',)
        return any(v == 0 for k, v in eng.trace_counts.items()
                   if k not in skip)

    # ---- observable state ---------------------------------------------

    def _gauge(self, name):
        fam = self.registry.get(name)
        return 0.0 if fam is None else fam.value()

    def queue_depth(self):
        return self._gauge('serving_queue_depth')

    def occupancy(self):
        return self._gauge('serving_occupancy')

    def load(self):
        """Router ranking key: queued requests + occupied slots, both in
        request units."""
        return (self.queue_depth()
                + self.occupancy() * self.engine.num_slots)

    def routable(self):
        """May the router place NEW work here?"""
        return self.state == READY and self.breaker.allow()

    @property
    def alive(self):
        """Still worth stepping (in-flight work may exist)?"""
        return self.state in (READY, DRAINING)

    def ready(self):
        """/readyz readiness: READY routes, anything else 503s while
        /healthz stays 200 (drain must not get the process restarted)."""
        return self.state == READY

    def metrics_server(self, **kwargs):
        """A MetricsServer over this replica's private registry with
        readiness wired to its drain state (not started)."""
        from ...monitor.server import MetricsServer
        return MetricsServer(registry=self.registry, readiness=self.ready,
                             **kwargs)

    # ---- lifecycle (gateway lock held unless noted) -------------------

    def drain(self):
        """Stop admissions, let in-flight decode finish."""
        self._transition(DRAINING)
        self.engine.shutdown()

    def mark_dead(self):
        self._transition(DEAD)

    def mark_stopped(self):
        self._transition(STOPPED)

    def _transition(self, state):
        """All writes of `state` go through the condvar: the driver
        thread check-and-sets DRAINING -> STOPPED under _cv, so a bare
        write here could race it and overwrite DEAD with STOPPED."""
        with self._cv:
            self.state = state
            self._cv.notify_all()

    def wake(self):
        with self._cv:
            self._cv.notify_all()

    # ---- driver thread ------------------------------------------------

    def start_driver(self, on_step, on_lost):
        """Spawn the replica's drive loop: step whenever work exists,
        park on the condvar otherwise. `on_step(self)` runs after every
        successful step (the gateway collects tokens there);
        `on_lost(self, exc)` runs once on transport failure and the
        thread exits. Neither callback is invoked under the condvar, so
        the gateway lock ordering (gateway -> engine) holds."""
        def _run():
            while True:
                with self._cv:
                    while self.alive and not self.engine.scheduler.pending:
                        if self.state == DRAINING and not self.assigned:
                            self.state = STOPPED
                            return
                        self._cv.wait(0.02)
                    if not self.alive:
                        return
                try:
                    self.step()
                except Exception as exc:     # noqa: BLE001 — transport
                    on_lost(self, exc)
                    return
                on_step(self)

        self._thread = threading.Thread(
            target=_run, name='gw-replica-%d' % self.index, daemon=True)
        self._thread.start()
        return self._thread

    def join(self, timeout=None):
        if self._thread is not None:
            self._thread.join(timeout)

    def __repr__(self):
        return ('InprocReplica(%d, %s, load=%.1f, assigned=%d)'
                % (self.index, self.state, self.load(),
                   len(self.assigned)))
