"""One engine replica behind the gateway: transport shim + lifecycle.

An InprocReplica wraps a ContinuousBatchingEngine (or the paged
variant) running in this process and gives it the same *shape* as a
remote worker:

- an endpoint string ('inproc://gw-replica-N') that chaos injectors
  scope to — every submission fires the resilience 'send' hook and
  every completed step fires 'recv', so `chaos.partition(endpoint)`
  black-holes this replica exactly as it would a socket peer;
- a per-endpoint CircuitBreaker (distributed/resilience.py) with
  in-proc defaults: one transport failure means partitioned-or-dead,
  not a blip, so a single strike opens the breaker and the gateway
  replaces rather than retries;
- its OWN MetricRegistry. Engines on the shared default registry would
  collide on the unlabeled serving gauges (last-writer-wins); a private
  registry per replica keeps `serving_queue_depth` / `serving_occupancy`
  honest, which is exactly what the router load-balances on — and what
  `metrics_server()` exposes for a real per-replica scrape.

Lifecycle: READY -> DRAINING (no new admissions, in-flight decode
finishes) -> STOPPED, or -> DEAD on transport loss. The gateway owns
all transitions except DRAINING -> STOPPED, which the driver thread
takes when the drained engine runs empty.

The lifecycle ladder, condvar discipline and driver loop live in the
extracted base class (serving/fabric/transport.py) so a replica in
another PROCESS (fabric.SocketReplica) walks the identical ladder;
this module keeps only what is in-proc specific: the engine binding,
the chaos hook points, and the shared-model trace lock.
"""
import threading

from ...distributed.resilience import fire_fault_points
from ..fabric.transport import (DEAD, DRAINING, READY, STATE_CODES,
                                STOPPED, ReplicaTransport)
from ..metrics import ServingMetrics

__all__ = ['InprocReplica', 'READY', 'DRAINING', 'DEAD', 'STOPPED',
           'STATE_CODES']

# Replicas commonly share ONE model object (decode_gateway clones the
# engine, not the artifact). Compiled dispatches are re-entrant, but
# TRACING is not: functional_call swaps params through the shared
# module while jax traces, so two replicas' first steps racing each
# other leak tracers. One process-wide lock, held only while a replica
# still has untraced programs, serializes warmup and costs steady-state
# nothing.
_TRACE_LOCK = threading.Lock()


class InprocReplica(ReplicaTransport):

    def __init__(self, index, engine, breaker=None, registry=None):
        super().__init__(index, 'inproc://gw-replica-%d' % int(index),
                         breaker=breaker, registry=registry)
        self.engine = engine
        # rebind the engine's metrics onto the private registry (the
        # bench-established pattern for multi-engine processes); the
        # construction-time trace gauge stays on the old registry, which
        # is fine — it is per-program, not per-replica
        engine.metrics = ServingMetrics(registry=self.registry)
        # the perf watchdog/timeline follow the metrics registry; the
        # rebind also re-keys the watchdog's owner filter so replica A's
        # armed watchdog ignores replica B's first-compile events
        engine.rebind_perf(self.registry)

    # ---- transport (chaos hook points fire around every engine op) ----

    def submit(self, prompt, **sampling):
        """Submit one request to the wrapped engine. Fires the 'send'
        hook first: a partitioned replica rejects the submission before
        the engine sees it, like a dead socket."""
        fire_fault_points('send', self.endpoint)
        # emit_event=False: the GATEWAY emits the one canonical wide
        # event per request (it alone knows the failover history); an
        # engine-level event per placement would double-count failovers
        eng_req = self.engine.add_request(prompt, emit_event=False,
                                          **sampling)
        # refresh the queue gauge immediately so the router's next
        # ranking sees this submission without waiting for a step
        self.engine.metrics.on_queue_depth(
            len(self.engine.scheduler.queue))
        return eng_req

    def step(self):
        """One engine step. Fires 'recv' after: a partition that lands
        mid-burst surfaces as a failed token delivery, which is the case
        failover must re-admit (tokens were generated but never made it
        back to the caller)."""
        if self._untraced():
            with _TRACE_LOCK:
                n = self.engine.step()
        else:
            n = self.engine.step()
        fire_fault_points('recv', self.endpoint)
        return n

    def has_pending(self):
        return bool(self.engine.scheduler.pending)

    def _untraced(self):
        """Any program this engine will certainly trace still untraced?
        ('verify' only traces when speculation is on.)"""
        eng = self.engine
        skip = () if getattr(eng, 'spec_k', 0) else ('verify',)
        return any(v == 0 for k, v in eng.trace_counts.items()
                   if k not in skip)

    # ---- observable state ---------------------------------------------

    def _gauge(self, name):
        fam = self.registry.get(name)
        return 0.0 if fam is None else fam.value()

    def queue_depth(self):
        return self._gauge('serving_queue_depth')

    def occupancy(self):
        return self._gauge('serving_occupancy')

    def load(self):
        return (self.queue_depth()
                + self.occupancy() * self.engine.num_slots)

    # ---- lifecycle ----------------------------------------------------

    def drain(self):
        """Stop admissions, let in-flight decode finish."""
        super().drain()
        self.engine.shutdown()
