"""The gateway: one front door over a pool of engine replicas.

    gw = ServingGateway(lambda: ContinuousBatchingEngine(model, ...),
                        replicas=2)
    req = gw.submit(prompt, max_new_tokens=32)
    gw.run()                       # or gw.start() for driver threads
    req.tokens                     # identical to a single engine's output

Four jobs, one lock:

- **Admission.** With `admission=QosPolicy(...)` configured, submit()
  first runs the per-tenant token bucket + concurrency quota; a shed
  request finishes immediately with outcome='rejected' (one wide
  event, `error` set — overload is data, not an exception). The
  pending queue becomes bounded (`max_pending`: overflow sheds the
  lowest-priority parked request) and deadline-aware
  (`max_queue_wait_s`: parked past the deadline sheds on the next
  drain). See admission.py for the contract.
- **Routing.** submit() walks the router's ranked candidates and places
  the request on the first replica whose transport accepts; when none is
  routable the request parks in the gateway queue and is drained on the
  next step (highest priority first, FIFO within a class). Routing
  emits a `gateway.route` span and per-replica `gateway_route_total`
  counts.
- **Failover.** A replica lost mid-flight (chaos partition, driver
  exception, kill_replica) has every non-finished assigned request
  re-submitted elsewhere — full prompt, same seed. Engines are
  deterministic for a fixed (prompt, sampling, seed), so the new replica
  regenerates the identical token stream, and the gateway's
  delivered-token ledger (`GatewayRequest.tokens`) forwards only the
  suffix the caller has not seen: exactly-once delivery with
  exact-token parity, no idempotency tokens needed. The breaker opens
  on the loss, so the router never offers the dead replica again.
- **Autoscaling.** autoscale_tick() feeds the pure AutoscalePolicy the
  windowed TTFT SLO burn rate plus pool occupancy/queue depth and
  applies the Decision: +1 builds a replica from the engine factory,
  -1 drains the least-loaded READY replica (drain, never kill — its
  in-flight work finishes).

Locking: one gateway RLock guards pool membership, assignment maps,
the pending queue, and delivery; replica driver threads call back into
_collect/_on_lost which take it. Order is strictly gateway lock ->
engine lock (replica.submit/step run under the gateway lock only in
sync mode; drivers call them lock-free and only take the gateway lock
inside the callbacks), and the replica condvar is never held across a
callback.
"""
import collections
import itertools
import queue as _queue
import threading
import time

from ...monitor import events as _events
from ...monitor import tracing as _tracing
from ...monitor.registry import default_registry
from ...monitor.telemetry import (record_gateway_schema, record_qos_schema,
                                  record_tenant_schema)
from .autoscaler import slo_burn_rate
from .replica import DRAINING, READY, STATE_CODES, InprocReplica
from .router import LeastLoadedRouter

__all__ = ['ServingGateway', 'GatewayRequest']

_gw_ids = itertools.count()


class GatewayRequest:
    """Caller-facing handle: the delivered-token ledger.

    `tokens` holds only what the gateway has handed to the caller —
    after a failover the replacement replica regenerates from scratch
    and the gateway forwards `engine_tokens[len(self.tokens):]`, so the
    caller never sees a duplicate or a gap. `replica_history` records
    every placement (length > 1 == the request survived a failover).
    """

    def __init__(self, prompt, sampling, stream=False):
        self.id = next(_gw_ids)
        self.prompt = [int(t) for t in prompt]
        self.sampling = dict(sampling)
        self.tokens = []
        self.replica_history = []
        self.failovers = 0       # replica losses survived
        self.arrival_t = None
        self.first_token_t = None
        self.error = None        # set iff rejected/shed/failed
        self._eng_req = None     # current engine-side Request
        self._qos_label = None   # admission slot held, iff admitted
        self._stream_q = _queue.Queue() if stream else None
        self._finished = threading.Event()

    @property
    def done(self):
        return self._finished.is_set()

    def wait(self, timeout=None):
        return self._finished.wait(timeout)

    def stream(self):
        """Yield tokens as the gateway delivers them (requires
        submit(..., stream=True) and a start()ed gateway)."""
        if self._stream_q is None:
            raise ValueError('request was not submitted with stream=True')
        while True:
            tok = self._stream_q.get()
            if tok is None:
                return
            yield tok

    def __repr__(self):
        return ('GatewayRequest(id=%d, delivered=%d/%d, replicas=%s)'
                % (self.id, len(self.tokens),
                   self.sampling.get('max_new_tokens', 0),
                   self.replica_history))


class ServingGateway:

    def __init__(self, engine_factory, replicas=2, router=None,
                 autoscaler=None, admission=None, registry=None,
                 clock=None):
        if engine_factory is None:
            # fabric mode: the pool is populated by adopt_replica()
            # (e.g. SocketReplicas proxying worker processes), so there
            # is nothing to build locally
            if replicas:
                raise ValueError('engine_factory=None requires replicas=0 '
                                 '(populate the pool via adopt_replica)')
        elif replicas < 1:
            raise ValueError('need at least one replica')
        self._factory = engine_factory
        self._clock = clock or time.monotonic
        self.registry = registry if registry is not None \
            else default_registry()
        self.router = router if router is not None else LeastLoadedRouter()
        self.policy = autoscaler
        self.admission = admission      # capacity.qos.QosPolicy or None
        self._lock = threading.RLock()
        self._tracer = _tracing.default_tracer()
        fams = record_gateway_schema(self.registry)
        self._m_requests = fams['gateway_requests_total']
        self._m_completed = fams['gateway_requests_completed_total']
        self._m_tokens = fams['gateway_tokens_total']
        self._m_route = fams['gateway_route_total']
        self._m_retries = fams['gateway_retries_total']
        self._m_failover = fams['gateway_failover_total']
        self._m_scale = fams['gateway_scale_events_total']
        self._m_replicas = fams['gateway_replicas']
        self._m_state = fams['gateway_replica_state']
        self._m_queue = fams['gateway_queue_depth']
        self._m_burn = fams['gateway_slo_burn_rate']
        self._m_ttft = fams['gateway_ttft_seconds']
        # tenant attribution at the FRONT DOOR (replicas keep their own
        # engine-level tenant families on private registries): requests
        # and TTFT are observed here where failovers are invisible to
        # the caller, so a tenant's TTFT includes failover stalls
        tfams = record_tenant_schema(self.registry)
        self._m_tenant_requests = tfams['tenant_requests_total']
        self._m_tenant_ttft = tfams['tenant_ttft_seconds']
        qfams = record_qos_schema(self.registry)
        self._m_qos_admitted = qfams['qos_admitted_total']
        self._m_qos_rejected = qfams['qos_rejected_total']
        self._m_qos_bucket = qfams['qos_token_bucket_level']
        self._m_qos_ttft = qfams['qos_ttft_seconds']
        self._n_rejected = 0
        self._labeler = _events.TenantLabeler()
        self._model_labeler = _events.ModelLabeler()
        # wide-event log, cached at construction like the tracer
        self.events = _events.default_request_log()
        self.pool = []                      # never shrinks; index == id
        self._pending = collections.deque()
        self._ttfts = collections.deque(maxlen=4096)   # (t, ttft_s)
        # per-tenant TTFT windows for premium-burn autoscaling (bounded:
        # labeler caps tenant cardinality, deque caps window length)
        self._tenant_ttfts = {}             # label -> deque of (t, ttft_s)
        self.failover_log = []
        self._started = False
        # fleet telemetry (attach_fleet): replicas self-register as
        # in-proc scrape targets; burn_source, when set, replaces the
        # local TTFT window in autoscale_tick so the policy can act on
        # the FEDERATED burn (e.g. alerts.federated_burn_source) —
        # a gateway that only sees its own TTFTs under-scales when the
        # SLO is burning elsewhere in the fleet.
        self._fleet = None
        self.burn_source = None
        with self._lock:
            for _ in range(int(replicas)):
                self._add_replica_locked()

    # ---- front door ---------------------------------------------------

    def submit(self, prompt, max_new_tokens=32, stream=False, tenant=None,
               priority=None, model=None, **sampling):
        """Accept one request; returns the GatewayRequest handle.
        Raises ValueError for requests no replica could EVER admit (the
        engines' front-door guard) — those must fail the caller, not
        trip failover.

        `tenant` and `priority` fold into the sampling dict so a
        failover re-submit carries them: attribution and scheduling
        class survive replica loss by construction. `priority` defaults
        from the admission policy's tenant class (0 without one).
        `model` rides the same way (routed like tenant: the router
        prefers replicas already hosting it, the wide event records it);
        None means the deployment's single/default model.

        With an admission policy, a shed request comes back as an
        already-finished handle (`error` set, outcome='rejected' in the
        wide event) — never an exception: overload is data."""
        adm = self.admission
        if priority is None:
            priority = adm.priority_of(tenant) if adm is not None else 0
        sampling = dict(sampling, max_new_tokens=max_new_tokens,
                        tenant=tenant, priority=int(priority))
        if model is not None:
            sampling['model'] = model
        gw = GatewayRequest(prompt, sampling, stream=stream)
        with self._lock:
            gw.arrival_t = self._clock()
            if adm is not None:
                label = self._labeler.label(tenant)
                ok, reason = adm.admit(gw.arrival_t, label)
                lvl = adm.bucket_level(label, gw.arrival_t)
                if lvl is not None:
                    self._m_qos_bucket.labels(label).set(lvl)
                if not ok:
                    self._reject_locked(gw, reason)
                    return gw
                gw._qos_label = label
                self._m_qos_admitted.labels(label).inc()
            try:
                routed = self._route_locked(gw)  # ValueError: inadmissible
            except ValueError:
                self._qos_finish_locked(gw)
                raise
            self._m_requests.inc()
            if not routed:
                self._park_locked(gw)
            self._m_queue.set(len(self._pending))
        return gw

    def _park_locked(self, gw):
        """Queue gw for the next drain. With a bounded queue
        (admission.max_pending) an overflow sheds the lowest-priority
        request — the newest of the lowest class already parked if one
        sits strictly below gw, else gw itself."""
        adm = self.admission
        cap = None if adm is None else adm.max_pending
        if cap is not None and len(self._pending) >= cap:
            p_new = gw.sampling.get('priority') or 0
            victim = None
            for g in self._pending:      # keep the newest among equals
                pg = g.sampling.get('priority') or 0
                if pg < p_new and (victim is None or pg <=
                                   (victim.sampling.get('priority') or 0)):
                    victim = g
            if victim is None:
                self._reject_locked(gw, 'queue_full')
                return
            self._pending.remove(victim)
            self._reject_locked(victim, 'queue_full')
        self._pending.append(gw)

    def _reject_locked(self, gw, reason):
        """Finish gw as shed: exactly one wide event (outcome
        'rejected'), error set, stream closed, admission slot (if one
        was taken — queue sheds were admitted) released."""
        self._m_qos_rejected.labels(
            reason, self._labeler.label(gw.sampling.get('tenant'))).inc()
        self._n_rejected += 1
        self._qos_finish_locked(gw)
        gw.error = RuntimeError('rejected: %s' % reason)
        if gw._stream_q is not None:
            gw._stream_q.put(None)
        self._emit_wide_event_locked(gw, 'rejected')
        gw._finished.set()

    def _qos_finish_locked(self, gw):
        """Release gw's admission concurrency slot, exactly once."""
        if gw._qos_label is not None and self.admission is not None:
            self.admission.finish(gw._qos_label)
            gw._qos_label = None

    def generate(self, prompts, **sampling):
        """Blocking batch door, mirroring the engines' generate()."""
        reqs = [self.submit(p, **sampling) for p in prompts]
        if self._started:
            for r in reqs:
                r.wait()
        else:
            self.run()
        return [r.tokens for r in reqs]

    # ---- routing ------------------------------------------------------

    def _route_locked(self, gw):
        """Place gw on the first accepting candidate; False if none.
        A transport failure during placement counts as a retry AND a
        replica loss (in-proc transports don't blip — see replica.py),
        so one walk both fails over the dead replica's in-flight work
        and still places gw if anyone is left."""
        model = gw.sampling.get('model')
        if hasattr(self.router, 'candidates_for_request'):
            # request-aware routing (e.g. fabric.PrefixAffinityRouter):
            # the router sees the PROMPT, which candidates() never does
            candidates = self.router.candidates_for_request(self.pool, gw)
        elif model is not None and hasattr(self.router, 'candidates_for'):
            candidates = self.router.candidates_for(self.pool, model)
        else:
            candidates = self.router.candidates(self.pool)
        with self._tracer.start_span(
                'gateway.route', tags={'request_id': gw.id}) as span:
            for rep in candidates:
                if not rep.routable():     # lost earlier in this walk
                    continue
                try:
                    eng_req = rep.submit(gw.prompt, **gw.sampling)
                except ValueError:
                    raise                  # inadmissible — caller's error
                except Exception as exc:   # noqa: BLE001 — transport
                    self._m_retries.inc()
                    self._lost_locked(rep, exc)
                    continue
                rep.breaker.record_success()
                rep.assigned[gw] = eng_req
                gw._eng_req = eng_req
                gw.replica_history.append(rep.index)
                note = getattr(self.router, 'note_placement', None)
                if note is not None:
                    # feed the prefix directory on EVERY placement,
                    # failover re-placements included — the hint table
                    # tracks where the tokens actually went
                    note(gw.prompt, rep.index)
                self._m_route.labels(str(rep.index)).inc()
                span.set_tag('replica', rep.index)
                if gw.failovers and eng_req._span is not None:
                    # force-retain the replacement trace: a failed-over
                    # request's span tree must be retrievable from the
                    # wide event's trace_id no matter how fast it ran
                    ret = self._tracer.retention
                    if ret is not None:
                        ret.mark(eng_req._span.trace_id, 'failover')
                rep.wake()
                return True
            span.set_tag('replica', -1)
            return False

    def _drain_pending_locked(self):
        adm = self.admission
        if adm is not None and self._pending:
            if adm.max_queue_wait_s is not None:
                # deadline-aware shedding: a request parked past the
                # deadline will blow its SLO anyway — shed it now and
                # spend the capacity on fresher work
                now = self._clock()
                keep = collections.deque()
                while self._pending:
                    gw = self._pending.popleft()
                    if now - gw.arrival_t > adm.max_queue_wait_s:
                        self._reject_locked(gw, 'deadline')
                    else:
                        keep.append(gw)
                self._pending = keep
            if len(self._pending) > 1:
                # drain best-first; sorted() is stable, so FIFO holds
                # within a priority class
                self._pending = collections.deque(sorted(
                    self._pending,
                    key=lambda g: -(g.sampling.get('priority') or 0)))
        while self._pending:
            gw = self._pending.popleft()
            try:
                routed = self._route_locked(gw)
            except ValueError as exc:
                # a request parked while NO replica was routable turns
                # out inadmissible once one is: fail it out-of-band (the
                # submit() caller is long gone) instead of crashing the
                # driver thread that happened to drain the queue
                gw.error = exc
                if gw._stream_q is not None:
                    gw._stream_q.put(None)
                self._emit_wide_event_locked(gw, 'error')
                gw._finished.set()
                continue
            if not routed:
                self._pending.appendleft(gw)
                break
        self._m_queue.set(len(self._pending))

    # ---- failover -----------------------------------------------------

    def _lost_locked(self, rep, exc):
        """rep's transport failed: open its breaker, mark it dead, and
        re-admit every in-flight request elsewhere. Idempotent per
        replica (drivers and routing walks may both observe the loss)."""
        if not rep.alive:
            return
        opened = rep.breaker.record_failure()
        rep.mark_dead()
        victims = []
        for gw in list(rep.assigned):
            if len(gw.tokens) >= gw.sampling['max_new_tokens']:
                self._complete_locked(gw)   # fully delivered already
            else:
                victims.append(gw)
        rep.assigned.clear()
        self.failover_log.append({
            'replica': rep.index, 'error': repr(exc),
            'requests': [g.id for g in victims]})
        with self._tracer.start_span(
                'gateway.failover',
                tags={'from_replica': rep.index,
                      'requests': len(victims),
                      'breaker_opened': bool(opened)}):
            for gw in victims:
                self._m_failover.inc()
                gw.failovers += 1    # before routing: the replacement
                gw._eng_req = None   # trace gets the failover mark
                if not self._route_locked(gw):
                    self._pending.append(gw)
        self._m_queue.set(len(self._pending))
        self._refresh_gauges_locked()

    def kill_replica(self, index):
        """Declare replica `index` lost (the non-chaos failover door —
        tests and operators; chaos.partition exercises the same path
        through the transport hooks)."""
        with self._lock:
            rep = self.pool[index]
            self._lost_locked(rep, RuntimeError('replica killed'))
            return rep

    def drain_replica(self, index):
        """Gracefully drain replica `index`: no new admissions, its
        in-flight requests finish and deliver."""
        with self._lock:
            rep = self.pool[index]
            if rep.state == READY:
                rep.drain()
                self._refresh_gauges_locked()
            return rep

    # ---- hot-swap -----------------------------------------------------

    def rollout(self, model, new_version):
        """Zero-downtime version swap for `model` across the pool.

        Three phases, ordered so no request is ever lost:

        1. **Warm.** Every routable multi-model replica (its engine is a
           registry.ModelHost) loads + pins the new version NEXT TO the
           old one — a warm bring-up that must hit the compile cache
           (same program shapes, new weights). In-flight requests on the
           old version keep their weights: they hold refcounts.
        2. **Flip.** Each distinct ModelRegistry's serving pointer moves
           to `new_version` atomically — from this instant every new
           submit(model=...) resolves to the new version.
        3. **Drain.** The old version is unpinned and evicted ONCE its
           refcount drops to zero (deferred eviction — the PR 8
           drain-never-kill discipline applied to weights instead of
           replicas). Nothing is cancelled.

        Returns a summary dict; `cache_hits`/`cache_misses` are the
        compile-cache delta across all warm loads (a correct rollout
        warms entirely from cache). Raises ValueError when no replica
        hosts models (the pool is single-model) or the version is
        unknown."""
        with self._lock:
            hosts = [r for r in self.pool if r.routable()
                     and hasattr(r.engine, 'prepare_rollout')]
        if not hosts:
            raise ValueError('no routable replica hosts models — '
                             'rollout needs ModelHost-backed replicas')
        with self._tracer.start_span(
                'gateway.rollout',
                tags={'model': model, 'version': new_version,
                      'replicas': len(hosts)}):
            registries = []
            for r in hosts:
                reg = r.engine.registry
                if all(reg is not g for g in registries):
                    registries.append(reg)
            old = registries[0].serving_version(model)
            infos = [r.engine.prepare_rollout(model, new_version)
                     for r in hosts]
            for reg in registries:
                reg.set_serving(model, new_version)
            for r in hosts:
                r.engine.finish_rollout(model, old)
        return {
            'model': model,
            'from_version': old,
            'to_version': new_version,
            'replicas': [r.index for r in hosts],
            'cache_hits': sum(i.get('cache_hits', 0) for i in infos),
            'cache_misses': sum(i.get('cache_misses', 0) for i in infos),
            'load_s': sum(i.get('load_s', 0.0) for i in infos),
        }

    # ---- delivery -----------------------------------------------------

    def _collect(self, rep):
        """Driver/step callback: forward newly generated tokens."""
        with self._lock:
            self._collect_locked(rep)
            self._drain_pending_locked()

    def _collect_locked(self, rep):
        now = self._clock()
        for gw, er in list(rep.assigned.items()):
            new = er.tokens[len(gw.tokens):]
            if new:
                if not gw.tokens:
                    gw.first_token_t = now
                    ttft = now - gw.arrival_t
                    self._m_ttft.observe(ttft)
                    label = self._labeler.label(
                        gw.sampling.get('tenant'))
                    self._m_tenant_ttft.labels(label).observe(ttft)
                    self._m_qos_ttft.labels(
                        str(gw.sampling.get('priority') or 0)).observe(
                            ttft)
                    self._ttfts.append((now, ttft))
                    win = self._tenant_ttfts.get(label)
                    if win is None:
                        win = self._tenant_ttfts[label] = \
                            collections.deque(maxlen=1024)
                    win.append((now, ttft))
                gw.tokens.extend(new)
                if gw._stream_q is not None:
                    for t in new:
                        gw._stream_q.put(t)
                self._m_tokens.inc(len(new))
            if er.done and len(gw.tokens) >= len(er.tokens):
                del rep.assigned[gw]
                # a terminal engine-side outcome (e.g. 'preempted' when
                # max_preempts ran out) surfaces through the gateway's
                # canonical event
                self._complete_locked(
                    gw, getattr(er, 'outcome', None) or 'ok')

    def _complete_locked(self, gw, outcome='ok'):
        self._qos_finish_locked(gw)
        if gw._stream_q is not None:
            gw._stream_q.put(None)
        self._m_tenant_requests.labels(self._labeler.label(
            gw.sampling.get('tenant'))).inc()
        self._emit_wide_event_locked(gw, outcome)
        gw._finished.set()
        self._m_completed.inc()

    def _emit_wide_event_locked(self, gw, outcome):
        """THE canonical record for a gateway-managed request. Engine
        events are suppressed at replica.submit (emit_event=False), so
        exactly one event per submitted request exists no matter how
        many replicas it traversed; failovers/replicas carry the part
        only the gateway knows. Per-request fields (prefill chunks, KV
        page-seconds, spec counts) come from the FINAL engine request —
        a dead replica's partial window is gone with the replica.

        Instrumentation attrs are read with getattr defaults: the
        replica contract only requires tokens/done on engine requests,
        so a duck-typed engine without the serving internals still gets
        a (sparser) event rather than an AttributeError."""
        log = self.events
        if not log.enabled:
            return
        er = gw._eng_req
        span = getattr(er, '_span', None)
        trace_id = None if span is None else span.trace_id
        admit_t = getattr(er, '_admit_t', None)
        wait = None
        if admit_t is not None:
            # both clocks default to time.monotonic; with an injected
            # gateway clock this degrades to engine-side wait only
            wait = admit_t - (gw.arrival_t if self._clock
                              is time.monotonic
                              else getattr(er, '_arrival_t', admit_t))
        log.emit(
            request_id=gw.id,
            tenant=self._labeler.label(gw.sampling.get('tenant')),
            model=self._model_labeler.label(gw.sampling.get('model')),
            priority=gw.sampling.get('priority', 0),
            trace_id=trace_id,
            arrival_t=gw.arrival_t,
            admit_t=admit_t,
            first_token_t=gw.first_token_t,
            finish_t=self._clock(),
            queue_wait_s=wait,
            prefill_chunks=getattr(er, '_prefill_chunks', 0),
            prompt_tokens=len(gw.prompt),
            output_tokens=len(gw.tokens),
            prefix_hit_tokens=getattr(er, '_prefix_hit', 0),
            spec_proposed=getattr(er, '_spec_proposed', 0),
            spec_accepted=getattr(er, '_spec_accepted', 0),
            kv_page_seconds=getattr(er, 'kv_page_seconds', 0.0),
            failovers=gw.failovers,
            replicas=list(gw.replica_history),
            outcome=outcome)

    # ---- drive: sync mode ---------------------------------------------

    def step(self):
        """One synchronous pass (no driver threads): step every replica
        with work, collect, drain the parked queue. Returns the number
        of gateway requests still outstanding — the deterministic drive
        loop tests and benches use."""
        if self._started:
            raise RuntimeError('gateway is running driver threads; '
                               'sync step() would race them')
        with self._lock:
            reps = [r for r in self.pool if r.alive]
        for rep in reps:
            with self._lock:
                has_work = bool(rep.assigned) \
                    or bool(rep.engine.scheduler.pending)
            if not has_work:
                continue
            try:
                rep.step()
            except Exception as exc:   # noqa: BLE001 — transport
                with self._lock:
                    self._lost_locked(rep, exc)
                continue
            self._collect(rep)
        with self._lock:
            for rep in reps:
                if rep.state == DRAINING and not rep.assigned \
                        and not rep.engine.scheduler.pending:
                    rep.mark_stopped()
            self._refresh_gauges_locked()
            self._drain_pending_locked()
            return len(self._pending) + sum(
                len(r.assigned) for r in self.pool)

    def run(self):
        """Drive synchronously until every accepted request finished."""
        while self.step():
            pass

    # ---- drive: threaded mode -----------------------------------------

    def start(self):
        """Spawn one driver thread per live replica; submit() callers
        then just wait() on their handles."""
        with self._lock:
            if self._started:
                return self
            self._started = True
            for rep in self.pool:
                if rep.alive:
                    rep.start_driver(self._collect, self._on_lost)
        return self

    def _on_lost(self, rep, exc):
        with self._lock:
            self._lost_locked(rep, exc)

    def shutdown(self, timeout=10.0):
        """Graceful stop: drain every replica, join the drivers."""
        with self._lock:
            reps = list(self.pool)
            for rep in reps:
                if rep.state == READY:
                    rep.drain()
            self._refresh_gauges_locked()
        for rep in reps:
            rep.join(timeout)
        with self._lock:
            self._started = False
            self._refresh_gauges_locked()

    # ---- autoscaling --------------------------------------------------

    def autoscale_tick(self, now=None):
        """One policy evaluation + application. Call it on whatever
        cadence fits (a scrape loop, a timer thread, a test's fake
        clock); the policy's own hysteresis makes the cadence safe."""
        from .autoscaler import Decision
        if self.policy is None:
            return Decision(0, 'no autoscaler policy configured')
        now = self._clock() if now is None else now
        with self._lock:
            if self.burn_source is not None:
                burn = float(self.burn_source(now))
            else:
                burn = slo_burn_rate(self._ttfts, now,
                                     self.policy.slo_ttft_s,
                                     self.policy.window_s)
            self._m_burn.set(burn)
            ready = [r for r in self.pool if r.state == READY]
            occ = (sum(r.occupancy() for r in ready) / len(ready)
                   if ready else 0.0)
            depth = len(self._pending) + sum(
                int(r.queue_depth()) for r in ready)
            if getattr(self.policy, 'premium_tenants', None):
                # per-tenant burn: the policy scales up when a premium
                # tenant is burning even while the aggregate looks fine.
                # Passed as a kwarg only when configured, so policies
                # with the positional-only decide() keep working.
                tenant_burns = {
                    label: slo_burn_rate(win, now,
                                         self.policy.slo_ttft_s,
                                         self.policy.window_s)
                    for label, win in self._tenant_ttfts.items()}
                decision = self.policy.decide(now, burn, occ, depth,
                                              len(ready),
                                              tenant_burns=tenant_burns)
            else:
                decision = self.policy.decide(now, burn, occ, depth,
                                              len(ready))
            if decision.delta > 0:
                self._add_replica_locked()
                self._m_scale.labels('up').inc()
            elif decision.delta < 0 and ready:
                victim = min(ready, key=lambda r: (r.load(), r.index))
                victim.drain()
                self._m_scale.labels('down').inc()
                self._refresh_gauges_locked()
            return decision

    # ---- fleet telemetry ----------------------------------------------

    def attach_fleet(self, collector):
        """Register every replica's private registry as an in-proc
        scrape target on `collector` (a monitor.federation
        FleetCollector); replicas added later by the autoscaler
        self-register. The collector's merged view then carries every
        replica's serving_* families with an `instance` label — the
        cross-replica occupancy/queue picture one registry per replica
        was built to preserve (see replica.py)."""
        with self._lock:
            self._fleet = collector
            for rep in self.pool:
                self._fleet_register_locked(rep)
        return collector

    def _fleet_register_locked(self, rep):
        if self._fleet is None:
            return
        # idempotent: re-attach / re-add keeps the same instance name.
        # The transport picks HOW it is scraped: in-proc replicas hand
        # over their private registry, SocketReplicas hand over the
        # worker process's /metrics.json URL (stale-not-wrong on kill).
        self._fleet.add_target('gw-replica-%d' % rep.index,
                               **rep.scrape_kwargs())

    # ---- pool management ----------------------------------------------

    def adopt_replica(self, rep):
        """Add an externally built ReplicaTransport (e.g. a fabric
        SocketReplica proxying a worker process) to the pool. The
        gateway assigns the pool index; everything downstream —
        routing, failover, QoS, rollout, fleet registration — treats
        it exactly like a factory-built replica."""
        with self._lock:
            rep.index = len(self.pool)
            self.pool.append(rep)
            if self._started:
                rep.start_driver(self._collect, self._on_lost)
            self._fleet_register_locked(rep)
            self._refresh_gauges_locked()
            return rep

    def _add_replica_locked(self):
        if self._factory is None:
            raise RuntimeError('gateway has no engine_factory — scale '
                               'fabric pools by adopting new workers, '
                               'not by local replica construction')
        rep = InprocReplica(len(self.pool), self._factory())
        self.pool.append(rep)
        if self._started:
            rep.start_driver(self._collect, self._on_lost)
        self._fleet_register_locked(rep)
        self._refresh_gauges_locked()
        return rep

    def _refresh_gauges_locked(self):
        alive = 0
        for rep in self.pool:
            self._m_state.labels(str(rep.index)).set(
                STATE_CODES[rep.state])
            if rep.alive:
                alive += 1
        self._m_replicas.set(alive)

    @property
    def replicas_alive(self):
        with self._lock:
            return sum(1 for r in self.pool if r.alive)

    def report(self):
        """Scalar summary for benches (the engines' report() analogue)."""
        with self._lock:
            return {
                'replicas': len(self.pool),
                'replicas_alive': sum(1 for r in self.pool if r.alive),
                'requests': int(self._m_requests.value()),
                'completed': int(self._m_completed.value()),
                'tokens': int(self._m_tokens.value()),
                'failovers': int(self._m_failover.value()),
                'retries': int(self._m_retries.value()),
                'pending': len(self._pending),
                'rejected': self._n_rejected,
            }
