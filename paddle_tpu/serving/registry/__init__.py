"""Multi-model serving: model registry, weight paging, hot-swap.

Three pieces compose the "many models per process" layer (ROADMAP
item 10) out of parts the stack already has:

- ``registry.ModelRegistry`` — the catalog: (model, version) → loadable
  artifact (a CRC-manifest checkpoint from framework/io_save), each
  entry carrying a content-addressed fingerprint that keys the
  persistent compile cache, plus the per-model *serving pointer* the
  hot-swap flips atomically.
- ``hosting.ModelHost`` — the per-replica weight pager: loads models on
  demand under a byte budget, pins hot ones, LRU-evicts cold ones with
  PageAllocator-style refcounts so an in-flight request never loses its
  weights. A ModelHost quacks like an engine, so ``InprocReplica`` and
  the gateway drive it unchanged.
- gateway glue (serving/gateway): ``submit(model=...)``,
  ``ModelAffinityRouter``, and ``ServingGateway.rollout()`` — the
  zero-downtime version swap.
"""
from .hosting import ModelHost
from .registry import ModelRegistry, RegistryEntry, artifact_fingerprint

__all__ = ['ModelRegistry', 'RegistryEntry', 'artifact_fingerprint',
           'ModelHost']
