"""Model registry: (model, version) → loadable artifact + serving pointer.

The registry is a catalog, not a loader: it records where each version's
checkpoint lives, fingerprints the artifact by content, and owns the
per-model *serving pointer* — the version new submissions resolve to.
Weight residency is ModelHost's job (hosting.py); the atomic pointer
flip is what makes `ServingGateway.rollout()` zero-downtime, because
in-flight requests captured their entry at submission and keep it.

Artifacts are io_save checkpoints (CRC-manifest sidecar), so the
fingerprint is content-addressed for free: the manifest already commits
to the payload's size + CRC32, and hashing the manifest bytes gives a
stable identity without re-reading a multi-GB payload. Files without a
manifest (foreign artifacts) hash their own bytes instead; directories
hash the sorted per-file fingerprints. Two registrations of the same
bytes — on any host, any path — get the same fingerprint, which is what
lets the fingerprint key the persistent compile cache: same weights +
same config → same traced program → warm bring-up is a cache hit.
"""
import hashlib
import os
import threading

from ...framework import io_save

__all__ = ['ModelRegistry', 'RegistryEntry', 'artifact_fingerprint']


def _file_fingerprint(path):
    h = hashlib.sha256()
    mf = io_save.manifest_path(path)
    src = mf if os.path.exists(mf) else path
    with open(src, 'rb') as f:
        for chunk in iter(lambda: f.read(1 << 20), b''):
            h.update(chunk)
    return h.hexdigest()


def artifact_fingerprint(path):
    """Content-addressed identity of an artifact file or directory
    (16-hex). For io_save checkpoints this hashes the CRC manifest —
    cheap and exactly as binding as hashing the payload."""
    if os.path.isdir(path):
        h = hashlib.sha256()
        for root, dirs, files in sorted(os.walk(path)):
            dirs.sort()
            for name in sorted(files):
                if name.endswith('.manifest'):
                    continue       # folded into its data file's print
                rel = os.path.relpath(os.path.join(root, name), path)
                h.update(rel.encode('utf-8'))
                h.update(_file_fingerprint(
                    os.path.join(root, name)).encode())
        return h.hexdigest()[:16]
    return _file_fingerprint(path)[:16]


def _artifact_nbytes(path):
    if os.path.isdir(path):
        total = 0
        for root, _, files in os.walk(path):
            for name in files:
                total += os.path.getsize(os.path.join(root, name))
        return total
    return os.path.getsize(path)


class RegistryEntry:
    """One registered (model, version): immutable after registration."""

    __slots__ = ('model', 'version', 'path', 'fingerprint', 'nbytes',
                 'meta')

    def __init__(self, model, version, path, fingerprint, nbytes,
                 meta=None):
        self.model = model
        self.version = version
        self.path = path
        self.fingerprint = fingerprint
        self.nbytes = int(nbytes)
        self.meta = dict(meta or {})

    @property
    def key(self):
        return (self.model, self.version)

    def __repr__(self):
        return ('RegistryEntry(%r, %r, fingerprint=%s, nbytes=%d)'
                % (self.model, self.version, self.fingerprint,
                   self.nbytes))


class ModelRegistry:
    """Thread-safe catalog of model versions + per-model serving pointer.

    `root` (optional) is where publish() writes checkpoints; register()
    accepts artifacts living anywhere. The first registered version of a
    model becomes its serving version; set_serving() flips the pointer
    atomically (one attribute write under the lock — readers via
    resolve() see either the old or the new version, never neither).
    """

    def __init__(self, root=None):
        self.root = root
        self._entries = {}        # (model, version) -> RegistryEntry
        self._serving = {}        # model -> version
        self._lock = threading.Lock()

    # ---- registration -------------------------------------------------

    def register(self, model, version, path, meta=None, verify=True):
        """Catalog an existing artifact; returns its RegistryEntry.
        `verify=True` checks a file artifact against its CRC manifest
        first — a torn checkpoint must fail at registration, not at the
        first load on a serving replica."""
        if not os.path.exists(path):
            raise FileNotFoundError('no artifact at %s' % path)
        if verify and os.path.isfile(path) and \
                not io_save.verify_checkpoint(path):
            raise io_save.CheckpointCorruptError(
                '%s does not verify against its manifest — refusing to '
                'register a torn artifact' % path)
        entry = RegistryEntry(model, version, path,
                              artifact_fingerprint(path),
                              _artifact_nbytes(path), meta=meta)
        with self._lock:
            self._entries[(model, version)] = entry
            self._serving.setdefault(model, version)
        return entry

    def publish(self, model, version, obj, meta=None):
        """Write `obj` through the snapshot transport (io_save: atomic
        rename + CRC manifest) under root/ and register it — the door
        rollout() uses to ship a new version."""
        if self.root is None:
            raise ValueError('publish() needs a registry root directory')
        path = os.path.join(self.root, str(model),
                            '%s.pdparams' % version)
        io_save.save(obj, path)
        return self.register(model, version, path, meta=meta)

    # ---- lookup -------------------------------------------------------

    def entry(self, model, version):
        try:
            return self._entries[(model, version)]
        except KeyError:
            raise KeyError('unknown model version (%r, %r); registered: '
                           '%s' % (model, version,
                                   sorted(self._entries))) from None

    def resolve(self, model, version=None):
        """The entry a new submission should use: the explicit version,
        or the model's current serving pointer."""
        if version is None:
            with self._lock:
                version = self._serving.get(model)
            if version is None:
                raise KeyError('model %r has no registered versions'
                               % (model,))
        return self.entry(model, version)

    def load(self, model, version=None, **configs):
        """io_save.load of the resolved artifact (CRC-checked)."""
        return io_save.load(self.resolve(model, version).path, **configs)

    # ---- serving pointer ----------------------------------------------

    def serving_version(self, model):
        with self._lock:
            return self._serving.get(model)

    def set_serving(self, model, version):
        """Atomically repoint `model` at `version`; returns the previous
        version. The version must already be registered — the pointer
        can never dangle."""
        if (model, version) not in self._entries:
            raise KeyError('cannot serve unregistered version (%r, %r)'
                           % (model, version))
        with self._lock:
            prev = self._serving.get(model)
            self._serving[model] = version
            return prev

    # ---- enumeration --------------------------------------------------

    def models(self):
        with self._lock:
            return sorted(self._serving)

    def versions(self, model):
        return sorted(v for (m, v) in self._entries if m == model)

    def __contains__(self, key):
        return key in self._entries

    def __len__(self):
        return len(self._entries)
