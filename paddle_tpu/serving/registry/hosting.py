"""ModelHost: per-replica weight paging over a ModelRegistry.

One host runs MANY models inside one replica process, paging weights
in and out under a byte budget the way the KV pool pages sequences:

- a resident model is REFCOUNTED like a `kv_cache.PageAllocator` page —
  every queued or in-flight request holds one reference from admission
  to completion, so eviction of a busy model *defers* until its last
  reference drops (never yanks weights out from under a decode), and a
  double-release raises instead of corrupting the count;
- a cold `submit(model=...)` PARKS the request and queues an async
  load: the load runs on the replica's driver thread inside step(),
  never on the gateway's submit/drain path;
- eviction is LRU over unpinned, idle models; `pin()` exempts hot
  models; the byte budget is enforced at load time (evict until it
  fits, else the load waits for references to drop).

The host duck-types as an engine — `add_request` / `step` / `shutdown`,
a scheduler shim with `pending`, settable `metrics`, `rebind_perf` — so
`InprocReplica` and `ServingGateway` drive a multi-model replica with
zero changes. Residency and churn export as the `registry_*` metric
families (monitor/telemetry.py REGISTRY_FAMILIES).
"""
import queue as _queue
import threading
import time
from collections import deque

from ...framework import compile_cache
from ...monitor.telemetry import record_registry_schema
from ..metrics import ServingMetrics
from ..scheduler import Request

__all__ = ['ModelHost', 'HostedModel']


class HostedModel:
    """One resident (model, version): the engine holding its weights
    plus the paging bookkeeping (refcount, pin, LRU stamp)."""

    __slots__ = ('entry', 'engine', 'refs', 'pinned', 'evict_pending',
                 'last_used')

    def __init__(self, entry, engine, pinned=False):
        self.entry = entry
        self.engine = engine
        self.refs = 0
        self.pinned = bool(pinned)
        self.evict_pending = False
        self.last_used = 0.0

    @property
    def key(self):
        return self.entry.key

    def __repr__(self):
        return ('HostedModel(%r, %r, refs=%d, pinned=%s, evict_pending=%s)'
                % (self.entry.model, self.entry.version, self.refs,
                   self.pinned, self.evict_pending))


class _HostScheduler:
    """Engine-shaped scheduler view over the whole host: parked
    requests plus every resident engine's own queue/residency — what
    the replica driver loop and queue-depth gauges read."""

    def __init__(self, host):
        self._host = host

    @property
    def pending(self):
        h = self._host
        with h._lock:
            return len(h._parked) + sum(
                hm.engine.scheduler.pending
                for hm in h._resident.values())

    @property
    def queue(self):
        h = self._host
        with h._lock:
            out = [req for _, req in h._parked]
            for hm in h._resident.values():
                out.extend(hm.engine.scheduler.queue)
            return tuple(out)


class ModelHost:
    """Engine-duck-typed multi-model replica over a ModelRegistry.

    `engine_factory(entry)` builds a ready engine for one registry
    entry (loading the artifact's weights is its job — the host only
    decides WHEN and accounts the bytes). `byte_budget` caps resident
    artifact bytes (None: unlimited); `max_len` enables the engines'
    front-door capacity guard before any engine exists.
    """

    # engine-contract shim: replica._untraced reads these. Trace-lock
    # serialization happens per ENGINE inside _step_engine (a merged
    # nonzero view here would deadlock the replica's own lock take).
    spec_k = 0
    trace_counts = {}

    def __init__(self, registry, engine_factory, byte_budget=None,
                 max_len=None, default_model=None, clock=None):
        self.registry = registry
        self._factory = engine_factory
        self.byte_budget = None if byte_budget is None else int(byte_budget)
        self.max_len = None if max_len is None else int(max_len)
        self.default_model = default_model
        self._clock = clock or time.monotonic
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._resident = {}       # (model, version) -> HostedModel
        self._parked = deque()    # (key, Request) awaiting a load
        self._want = deque()      # keys queued for async load
        self._want_set = set()
        self._loading = set()     # keys being built outside the lock
        self._inflight = {}       # req.id -> (key, Request): refs held
        self._use_seq = 0
        self._closed = False
        self._perf_registry = None
        self.scheduler = _HostScheduler(self)
        self._metrics = None
        self.metrics = ServingMetrics(clock=clock)

    # ---- engine-contract surface --------------------------------------

    @property
    def metrics(self):
        return self._metrics

    @metrics.setter
    def metrics(self, m):
        """The replica rebind point: moving the host onto a private
        registry re-registers the registry_* families there and carries
        every resident engine along (the InprocReplica pattern)."""
        self._metrics = m
        fams = record_registry_schema(m.registry)
        self._m_resident_bytes = fams['registry_resident_bytes']
        self._m_models = fams['registry_models_resident']
        self._m_loads = fams['registry_loads_total']
        self._m_evictions = fams['registry_evictions_total']
        self._m_deferred = fams['registry_evictions_deferred_total']
        self._m_load_s = fams['registry_load_seconds']
        self._m_warm_hits = fams['registry_warm_load_cache_hits_total']
        self._m_warm_misses = fams['registry_warm_load_cache_misses_total']
        self._m_rollouts = fams['registry_rollouts_total']
        with self._lock:
            for hm in self._resident.values():
                hm.engine.metrics = ServingMetrics(registry=m.registry)

    def rebind_perf(self, registry):
        with self._lock:
            self._perf_registry = registry
            for hm in self._resident.values():
                hm.engine.rebind_perf(registry)
        return self

    @property
    def num_slots(self):
        with self._lock:
            return sum(hm.engine.num_slots
                       for hm in self._resident.values())

    def shutdown(self):
        with self._lock:
            self._closed = True
            for hm in self._resident.values():
                hm.engine.shutdown()

    # ---- front door ---------------------------------------------------

    def add_request(self, prompt, max_new_tokens=32, temperature=1.0,
                    top_k=0, do_sample=False, seed=0, stream=False,
                    tenant=None, priority=0, model=None, version=None,
                    emit_event=True):
        """Queue one request against `model` (the host's default_model,
        or the sole registered model, when omitted). `version=None`
        resolves the registry's serving pointer AT SUBMISSION — the
        hot-swap contract: requests accepted before a rollout flip keep
        the old version, requests after it get the new one.

        A miss parks the request and queues an async load for step();
        it never loads inline, so the caller (the gateway's routing
        walk) returns immediately."""
        if model is None:
            model = self.default_model
        if model is None:
            models = self.registry.models()
            if len(models) != 1:
                raise ValueError(
                    'multi-model host needs model=... (registered: %s)'
                    % models)
            model = models[0]
        entry = self.registry.resolve(model, version)
        req = Request(prompt, max_new_tokens=max_new_tokens,
                      temperature=temperature, top_k=top_k,
                      do_sample=do_sample, seed=seed, tenant=tenant,
                      priority=priority, model=model)
        req._emit_event = bool(emit_event)
        if stream:
            req._stream_q = _queue.Queue()
        # the engines' shared front-door guard, verbatim, so impossible
        # requests fail here even before their model's engine exists
        worst = len(req.prompt) + req.max_new_tokens - 1
        if self.max_len and len(req.prompt) and worst > self.max_len:
            raise ValueError(
                'request cannot ever be admitted: prompt of %d tokens + '
                'max_new_tokens=%d needs %d cache rows but max_len=%d'
                % (len(req.prompt), req.max_new_tokens, worst,
                   self.max_len))
        with self._lock:
            if self._closed:
                raise RuntimeError(
                    'engine is shut down — it no longer admits requests')
            req._arrival_t = self.metrics.now()
            hm = self._resident.get(entry.key)
            if hm is not None and not hm.evict_pending:
                self._enqueue_locked(hm, req)
            else:
                self._parked.append((entry.key, req))
                if entry.key not in self._want_set:
                    self._want.append(entry.key)
                    self._want_set.add(entry.key)
        return req

    def generate(self, prompts, **sampling):
        reqs = [self.add_request(p, **sampling) for p in prompts]
        self.run()
        return [r.tokens for r in reqs]

    def run(self):
        while self.step():
            pass

    # ---- residency ----------------------------------------------------

    def hosts_model(self, model, version=None):
        """Is (model, version) resident and servable? version=None
        matches any — the router's affinity question."""
        with self._lock:
            for hm in self._resident.values():
                if hm.evict_pending:
                    continue
                if hm.entry.model == model and \
                        (version is None or hm.entry.version == version):
                    return True
            return False

    def resident_models(self):
        with self._lock:
            return sorted(self._resident)

    @property
    def resident_bytes(self):
        with self._lock:
            return sum(hm.entry.nbytes for hm in self._resident.values())

    def refcount(self, model, version):
        with self._lock:
            hm = self._resident.get((model, version))
            return 0 if hm is None else hm.refs

    def load(self, model, version=None, pin=False, warm=False):
        """Synchronously bring (model, version) resident; returns a
        load-info dict. `warm=True` runs a tiny generate under the
        process trace lock and reports the persistent-compile-cache
        delta — the rollout bring-up proof. Raises RuntimeError when
        the byte budget cannot be met (nothing evictable)."""
        entry = self.registry.resolve(model, version)
        with self._lock:
            while entry.key in self._loading:
                self._cv.wait(0.01)     # driver thread building it
            hm = self._resident.get(entry.key)
            if hm is not None:
                hm.evict_pending = False
                if pin:
                    hm.pinned = True
                return {'loaded': False, 'model': entry.model,
                        'version': entry.version,
                        'fingerprint': entry.fingerprint,
                        'cache_hits': 0, 'cache_misses': 0,
                        'load_s': 0.0}
            if not self._make_room_locked(entry.nbytes):
                raise RuntimeError(
                    'byte budget %d cannot admit %r (%d bytes): %d bytes '
                    'resident and nothing evictable (all pinned or '
                    'referenced)' % (self.byte_budget, entry.key,
                                     entry.nbytes, self._bytes_locked()))
            self._loading.add(entry.key)
        try:
            hm, info = self._build(entry, warm=warm, pin=pin)
        finally:
            with self._lock:
                self._loading.discard(entry.key)
                self._cv.notify_all()
        with self._lock:
            self._install_locked(hm)
        return info

    def pin(self, model, version=None):
        entry = self.registry.resolve(model, version)
        with self._lock:
            hm = self._resident.get(entry.key)
            if hm is None:
                raise KeyError('%r is not resident' % (entry.key,))
            hm.pinned = True

    def unpin(self, model, version=None):
        entry = self.registry.resolve(model, version)
        with self._lock:
            hm = self._resident.get(entry.key)
            if hm is not None:
                hm.pinned = False

    def evict(self, model, version):
        """Page (model, version) out. With live references the eviction
        DEFERS — flagged now, completed when the last reference drops —
        so an in-flight request never loses its weights. Returns True
        when evicted immediately, False when deferred."""
        with self._lock:
            hm = self._resident.get((model, version))
            if hm is None:
                raise KeyError('(%r, %r) is not resident'
                               % (model, version))
            if hm.pinned:
                raise ValueError('(%r, %r) is pinned — unpin before '
                                 'evicting' % (model, version))
            return self._evict_or_defer_locked(hm)

    def acquire(self, model, version):
        """Take one reference on a resident model (what admission does
        internally) — the test door for the refcount contract."""
        with self._lock:
            hm = self._resident.get((model, version))
            if hm is None:
                raise KeyError('(%r, %r) is not resident'
                               % (model, version))
            hm.refs += 1
            return hm.refs

    def release(self, model, version):
        """Drop one reference; completes a deferred eviction at zero.
        Releasing a model that holds no references raises — a silent
        double-release here would let a deferred eviction fire while a
        request still decodes on the weights, the exact corruption the
        PageAllocator's double-free rule exists to prevent."""
        with self._lock:
            hm = self._resident.get((model, version))
            if hm is None or hm.refs <= 0:
                raise ValueError(
                    'model (%r, %r) holds no references (double-release, '
                    'or never acquired)' % (model, version))
            self._release_locked(hm)
            return hm.refs

    # ---- hot-swap (gateway.rollout drives these) ----------------------

    def prepare_rollout(self, model, version):
        """Warm-load and pin the incoming version; returns the load
        info (compile-cache delta included)."""
        return self.load(model, version, pin=True, warm=True)

    def finish_rollout(self, model, old_version):
        """Retire the outgoing version: unpin + evict (deferred while
        its in-flight requests finish — drain, never kill)."""
        self._m_rollouts.labels(self.metrics.model_label(model)).inc()
        if old_version is None:
            return True
        with self._lock:
            hm = self._resident.get((model, old_version))
            if hm is None:
                return True
            hm.pinned = False
            return self._evict_or_defer_locked(hm)

    # ---- drive --------------------------------------------------------

    def step(self):
        """One host iteration: service queued loads, admit parked
        requests whose model came resident, step every engine with
        work, release references for finished requests (completing any
        deferred evictions), refresh gauges. Returns requests still
        pending anywhere in the host."""
        self._process_loads()
        with self._lock:
            keep = deque()
            while self._parked:
                key, req = self._parked.popleft()
                hm = self._resident.get(key)
                if hm is not None and not hm.evict_pending:
                    self._enqueue_locked(hm, req)
                else:
                    keep.append((key, req))
            self._parked = keep
            engines = [hm.engine for hm in self._resident.values()
                       if hm.engine.scheduler.pending]
        for eng in engines:
            self._step_engine(eng)
        with self._lock:
            done = [rid for rid, (_, req) in self._inflight.items()
                    if req.done]
            for rid in done:
                key, _ = self._inflight.pop(rid)
                hm = self._resident.get(key)
                if hm is not None:
                    self._release_locked(hm)
            self._refresh_gauges_locked()
            pending = len(self._parked) + sum(
                hm.engine.scheduler.pending
                for hm in self._resident.values())
            if pending and not engines and not self._inflight \
                    and not self._want_progress_possible_locked():
                raise RuntimeError(
                    'weight paging deadlock: %d requests parked but the '
                    'byte budget (%s) cannot admit their models and no '
                    'in-flight work can free references'
                    % (len(self._parked), self.byte_budget))
            return pending

    def program_trace_counts(self):
        """{(model, version): engine.trace_counts} — the per-engine
        no-retrace ledger (the host-level `trace_counts` shim is empty
        by design; see the class comment)."""
        with self._lock:
            return {key: dict(hm.engine.trace_counts)
                    for key, hm in self._resident.items()}

    # ---- internals (lock held unless noted) ---------------------------

    def _enqueue_locked(self, hm, req):
        hm.refs += 1
        self._inflight[req.id] = (hm.key, req)
        self._use_seq += 1
        hm.last_used = self._use_seq
        hm.engine.enqueue(req)

    def _release_locked(self, hm):
        hm.refs -= 1
        if hm.refs == 0 and hm.evict_pending:
            self._evict_locked(hm)

    def _evict_or_defer_locked(self, hm):
        if hm.refs > 0:
            if not hm.evict_pending:
                hm.evict_pending = True
                self._m_deferred.inc()
            return False
        self._evict_locked(hm)
        return True

    def _evict_locked(self, hm):
        del self._resident[hm.key]
        hm.engine.shutdown()
        self._m_evictions.labels(
            self.metrics.model_label(hm.entry.model)).inc()
        self._refresh_residency_locked()

    def _bytes_locked(self):
        return sum(hm.entry.nbytes for hm in self._resident.values())

    def _make_room_locked(self, need):
        """Evict LRU idle unpinned models until `need` more bytes fit
        the budget; False when they cannot."""
        if self.byte_budget is None:
            return True
        while self._bytes_locked() + need > self.byte_budget:
            victims = [hm for hm in self._resident.values()
                       if not hm.pinned and hm.refs == 0]
            if not victims:
                return False
            self._evict_locked(min(victims, key=lambda h: h.last_used))
        return True

    def _want_progress_possible_locked(self):
        """Could any queued load ever be admitted as things stand?"""
        for key in self._want:
            if key in self._resident:
                return True
            entry = self.registry.entry(*key)
            if self.byte_budget is None or \
                    self._bytes_locked() + entry.nbytes <= self.byte_budget:
                return True
            if any(not hm.pinned and hm.refs == 0
                   for hm in self._resident.values()):
                return True
        return not self._want

    def _process_loads(self):
        """Drain the async load queue (driver thread). The engine build
        runs OUTSIDE the host lock so submissions keep flowing during a
        multi-second weight load; budget-blocked keys stay queued and
        retry next step, after completions have dropped references."""
        while True:
            with self._lock:
                if not self._want:
                    return
                key = self._want[0]
                hm = self._resident.get(key)
                if hm is not None:
                    # an eviction raced the re-request: cancel it
                    hm.evict_pending = False
                    self._want.popleft()
                    self._want_set.discard(key)
                    continue
                if key in self._loading:
                    self._want.popleft()
                    self._want_set.discard(key)
                    continue
                entry = self.registry.entry(*key)
                if not self._make_room_locked(entry.nbytes):
                    return          # blocked: retry next step
                self._want.popleft()
                self._want_set.discard(key)
                self._loading.add(key)
            try:
                hm, _ = self._build(entry)
            finally:
                with self._lock:
                    self._loading.discard(key)
                    self._cv.notify_all()
            with self._lock:
                self._install_locked(hm)

    def _build(self, entry, warm=False, pin=False):
        """Construct the engine for `entry` (no host lock held) and
        account the load. Warmup runs under the process-wide trace lock
        (gateway/replica.py): functional_call tracing through a shared
        model object is not re-entrant."""
        t0 = self._clock()
        before = compile_cache.stats()
        engine = self._factory(entry)
        engine.metrics = ServingMetrics(
            registry=self._metrics.registry)
        if self._perf_registry is not None:
            engine.rebind_perf(self._perf_registry)
        if warm:
            from ..gateway.replica import _TRACE_LOCK
            with _TRACE_LOCK:
                engine.generate([[0, 0]], max_new_tokens=2,
                                emit_event=False)
        after = compile_cache.stats()
        load_s = self._clock() - t0
        hits = after['hits'] - before['hits']
        misses = after['misses'] - before['misses']
        label = self.metrics.model_label(entry.model)
        self._m_loads.labels(label).inc()
        self._m_load_s.observe(load_s)
        if warm:
            if hits:
                self._m_warm_hits.inc(hits)
            if misses:
                self._m_warm_misses.inc(misses)
        hm = HostedModel(entry, engine, pinned=pin)
        info = {'loaded': True, 'model': entry.model,
                'version': entry.version,
                'fingerprint': entry.fingerprint,
                'cache_hits': hits, 'cache_misses': misses,
                'load_s': load_s}
        return hm, info

    def _install_locked(self, hm):
        self._use_seq += 1
        hm.last_used = self._use_seq
        self._resident[hm.key] = hm
        self._refresh_residency_locked()

    def _step_engine(self, engine):
        """Step one engine, trace-lock-serialized while it still has
        untraced programs (the InprocReplica rule, applied per engine
        since one host drives many)."""
        skip = () if getattr(engine, 'spec_k', 0) else ('verify',)
        if any(v == 0 for k, v in engine.trace_counts.items()
               if k not in skip):
            from ..gateway.replica import _TRACE_LOCK
            with _TRACE_LOCK:
                return engine.step()
        return engine.step()

    def _refresh_residency_locked(self):
        self._m_resident_bytes.set(self._bytes_locked())
        self._m_models.set(len(self._resident))

    def _refresh_gauges_locked(self):
        hms = list(self._resident.values())
        queued = len(self._parked) + sum(
            len(hm.engine.scheduler.queue) for hm in hms)
        self.metrics.on_queue_depth(queued)
        slots = sum(hm.engine.num_slots for hm in hms)
        if slots:
            # duck-typed engines (test stubs) may lack an allocator —
            # occupancy then reads zero rather than crashing the driver
            self.metrics.on_step(
                sum(getattr(hm.engine, 'allocator', None).in_use
                    if getattr(hm.engine, 'allocator', None) is not None
                    else 0 for hm in hms), slots)
        self._refresh_residency_locked()

    def __repr__(self):
        with self._lock:
            return ('ModelHost(resident=%d, bytes=%d/%s, parked=%d, '
                    'inflight=%d)'
                    % (len(self._resident), self._bytes_locked(),
                       self.byte_budget, len(self._parked),
                       len(self._inflight)))
