"""Paged continuous batching: block-granular KV + prefix reuse + spec
decode, in exactly THREE compiled programs.

The slot engine (engine.py) reserves `max_len` KV rows per slot, so
memory density scales with the WORST-CASE sequence and identical system
prompts re-prefill on every request. This engine keeps one physical pool
of fixed-size pages per layer and maps sequences onto it through host
numpy block tables (vLLM's PagedAttention layout):

  - a sequence holds only the pages its actual length needs (reserved
    up front at admission — residents can never fail mid-flight);
  - requests sharing a prompt prefix map their leading block-table
    entries to the SAME already-filled pages (PrefixCache, chain-hashed
    full blocks) and skip that part of prefill entirely;
  - optionally, an n-gram proposer drafts K tokens per decode step and
    ONE batched verify forward accepts the longest prefix matching the
    model's own greedy picks — up to K+1 tokens per dispatch, output
    token-identical to sequential generate() by construction (every
    accepted token equals the greedy pick the model would have made).

Program set (the PR-3 two-program invariant, generalized but still
bounded — trace-count gauges assert it):

  prefill chunk  — [1, C] prompt tokens through one sequence's block-
                   table row;
  decode burst   — K cached steps for ALL sequences (spec off);
  verify pass    — [S, K+1] draft tokens for ALL sequences (spec on).

Only the page pools live on device; block tables and lengths are host
numpy handed to jit per dispatch (values change freely, shapes never).
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..framework import functional as _fm
from ..framework.core import Tensor
from ..text.models.gpt import GPTPagedCache
from .engine import _EngineBase, _kv_row_bytes, _pick_token
from .kv_cache import (PageAllocator, PrefixCache, SlotAllocator,
                       build_paged_pools)
from .scheduler import PagedScheduler

__all__ = ['PagedContinuousBatchingEngine', 'NGramProposer']


class NGramProposer:
    """Prompt-lookup drafting: find the most recent earlier occurrence
    of the sequence's trailing n-gram and propose whatever followed it.

    Free (no draft model, no device work) and surprisingly effective on
    serving traffic, where outputs quote their prompts — exactly the
    regime prefix sharing also targets. Wrong drafts cost only their
    share of one verify pass; the accept rule keeps output exact.
    """

    def __init__(self, n=2):
        if n < 1:
            raise ValueError('n-gram size must be >= 1')
        self.n = int(n)

    def propose(self, history, k):
        """k draft ids continuing `history` (prompt + generated so far).
        Falls back to repeating the last token when the n-gram has no
        earlier occurrence — a cheap guess beats proposing nothing,
        since the verify pass runs at [S, K+1] either way."""
        n = min(self.n, len(history) - 1)
        draft = []
        if n > 0:
            tail = history[-n:]
            for i in range(len(history) - n - 1, -1, -1):
                if history[i:i + n] == tail:
                    draft = list(history[i + n:i + n + k])
                    break
        last = history[-1]
        while len(draft) < k:
            draft.append(draft[-1] if draft else last)
        return draft[:k]


class PagedContinuousBatchingEngine(_EngineBase):
    """Page-granular continuous batching over a GPTForCausalLM.

    Same front door and scheduling policy as ContinuousBatchingEngine;
    differs in the KV layout (page pool + block tables), prefix-cache
    admission, and the optional speculative decode path. `spec_k > 0`
    replaces the decode burst with draft-and-verify and is greedy-only:
    sampled requests are rejected at add_request, because the accept
    rule compares drafts against argmax picks.
    """

    _programs = ('prefill', 'decode', 'verify')

    def __init__(self, model, num_seqs=8, max_len=None, page_size=16,
                 num_pages=None, prefill_chunk=16, decode_block=4,
                 spec_k=0, ngram=2, prefix_cache=True, preempt=False,
                 max_preempts=None, donate=None):
        super().__init__(model, num_seqs, max_len)
        if self.max_len > model.config.max_position_embeddings:
            raise ValueError(
                'max_len %d exceeds max_position_embeddings %d'
                % (self.max_len, model.config.max_position_embeddings))
        self.page_size = int(page_size)
        self.num_blocks = -(-self.max_len // self.page_size)
        if num_pages is None:
            # parity default: enough for every sequence at max_len plus
            # scratch — same footprint as the slot engine. Real
            # deployments size the pool to ACTUAL length distributions
            # (the density win); the scheduler's up-front reservation
            # keeps a small pool safe, just slower to admit.
            num_pages = self.num_slots * self.num_blocks + 1
        self.num_pages = int(num_pages)
        self.decode_block = int(decode_block)
        if self.decode_block < 1:
            raise ValueError('decode_block must be >= 1')
        self.spec_k = int(spec_k)
        if self.spec_k < 0:
            raise ValueError('spec_k must be >= 0')
        self._proposer = NGramProposer(ngram) if self.spec_k else None
        self._pools = build_paged_pools(model, self.num_pages,
                                        self.page_size)
        self.pages = PageAllocator(self.num_pages)
        self.prefix = (PrefixCache(self.page_size, self.pages)
                       if prefix_cache else None)
        self.allocator = SlotAllocator(self.num_slots)
        self.scheduler = PagedScheduler(self.allocator, self.pages,
                                        self.max_len, prefill_chunk,
                                        self.page_size, self.prefix)
        # priority preemption: a page-blocked high-priority arrival may
        # evict strictly-lower-priority residents (scheduler policy);
        # this engine's hook clears the freed lane and accounts the
        # eviction. max_preempts bounds how often one request may lose
        # its pages before it is finished terminally (outcome
        # 'preempted') instead of requeued.
        self.scheduler.preempt_enabled = bool(preempt)
        self.scheduler.max_preempts = (None if max_preempts is None
                                       else int(max_preempts))
        self.scheduler.on_preempt = self._on_preempt
        # billing unit for kv_byte_seconds: one physical page
        self._kv_page_bytes = _kv_row_bytes(model) * self.page_size
        # per-row KV length (rows written), the block-table companion to
        # the base class's host control arrays. Mid-prefill rows track
        # consumed so in-program garbage writes from frozen lanes land
        # on rows the next real pass overwrites anyway.
        self._lens = np.zeros((self.num_slots,), np.int32)
        self._prefix_seen = [0, 0]    # hit/miss totals already reported
        if donate is None:
            donate = jax.default_backend() in ('tpu', 'gpu')
        dn = (2,) if donate else ()
        self._prefill_jit = jax.jit(self._prefill_fn, donate_argnums=dn)
        self._decode_jit = jax.jit(self._decode_fn, donate_argnums=dn)
        self._verify_jit = jax.jit(self._verify_fn, donate_argnums=dn)
        self._verify_args = None

    @property
    def num_seqs(self):
        return self.num_slots

    def _warm_programs(self):
        # the verify program only ever traces when speculation is on;
        # without spec_k the watchdog must not wait for it forever
        if self.spec_k:
            return self._programs
        return ('prefill', 'decode')

    def _perf_target(self):
        # under speculation the verify forward is the steady-state
        # spender (the plain decode program never dispatches)
        if self.spec_k and self._verify_args is not None:
            return self._verify_jit, self._verify_args
        return self._decode_jit, self._decode_args

    def _validate(self, req):
        if self.spec_k and req.do_sample:
            raise ValueError(
                'speculative decoding (spec_k=%d) is greedy-only: the '
                'accept rule compares drafts against argmax picks. '
                'Submit with do_sample=False or run spec_k=0.'
                % self.spec_k)

    def _bind(self, slot, req):
        # a prefix hit means rows [0, hit) are already valid shared
        # pages: the row's length starts there, not at zero
        self._lens[slot] = req._consumed
        if req._prefix_hit and req._span is not None:
            req._span.add_event('prefix_cache_hit',
                                tokens=req._prefix_hit)

    def _on_step_metrics(self):
        self.metrics.on_pages_in_use(self.pages.in_use)
        if self.prefix is not None:
            h, m = self.prefix.hits, self.prefix.misses
            self.metrics.on_prefix_lookup(h - self._prefix_seen[0],
                                          m - self._prefix_seen[1])
            self._prefix_seen = [h, m]

    def _retire(self, req, outcome='ok'):
        slot = req.slot
        super()._retire(req, outcome)
        self._lens[slot] = 0

    def _on_preempt(self, slot, req, dropped):
        """PagedScheduler eviction hook (lock held): the victim's pages
        and slot are already released — freeze the lane so the next
        decode burst cannot advance it (the freed pages may belong to
        someone else by then) and close the victim's phase span. A
        `dropped` victim burned its preemption budget: retire it here
        with outcome='preempted' (the scheduler already closed its
        billing window and sets the finished flag after this returns)."""
        self._active[slot] = False
        self._lens[slot] = 0
        self._requests.pop(slot, None)
        self.metrics.on_preempted(req._tenant_label)
        if req._phase is not None:
            req._phase.finish()
            req._phase = None
        if req._span is not None:
            req._span.add_event('preempted', count=req._preempts,
                                dropped=dropped)
        if not dropped:
            return
        req.outcome = 'preempted'
        req._finish_t = self.metrics.now()
        self.metrics.on_retired(req.id)
        self.metrics.on_tenant_retired(
            req._tenant_label, req.kv_page_seconds * self._kv_page_bytes)
        if req._span is not None:
            req._span.set_tag('tokens', len(req.tokens))
            req._span.add_event('retired')
            req._span.finish()
        self._emit_wide_event(req, 'preempted')

    # ---- the three compiled programs ----------------------------------

    def _caches(self, pools, bt, lens):
        return [GPTPagedCache(Tensor(k), Tensor(v), bt, lens)
                for k, v in pools]

    @staticmethod
    def _unpack(caches):
        return [(c.k._data, c.v._data) for c in caches]

    def _prefill_fn(self, params, bufs, pools, bt1, len1, ids, valid,
                    key, temp, topk, sample):
        """One [1, C] prompt chunk through block-table row `bt1` at
        offset len1. Same contract as the slot prefill: only `valid`
        tokens are real, padded-tail writes are garbage the next pass
        overwrites, and the returned pick matters on the final chunk."""
        self.trace_counts['prefill'] += 1
        caches = self._caches(pools, bt1, len1)
        (lg, new_cs), _ = _fm.functional_call(
            self._model, params, bufs, args=(Tensor(ids),),
            kwargs={'caches': caches}, training=False)
        last = jax.lax.dynamic_index_in_dim(lg[0], valid - 1, axis=0,
                                            keepdims=False)
        key2, sub = jax.random.split(key)
        tok = _pick_token(last, sub, temp, topk, sample)
        return self._unpack(new_cs), tok, key2

    def _decode_fn(self, params, bufs, pools, bt, lens, tok, gen,
                   budgets, active, keys, temps, topks, sample):
        """K cached decode steps for all rows — the slot engine's burst
        with lengths carried through the scan instead of living inside
        the cache pytree (block tables are per-dispatch constants)."""
        self.trace_counts['decode'] += 1

        def body(carry, _):
            pools, lens, tok, gen, keys = carry
            step_active = active & (gen < budgets)
            caches = self._caches(pools, bt, lens)
            (lg, new_cs), _ = _fm.functional_call(
                self._model, params, bufs, args=(Tensor(tok),),
                kwargs={'caches': caches}, training=False)
            inc = step_active.astype(jnp.int32)
            ks = jax.vmap(jax.random.split)(keys)
            subs = ks[:, 1]
            keys2 = jnp.where(step_active[:, None], ks[:, 0], keys)
            nxt = jax.vmap(_pick_token)(lg[:, -1], subs, temps, topks,
                                        sample)
            tok2 = jnp.where(step_active, nxt, tok[:, 0])[:, None]
            return ((self._unpack(new_cs), lens + inc, tok2, gen + inc,
                     keys2), (tok2[:, 0], step_active))

        carry, (toks, actives) = jax.lax.scan(
            body, (pools, lens, tok, gen, keys), None,
            length=self.decode_block)
        pools2, lens2, tok2, gen2, keys2 = carry
        return pools2, lens2, tok2, gen2, keys2, toks, actives

    def _verify_fn(self, params, bufs, pools, bt, lens, toks):
        """ONE forward over [S, K+1] rows: position 0 feeds each row's
        last emitted token, positions 1..K feed its drafts. Returns the
        greedy pick after every position — pick i is the model's true
        next token given [..., tok_0..tok_i], which is what the host
        accept rule compares drafts against. Writes land at lens..
        lens+K; rows past what acceptance advances are garbage the next
        pass overwrites (or scratch-mapped, past the reservation)."""
        self.trace_counts['verify'] += 1
        caches = self._caches(pools, bt, lens)
        (lg, new_cs), _ = _fm.functional_call(
            self._model, params, bufs, args=(Tensor(toks),),
            kwargs={'caches': caches}, training=False)
        picks = jnp.argmax(lg.astype(jnp.float32), axis=-1).astype(
            jnp.int32)
        return self._unpack(new_cs), picks

    # ---- per-step dispatches (lock held) ------------------------------

    def _prefill_step(self):
        for req, start, ids, valid, final in self.scheduler.prefill_plan():
            slot = req.slot
            self._pools, tok, key2 = self._prefill_jit(
                self._params, self._bufs, self._pools,
                self.scheduler.block_tables[slot:slot + 1],
                np.asarray([start], np.int32),
                np.asarray(ids, np.int32)[None, :],
                np.int32(valid), req._key,
                np.float32(req.temperature), np.int32(req.top_k),
                np.asarray(req.do_sample))
            self.metrics.on_prefill_tokens(valid)
            self._lens[slot] = start + valid
            self.scheduler.mark_prefilled(req, start + valid)
            self._trace_prefill(req, start, valid, final)
            if not final:
                continue
            tok = int(tok)
            self._last[slot, 0] = tok
            self._gen[slot] = 1
            self._keys[slot] = np.asarray(key2)
            self._active[slot] = True
            self._emit(req, [tok])
            if len(req.tokens) >= req.max_new_tokens:
                self._retire(req)

    def _decode_step(self):
        slots = self.scheduler.decode_slots()
        if not slots:
            return
        if self.spec_k:
            return self._spec_step(slots)
        # span covers dispatch AND the device_get sync — the burst's
        # actual wall time, not just the async enqueue. The timeline
        # splits the same window (host_dispatch vs device_block) and the
        # dispatch args are stashed for perf_estimate's cost-model
        # lowering (identical avals, so no retrace).
        args = (self._params, self._bufs, self._pools,
                self.scheduler.block_tables, self._lens, self._last,
                self._gen, self._budgets, self._active, self._keys,
                self._temps, self._topks, self._sample)
        self._decode_args = args
        with self._tracer.start_span('serving.decode_burst',
                                     tags={'rows': len(slots),
                                           'block': self.decode_block}):
            with self.timeline.phase('host_dispatch'):
                (self._pools, lens, last, gen, keys, toks,
                 actives) = self._decode_jit(*args)
            with self.timeline.phase('device_block'):
                lens, last, gen, keys, toks, actives = jax.device_get(
                    (lens, last, gen, keys, toks, actives))
        self.timeline.end_step()
        self._lens = np.array(lens)
        self._last = np.array(last)
        self._gen = np.array(gen)
        self._keys = np.array(keys)
        for slot in slots:
            req = self._requests[slot]
            new = [int(toks[k, slot]) for k in range(toks.shape[0])
                   if actives[k, slot]]
            self._emit(req, new)
            if len(req.tokens) >= req.max_new_tokens:
                self._retire(req)

    def _spec_step(self, slots):
        """Draft K tokens per decoding row, verify all rows in ONE
        [S, K+1] forward, accept each row's longest draft prefix that
        matches the model's own greedy picks, plus the pick after it
        (the 'bonus' token — free, since the verify forward already
        computed it). Worst case (0 accepted) this emits 1 token per
        row, exactly a decode step; best case K+1."""
        K = self.spec_k
        toks = np.zeros((self.num_slots, K + 1), np.int32)
        drafts = {}
        for slot in slots:
            req = self._requests[slot]
            d = self._proposer.propose(req.prompt + req.tokens, K)
            drafts[slot] = d
            toks[slot, 0] = self._last[slot, 0]
            toks[slot, 1:] = d
        args = (self._params, self._bufs, self._pools,
                self.scheduler.block_tables, self._lens, toks)
        self._verify_args = args
        with self._tracer.start_span('serving.decode_burst',
                                     tags={'rows': len(slots),
                                           'spec_k': K}):
            with self.timeline.phase('host_dispatch'):
                self._pools, picks = self._verify_jit(*args)
            with self.timeline.phase('device_block'):
                picks = np.asarray(jax.device_get(picks))
        self.timeline.end_step()
        for slot in slots:
            req = self._requests[slot]
            d, g = drafts[slot], picks[slot]
            a = 0
            while a < K and d[a] == int(g[a]):
                a += 1
            # accepted drafts + the bonus pick, clipped to budget; a
            # decoding row always has budget left (it would have retired
            # otherwise), so at least one token emits and lens advances
            left = int(self._budgets[slot]) - int(self._gen[slot])
            emit = [int(x) for x in g[:min(a + 1, left)]]
            self.metrics.on_spec(K, max(len(emit) - 1, 0))
            req._spec_proposed += K
            req._spec_accepted += max(len(emit) - 1, 0)
            if req._span is not None:
                req._span.add_event('spec_accept', proposed=K,
                                    accepted=max(len(emit) - 1, 0))
            self._lens[slot] += len(emit)
            self._gen[slot] += len(emit)
            self._last[slot, 0] = emit[-1]
            self._emit(req, emit)
            if len(req.tokens) >= req.max_new_tokens:
                self._retire(req)
