"""Named predictor-zoo presets: known-good engine configs by name.

A ReplicaWorker process has to build an engine from nothing but argv.
Hand-assembling a config + seeded weights + engine kwargs in every
spawn site (tests, benches, ops runbooks) is exactly the drift the
model registry exists to prevent, so the zoo pins 2–3 named presets:

    ReplicaWorker --preset gpt-nano          # seeded weights, no registry
    publish_preset(registry, 'gpt-nano')     # ship the weights as a
                                             # CRC-manifested artifact

`publish_preset` stamps `meta={'preset': name}` on the registry entry,
so a worker that pulls the artifact by fingerprint knows which config
to rebuild around the weights — the preset name IS the architecture
pointer, the artifact IS the weights. `host_factory()` closes the loop
for ModelHost: entry -> engine, loading the entry's state dict into
the preset's model skeleton.

Determinism contract: build_model(name) seeds the global RNG with the
preset's pinned seed before construction, so two processes building
the same preset hold bit-identical weights — which is what lets the
fabric chaos tests compare a SIGKILL'd worker's re-generated tokens
against a single-engine reference without shipping weights at all.
"""
from ...framework import io_save

__all__ = ['PRESETS', 'preset', 'build_model', 'build_engine',
           'publish_preset', 'host_factory']

# model: GPTConfig kwargs. engine: 'slot' | 'paged'. engine_kwargs:
# engine constructor kwargs. seed: global RNG seed pinned per preset.
PRESETS = {
    # the test-suite workhorse: matches the serving test fixtures so a
    # worker process and an in-proc reference engine are token-identical
    'gpt-nano': {
        'model': dict(vocab_size=211, hidden_size=64, num_layers=2,
                      num_heads=4, max_position_embeddings=128,
                      dropout=0.0),
        'engine': 'slot',
        'engine_kwargs': dict(num_slots=2, max_len=32, prefill_chunk=8,
                              decode_block=2),
        'seed': 7,
    },
    # same weights, paged KV with the prefix cache on — the preset the
    # prefix-affinity routing bench runs, where directory hits matter
    'gpt-nano-paged': {
        'model': dict(vocab_size=211, hidden_size=64, num_layers=2,
                      num_heads=4, max_position_embeddings=128,
                      dropout=0.0),
        'engine': 'paged',
        'engine_kwargs': dict(num_seqs=4, max_len=64, page_size=8,
                              prefill_chunk=8, decode_block=2,
                              prefix_cache=True),
        'seed': 7,
    },
    # bench-sized: the CPU serving-bench config (bench_extra) with a
    # paged engine big enough for Poisson bursts over real sockets
    'gpt-micro': {
        'model': dict(vocab_size=512, hidden_size=128, num_layers=2,
                      num_heads=4, max_position_embeddings=256,
                      dropout=0.0),
        'engine': 'paged',
        'engine_kwargs': dict(num_seqs=8, max_len=128, page_size=16,
                              prefill_chunk=16, decode_block=4,
                              prefix_cache=True),
        'seed': 11,
    },
}


def preset(name):
    """The named preset spec (a copy), KeyError listing the zoo."""
    try:
        spec = PRESETS[name]
    except KeyError:
        raise KeyError('unknown preset %r; available: %s'
                       % (name, sorted(PRESETS))) from None
    return {'model': dict(spec['model']),
            'engine': spec['engine'],
            'engine_kwargs': dict(spec['engine_kwargs']),
            'seed': spec['seed']}


def build_model(name, state_dict=None):
    """The preset's model, eval mode. With no state_dict the global RNG
    is seeded with the preset's pin first, so every process building
    the same preset holds bit-identical weights."""
    import paddle_tpu as paddle
    from ...text.models.gpt import GPTConfig, GPTForCausalLM
    spec = preset(name)
    if state_dict is None:
        paddle.seed(spec['seed'])
    m = GPTForCausalLM(GPTConfig(**spec['model']))
    if state_dict is not None:
        m.set_state_dict(state_dict)
    m.eval()
    return m


def build_engine(name, model=None, state_dict=None, **overrides):
    """The preset's engine around `model` (built fresh if omitted).
    `overrides` patch engine kwargs (e.g. spec_k for a spec-decode
    variant) without forking the preset."""
    from ..engine import ContinuousBatchingEngine
    from ..paged_engine import PagedContinuousBatchingEngine
    spec = preset(name)
    if model is None:
        model = build_model(name, state_dict=state_dict)
    kwargs = spec['engine_kwargs']
    kwargs.update(overrides)
    cls = PagedContinuousBatchingEngine if spec['engine'] == 'paged' \
        else ContinuousBatchingEngine
    return cls(model, **kwargs)


def publish_preset(registry, name, version='v0'):
    """Ship the preset's seeded weights into `registry` as a
    CRC-manifested artifact under (name, version), meta-stamped with
    the preset name so pullers can rebuild the architecture."""
    state = build_model(name).state_dict()
    return registry.publish(name, version, state,
                            meta={'preset': name})


def host_factory(default_preset=None):
    """entry -> engine factory for ModelHost: loads the entry's state
    dict (CRC-checked by io_save) into the preset named by the entry's
    meta — or `default_preset` for entries published outside the zoo."""
    def _factory(entry):
        pname = entry.meta.get('preset', default_preset)
        if pname is None:
            raise KeyError(
                'registry entry (%r, %r) has no preset meta and no '
                'default_preset was given' % (entry.model, entry.version))
        return build_engine(pname, state_dict=io_save.load(entry.path))
    return _factory
