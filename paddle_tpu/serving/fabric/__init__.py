"""Cross-process serving fabric: replica transport, worker processes.

The gateway (serving/gateway/) was built against a duck-typed replica
contract — submit/step/drain/mark_dead/readyz/metrics — that only one
class implemented: InprocReplica, an engine in the same process. This
package makes the contract explicit and gives it a second
implementation that crosses a real process boundary:

- transport.py   ReplicaTransport: the extracted lifecycle + driver
                 base (READY -> DRAINING -> STOPPED | DEAD, condvar
                 drive loop). InprocReplica subclasses it.
- protocol.py    Length-prefixed JSON wire codec with typed frame
                 errors; plugs into ResilientChannel as a codec.
- worker.py      ReplicaWorker: a spawnable process hosting one engine
                 (or ModelHost) behind the wire protocol, with
                 OP_SEMANTICS lint-enforced retry safety, /readyz +
                 /metrics, and (client_id, seq) submit dedup.
- socket_replica.py  SocketReplica: the gateway-side proxy. Failover,
                 QoS shedding and rollout() work unchanged.
- artifacts.py   Content-fingerprinted model artifact distribution:
                 workers pull checkpoints over the transport and
                 CRC-verify the manifest on receipt.
- directory.py   PrefixDirectory + PrefixAffinityRouter: gateway-level
                 chain-hash directory so routing prefers the replica
                 already holding a request's prefix pages.
- presets.py     Named predictor-zoo presets: `ReplicaWorker --preset
                 gpt-nano` brings up a known config with seeded
                 weights, no hand-built state dicts.

See docs/serving.md#fabric for the wire format and lifecycle ladder.
"""
from .artifacts import ArtifactClient, ArtifactServer, ArtifactVerifyError
from .directory import PrefixAffinityRouter, PrefixDirectory
from .presets import PRESETS, build_engine, preset, publish_preset
from .protocol import (JSON_CODEC, MAX_FRAME, FrameDecodeError,
                       FrameTooLargeError, recv_frame, send_frame)
from .socket_replica import SocketReplica
from .transport import ReplicaTransport
from .worker import ReplicaWorker, spawn_worker

__all__ = ['ReplicaTransport', 'SocketReplica', 'ReplicaWorker',
           'spawn_worker', 'ArtifactServer', 'ArtifactClient',
           'ArtifactVerifyError', 'PrefixDirectory',
           'PrefixAffinityRouter', 'PRESETS', 'preset', 'build_engine',
           'publish_preset', 'JSON_CODEC', 'MAX_FRAME', 'send_frame',
           'recv_frame', 'FrameDecodeError', 'FrameTooLargeError']
