"""Gateway-level prefix-cache directory + prefix-affinity routing.

Each paged replica owns a PrefixCache (serving/kv_cache.py) keyed by
the chain hash of full prompt blocks. Those caches are per-engine: a
request routed by load alone lands wherever the pool is idlest, and a
90%-shared system prompt re-prefills on every replica that has not
seen it. The directory is the gateway's cheap global view: every
successful placement records the prompt's chain hashes -> replica
index, and the PrefixAffinityRouter ranks replicas by how deep a chain
for THIS prompt they have already served.

It is a HINT table, not a coherence protocol: entries go stale when a
replica evicts or dies, and the cost of a stale hint is one prefix
miss — the engine re-prefills exactly as it would have without the
directory. That is why the directory can be an LRU map updated on
placement only, with no invalidation traffic over the fabric.

The chain function is PrefixCache._chain itself, so a directory depth
of b blocks corresponds exactly to the pages a replica's own cache
would match (same block alignment, same never-cover-the-whole-prompt
rule).
"""
from collections import OrderedDict

from ..gateway.router import LeastLoadedRouter
from ..kv_cache import PrefixCache

__all__ = ['PrefixDirectory', 'PrefixAffinityRouter']


class PrefixDirectory:
    """LRU map: chain hash of a full prompt block -> replica index that
    most recently prefilled it."""

    def __init__(self, page_size, capacity=4096):
        if page_size < 1:
            raise ValueError('page_size must be >= 1')
        self.page_size = int(page_size)
        self.capacity = int(capacity)
        self._dir = OrderedDict()

    def chain_hashes(self, prompt):
        """Chain hash per full block, matching PrefixCache.match's
        coverage rule (at most len(prompt)-1 tokens — the last token
        always prefills)."""
        P = self.page_size
        nfull = (len(prompt) - 1) // P
        out, h = [], None
        for b in range(nfull):
            h = PrefixCache._chain(h, prompt[b * P:(b + 1) * P])
            out.append(h)
        return out

    def observe(self, prompt, replica_index):
        """Record a placement: every full block of `prompt` now (very
        likely) has its pages on `replica_index`. Latest writer wins —
        the most recent placement is the warmest cache."""
        for h in self.chain_hashes(prompt):
            if h in self._dir:
                self._dir.move_to_end(h)
            self._dir[h] = int(replica_index)
        while len(self._dir) > self.capacity:
            self._dir.popitem(last=False)

    def depths(self, prompt):
        """{replica_index: matched chain depth in blocks} for `prompt`.
        The walk stops at the first unknown hash — beyond it no
        replica's cache can chain-match either."""
        depths = {}
        for b, h in enumerate(self.chain_hashes(prompt)):
            owner = self._dir.get(h)
            if owner is None:
                break
            self._dir.move_to_end(h)
            depths[owner] = b + 1
        return depths

    def __len__(self):
        return len(self._dir)


class PrefixAffinityRouter(LeastLoadedRouter):
    """LeastLoaded with a prefix-depth tier in front: replicas holding
    a deeper cached chain for the request's prompt rank first,
    least-loaded among equals.

    The gateway calls `candidates_for_request(pool, gw)` when the
    router has one (it sees the PROMPT, which `candidates(pool)` never
    does) and `note_placement(prompt, index)` after every successful
    placement — including failover re-placements, so the directory
    tracks where the tokens actually went."""

    name = 'prefix_affinity'

    def __init__(self, page_size, capacity=4096):
        self.directory = PrefixDirectory(page_size, capacity=capacity)

    def candidates_for_request(self, pool, gw):
        depths = self.directory.depths(gw.prompt)
        rs = [r for r in pool if r.routable()]
        rs.sort(key=lambda r: (-depths.get(r.index, 0), r.load(),
                               r.index))
        return rs

    def note_placement(self, prompt, replica_index):
        self.directory.observe(prompt, replica_index)
