"""ReplicaTransport: the gateway's replica contract, made explicit.

The gateway composes replicas through a small surface — submit new
work, step in-flight work, observe load, walk the lifecycle ladder —
and for one PR-generation that surface existed only as the duck type
InprocReplica happened to have. This base class extracts it so a
replica living in another PROCESS (fabric/socket_replica.py) is
interchangeable with one living in this one (gateway/replica.py).

The contract, by group:

transport (subclass MUST implement)
    submit(prompt, **sampling) -> request handle with .tokens/.done/
        .outcome (engine Request in-proc, RemoteRequest over a socket)
    step() -> int            one unit of progress; raising means
                             transport loss, the driver calls on_lost
    has_pending() -> bool    unfinished work exists (drives parking)

observability (subclass MUST implement)
    queue_depth(), occupancy(), load()   router ranking inputs

lifecycle (provided here)
    READY -> DRAINING -> STOPPED, or -> DEAD on loss. All state writes
    go through one condvar so the driver's DRAINING -> STOPPED
    check-and-set cannot race the gateway's mark_dead.

driver (provided here)
    start_driver(on_step, on_lost): the park/step loop every transport
    shares. Parks while no pending work; a DRAINING replica with no
    assigned requests self-transitions to STOPPED and exits.

scrape (default here, socket transports override)
    scrape_kwargs() -> kwargs for FleetCollector.add_target: in-proc
    replicas hand over their registry object; socket replicas hand a
    /metrics.json url so the collector scrapes the worker PROCESS and
    a SIGKILL'd worker reads stale-not-wrong (fleet_target_up -> 0).
"""
import threading

from ...distributed.resilience import CircuitBreaker
from ...monitor.registry import MetricRegistry

__all__ = ['ReplicaTransport', 'READY', 'DRAINING', 'DEAD', 'STOPPED',
           'STATE_CODES']

READY = 'ready'
DRAINING = 'draining'
DEAD = 'dead'
STOPPED = 'stopped'

# gauge encoding for gateway_replica_state (docs/observability.md)
STATE_CODES = {READY: 0, DRAINING: 1, DEAD: 2, STOPPED: 3}


class ReplicaTransport:

    def __init__(self, index, endpoint, breaker=None, registry=None,
                 failure_threshold=1):
        self.index = int(index)
        self.endpoint = endpoint
        self.registry = registry if registry is not None \
            else MetricRegistry()
        if breaker is None:
            # in-proc default: one transport failure means
            # partitioned-or-dead, not a blip — a single strike opens
            # the breaker and the gateway replaces rather than retries.
            # Socket transports raise the threshold to tolerate one
            # reconnect (see SocketReplica).
            breaker = CircuitBreaker(failure_threshold=failure_threshold,
                                     reset_timeout=3600.0)
        breaker.bind_name(self.endpoint)
        self.breaker = breaker
        self.state = READY
        # GatewayRequest -> request handle; guarded by the GATEWAY lock
        # (never touched by the driver thread directly)
        self.assigned = {}
        self._cv = threading.Condition()
        self._thread = None

    # ---- transport (subclass responsibility) --------------------------

    def submit(self, prompt, **sampling):
        raise NotImplementedError

    def step(self):
        raise NotImplementedError

    def has_pending(self):
        """Unfinished work the driver should keep stepping for."""
        raise NotImplementedError

    # ---- observability (subclass responsibility) ----------------------

    def queue_depth(self):
        raise NotImplementedError

    def occupancy(self):
        raise NotImplementedError

    def load(self):
        """Router ranking key: queued requests + occupied slots, both
        in request units."""
        raise NotImplementedError

    def scrape_kwargs(self):
        """How gateway.attach_fleet registers this replica with the
        FleetCollector. In-proc: the registry object itself."""
        return {'registry': self.registry}

    def metrics_server(self, **kwargs):
        """A MetricsServer over this replica's registry with readiness
        wired to its drain state (not started)."""
        from ...monitor.server import MetricsServer
        return MetricsServer(registry=self.registry, readiness=self.ready,
                             **kwargs)

    # ---- lifecycle (gateway lock held unless noted) -------------------

    def routable(self):
        """May the router place NEW work here?"""
        return self.state == READY and self.breaker.allow()

    @property
    def alive(self):
        """Still worth stepping (in-flight work may exist)?"""
        return self.state in (READY, DRAINING)

    def ready(self):
        """/readyz readiness: READY routes, anything else 503s while
        /healthz stays 200 (drain must not get the process restarted)."""
        return self.state == READY

    def drain(self):
        """Stop admissions, let in-flight decode finish. Subclasses
        chain to propagate the drain to the engine/worker."""
        self._transition(DRAINING)

    def mark_dead(self):
        self._transition(DEAD)

    def mark_stopped(self):
        self._transition(STOPPED)

    def _transition(self, state):
        """All writes of `state` go through the condvar: the driver
        thread check-and-sets DRAINING -> STOPPED under _cv, so a bare
        write here could race it and overwrite DEAD with STOPPED."""
        with self._cv:
            self.state = state
            self._cv.notify_all()

    def wake(self):
        with self._cv:
            self._cv.notify_all()

    # ---- driver thread ------------------------------------------------

    def start_driver(self, on_step, on_lost):
        """Spawn the replica's drive loop: step whenever work exists,
        park on the condvar otherwise. `on_step(self)` runs after every
        successful step (the gateway collects tokens there);
        `on_lost(self, exc)` runs once on transport failure and the
        thread exits. Neither callback is invoked under the condvar, so
        the gateway lock ordering (gateway -> engine) holds."""
        def _run():
            while True:
                with self._cv:
                    while self.alive and not self.has_pending():
                        if self.state == DRAINING and not self.assigned:
                            self.state = STOPPED
                            return
                        self._cv.wait(0.02)
                    if not self.alive:
                        return
                try:
                    self.step()
                except Exception as exc:     # noqa: BLE001 — transport
                    on_lost(self, exc)
                    return
                on_step(self)

        self._thread = threading.Thread(
            target=_run, name='gw-replica-%d' % self.index, daemon=True)
        self._thread.start()
        return self._thread

    def join(self, timeout=None):
        if self._thread is not None:
            self._thread.join(timeout)

    def __repr__(self):
        return ('%s(%d, %s, load=%.1f, assigned=%d)'
                % (type(self).__name__, self.index, self.state,
                   self.load(), len(self.assigned)))
