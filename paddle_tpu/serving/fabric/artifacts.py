"""Model artifact distribution over the fabric wire protocol.

A worker process spawned with nothing but a (model, version) pair must
obtain the exact bytes the gateway's registry catalogs — copying
checkpoint paths around by hand is how fleets end up serving the wrong
weights. The flow:

    gateway side                         worker side
    ------------                         -----------
    ArtifactServer(registry)             ArtifactClient(endpoint, dir)
        op 'manifest' ------------------>  what files, what fingerprint
        op 'fetch'    ------------------>  base64 chunks (CHUNK raw
                                           bytes per frame, well under
                                           protocol.MAX_FRAME)
                                           write atomically, then
                                           VERIFY: CRC manifest check +
                                           content fingerprint match
                                           -> registry.register()

Verification is the contract: a corrupted transfer (or a corrupted
source) raises ArtifactVerifyError — a typed reject the worker can
report and survive, never weights-silently-wrong and never a crash.
The fingerprint is `registry.artifact_fingerprint` — a hash of the CRC
manifest, so matching it proves content identity, not just transfer
integrity.

The client rides ResilientChannel with the JSON codec: fetches are
pure reads (idempotent, retried) and inherit breaker/deadline/trace
behavior like every other fabric call.
"""
import base64
import os
import socketserver
import threading

from ...distributed.resilience import FrameError, ResilientChannel
from ...framework import io_save
from ...monitor import tracing as _tracing
from ..registry.registry import artifact_fingerprint
from .protocol import MAX_FRAME, recv_frame, send_frame

__all__ = ['ArtifactServer', 'ArtifactClient', 'ArtifactVerifyError',
           'CHUNK', 'OP_SEMANTICS']

# raw bytes per fetch reply; base64 inflates 4/3, comfortably < MAX_FRAME
CHUNK = 4 << 20


class ArtifactVerifyError(RuntimeError):
    """Pulled artifact failed verification (CRC manifest mismatch or
    content fingerprint != the cataloged fingerprint). The partial
    download is removed; the worker should report and keep serving
    what it has."""


# retry semantics per op, lint-enforced (tools/graftlint idempotency):
OP_SEMANTICS = {
    'manifest': 'idempotent',   # pure read of the catalog entry
    'fetch': 'idempotent',      # pure read at an explicit offset
    'ping': 'idempotent',       # liveness probe, pure read
    'stop': 'non_idempotent',   # second delivery hits a dead server
}


class _Handler(socketserver.BaseRequestHandler):
    def setup(self):
        self.server.live_connections.add(self.request)

    def finish(self):
        self.server.live_connections.discard(self.request)

    def handle(self):
        art = self.server.artifact_server
        while True:
            try:
                msg = recv_frame(self.request)
            except FrameError as e:
                # typed reject, then close (framing may be out of sync)
                try:
                    send_frame(self.request,
                               {'error': repr(e),
                                'error_type': type(e).__name__})
                except OSError:
                    pass
                return
            except (ConnectionError, OSError):
                return
            if msg is None:
                return
            span = _tracing.default_tracer().server_span(
                msg, 'fabric.artifacts')
            try:
                op = msg.get('op')
                if op == 'manifest':
                    send_frame(self.request,
                               art.manifest(msg['model'], msg['version']))
                elif op == 'fetch':
                    send_frame(self.request,
                               art.fetch(msg['model'], msg['version'],
                                         msg['file'], msg['offset']))
                elif op == 'ping':
                    send_frame(self.request, {'ok': True})
                elif op == 'stop':
                    send_frame(self.request, {'ok': True})
                    self.server.shutdown()
                    return
                else:
                    send_frame(self.request,
                               {'error': 'unknown op %r' % op})
            except Exception as e:  # report instead of killing the server
                span.set_error(e)
                try:
                    send_frame(self.request, {'error': repr(e)})
                except OSError:
                    return
            finally:
                span.finish()


class _ArtifactTCPServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class ArtifactServer:
    """Serves a ModelRegistry's file artifacts (+ their CRC manifest
    sidecars) over the fabric wire protocol. Start next to the gateway;
    pass `.endpoint` to worker processes."""

    def __init__(self, registry, host='127.0.0.1', port=0):
        self.registry = registry
        self._srv = _ArtifactTCPServer((host, port), _Handler,
                                       bind_and_activate=True)
        self._srv.artifact_server = self
        self._srv.live_connections = set()
        self.port = self._srv.server_address[1]
        self.endpoint = '%s:%d' % (host, self.port)
        self._thread = None

    def manifest(self, model, version):
        entry = self.registry.entry(model, version)
        if not os.path.isfile(entry.path):
            raise ValueError('artifact (%r, %r) is not a file artifact — '
                             'fabric distribution serves file checkpoints'
                             % (model, version))
        files = [{'name': os.path.basename(entry.path),
                  'size': os.path.getsize(entry.path)}]
        side = io_save.manifest_path(entry.path)
        if os.path.exists(side):
            files.append({'name': os.path.basename(side),
                          'size': os.path.getsize(side)})
        return {'model': entry.model, 'version': entry.version,
                'fingerprint': entry.fingerprint, 'nbytes': entry.nbytes,
                'meta': entry.meta, 'artifact': files[0]['name'],
                'files': files}

    def fetch(self, model, version, name, offset):
        entry = self.registry.entry(model, version)
        root = os.path.dirname(entry.path)
        # the manifest names only basenames it advertised; refuse path
        # traversal rather than serve arbitrary files
        if os.path.basename(name) != name:
            raise ValueError('bad artifact file name %r' % name)
        path = os.path.join(root, name)
        with open(path, 'rb') as f:
            f.seek(int(offset))
            data = f.read(CHUNK)
            eof = f.tell() >= os.path.getsize(path)
        return {'data': base64.b64encode(data).decode('ascii'),
                'eof': bool(eof)}

    def start(self):
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


class ArtifactClient:
    """Worker-side puller: fetch, verify, register."""

    def __init__(self, endpoint, cache_dir):
        from .protocol import JSON_CODEC
        self.endpoint = endpoint
        self.cache_dir = cache_dir
        self._channel = ResilientChannel(endpoint, codec=JSON_CODEC,
                                         max_frame=MAX_FRAME)

    def close(self):
        self._channel.close()

    def _checked(self, out):
        if isinstance(out, dict) and 'error' in out:
            raise RuntimeError('artifact server error: %s' % out['error'])
        return out

    def ensure(self, registry, model, version):
        """Make (model, version) available in the worker's local
        `registry`, pulling and verifying the artifact if absent.
        Returns the local RegistryEntry."""
        if (model, version) in registry:
            return registry.entry(model, version)
        info = self._checked(self._channel.call(
            {'op': 'manifest', 'model': model, 'version': version}))
        dest_dir = os.path.join(self.cache_dir, str(model))
        local = None
        for f in info['files']:
            data = bytearray()
            while True:
                out = self._checked(self._channel.call(
                    {'op': 'fetch', 'model': model, 'version': version,
                     'file': f['name'], 'offset': len(data)}))
                data.extend(base64.b64decode(out['data']))
                if out['eof']:
                    break
            path = os.path.join(dest_dir, f['name'])
            # atomic write: a torn local file can never masquerade as a
            # complete artifact even if the worker dies mid-pull
            io_save.write_bytes_atomic(path, bytes(data))
            if f['name'] == info['artifact']:
                local = path
        got = artifact_fingerprint(local)
        if got != info['fingerprint']:
            os.unlink(local)
            raise ArtifactVerifyError(
                'pulled artifact (%r, %r) fingerprint %s does not match '
                'cataloged %s — rejecting' % (model, version, got,
                                              info['fingerprint']))
        try:
            # register(verify=True) re-checks the CRC manifest sidecar
            return registry.register(model, version, local,
                                     meta=info.get('meta'), verify=True)
        except io_save.CheckpointCorruptError as e:
            os.unlink(local)
            raise ArtifactVerifyError(
                'pulled artifact (%r, %r) failed CRC manifest '
                'verification: %s' % (model, version, e))

    def stop_server(self):
        self._checked(self._channel.call({'op': 'stop'}, idempotent=False))
