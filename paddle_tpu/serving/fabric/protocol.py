"""Length-prefixed JSON wire protocol for the serving fabric.

Frame layout (identical skeleton to the PS wire: 8-byte big-endian
length, then payload) with a JSON payload instead of the PS binary
codec:

    +----------------+----------------------+
    | len (8B, >Q)   | utf-8 JSON object    |
    +----------------+----------------------+

JSON over the PS frame is a deliberate trade: fabric messages are
small control records (op, prompt token ids, sampled tokens) where
schema evolution and debuggability beat the binary codec's density —
and bulk bytes (model artifacts) ride base64-chunked fetches, not one
giant frame. The codec plugs into ResilientChannel via its `codec=`
pair, so retries, breakers, deadlines and `_trace` span continuation
are inherited, not reimplemented.

Failure taxonomy (all defined in distributed/resilience.py so the
channel can classify them without importing serving):

- FrameTooLargeError  declared length exceeds MAX_FRAME — refused
                      BEFORE allocating, so a corrupted header cannot
                      OOM the receiver. Not retryable.
- FrameDecodeError    payload arrived whole but is not valid JSON (or
                      not JSON-encodable on send). Not retryable.
- ConnectionError     peer closed mid-frame — the standard transport
                      loss the channel reconnects/retries on.
"""
import json
import struct

from ...distributed.resilience import FrameDecodeError, FrameTooLargeError

__all__ = ['MAX_FRAME', 'JSON_CODEC', 'encode', 'decode', 'send_frame',
           'recv_frame', 'FrameDecodeError', 'FrameTooLargeError']

# Generous for control traffic (a 4k-token prompt is ~30KB of JSON) yet
# small enough that a corrupted length header fails fast. Artifact
# fetches chunk well below this (artifacts.CHUNK).
MAX_FRAME = 16 << 20


def encode(obj):
    """Object -> utf-8 JSON bytes. Raises FrameDecodeError on
    non-JSON-encodable input so the caller sees a typed protocol error,
    not a bare TypeError from deep inside the channel."""
    try:
        return json.dumps(obj, separators=(',', ':')).encode('utf-8')
    except (TypeError, ValueError) as e:
        raise FrameDecodeError('message is not JSON-encodable: %s' % e)


def decode(buf):
    """utf-8 JSON bytes -> object, FrameDecodeError on garbage."""
    try:
        return json.loads(buf.decode('utf-8'))
    except (UnicodeDecodeError, ValueError) as e:
        raise FrameDecodeError('frame payload is not valid JSON: %s' % e)


# the (encode, decode) pair ResilientChannel(codec=...) expects
JSON_CODEC = (encode, decode)


def send_frame(sock, obj, max_frame=MAX_FRAME):
    """Server-side helper: frame and send one JSON message."""
    payload = encode(obj)
    if len(payload) > max_frame:
        raise FrameTooLargeError(
            'refusing to send %d-byte frame (max_frame=%d)'
            % (len(payload), max_frame))
    sock.sendall(struct.pack('>Q', len(payload)) + payload)


def recv_frame(sock, max_frame=MAX_FRAME):
    """Server-side helper: receive one framed JSON message.

    Returns None on a clean EOF at a frame boundary (client hung up
    between requests — the normal end of a connection); raises
    ConnectionError on EOF MID-frame (the bytes the peer promised never
    arrived), FrameTooLargeError / FrameDecodeError per the taxonomy.
    """
    hdr = b''
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            if not hdr:
                return None
            raise ConnectionError('peer closed mid-header')
        hdr += chunk
    n = struct.unpack('>Q', hdr)[0]
    if n > max_frame:
        raise FrameTooLargeError(
            'peer declared %d-byte frame (max_frame=%d)' % (n, max_frame))
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError('peer closed mid-frame')
        buf.extend(chunk)
    return decode(bytes(buf))
