"""SocketReplica: the gateway-side proxy for a ReplicaWorker process.

Implements the ReplicaTransport contract over a ResilientChannel with
the fabric JSON codec, so the gateway's failover, QoS shedding and
rollout() machinery work unchanged across the process boundary:

- submit() journals every send with a (client, seq) pair; the worker
  dedups on it, so the channel's retry of a timed-out submit admits
  exactly once (idempotent= is COMPUTED from the journal pair — the
  lint-enforced discipline for conditional ops);
- step() is one 'poll': it pulls newly generated tokens into local
  RemoteRequest shadows that quack like engine requests (.tokens /
  .done / .outcome plus the wide-event stat fields, stamped from the
  worker's final record), which is all _collect_locked ever reads;
- a step/submit failure after the channel's retry budget raises — the
  driver's on_lost fires and the gateway fails the work over exactly
  as it would for a dead in-proc replica. The breaker is SHARED
  between the channel and the replica (threshold 2: one reconnect
  retry is a blip, two consecutive failures is a dead worker);
- rollout() sees a multi-model worker through _EngineProxy, which
  forwards prepare_rollout/finish_rollout and exposes a per-worker
  registry proxy — the gateway's identity-dedup then flips EVERY
  worker's serving pointer, which is precisely correct: each process
  has its own registry;
- scrape_kwargs() hands the worker's /metrics.json URL to the
  FleetCollector, so fleet federation scrapes the worker PROCESS and
  a SIGKILL'd worker reads stale-not-wrong.
"""
import os
import threading
import time

from ...distributed.resilience import (CircuitBreaker, ResilientChannel,
                                       RetryPolicy)
from .protocol import JSON_CODEC, MAX_FRAME
from .transport import ReplicaTransport

__all__ = ['SocketReplica', 'RemoteRequest']


class RemoteRequest:
    """Local shadow of a worker-side engine request. Carries exactly
    what the gateway reads off an engine request: the delivered-token
    ledger, terminal state, and the wide-event instrumentation attrs
    (stamped from the worker's final poll record)."""

    __slots__ = ('id', 'tokens', 'done', 'outcome', '_span', '_admit_t',
                 '_arrival_t', '_prefill_chunks', '_prefix_hit',
                 '_spec_proposed', '_spec_accepted', 'kv_page_seconds')

    def __init__(self, rid):
        self.id = rid
        self.tokens = []
        self.done = False
        self.outcome = None
        self._span = None
        self._admit_t = None
        self._arrival_t = None
        self._prefill_chunks = 0
        self._prefix_hit = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self.kv_page_seconds = 0.0

    def finish(self, rec):
        self.outcome = rec.get('outcome')
        self._admit_t = rec.get('admit_t')
        self._arrival_t = rec.get('arrival_t')
        self._prefill_chunks = rec.get('prefill_chunks', 0)
        self._prefix_hit = rec.get('prefix_hit', 0)
        self._spec_proposed = rec.get('spec_proposed', 0)
        self._spec_accepted = rec.get('spec_accepted', 0)
        self.kv_page_seconds = rec.get('kv_page_seconds', 0.0)
        self.done = True


class _SchedulerProxy:
    """The two scheduler attrs the gateway reads, answered locally —
    sync step() holds the gateway lock, so these must never hit the
    wire."""

    def __init__(self, replica):
        self._r = replica

    @property
    def pending(self):
        return self._r._n_unfinished()

    @property
    def queue(self):
        return [rr for rr in self._r._shadow_list() if not rr.done]


class _RegistryProxy:
    """The registry surface rollout() touches, forwarded to the
    worker's own ModelRegistry. One proxy per replica: the gateway's
    identity-dedup treats each worker as the distinct registry it is."""

    def __init__(self, replica):
        self._r = replica

    def serving_version(self, model):
        out = self._r._call({'op': 'serving_version', 'model': model})
        return out['version']

    def set_serving(self, model, version):
        out = self._r._call({'op': 'set_serving', 'model': model,
                             'version': version})
        return out['prev']


_ROLLOUT_ATTRS = ('prepare_rollout', 'finish_rollout', 'hosts_model',
                  'registry')


class _EngineProxy:
    """Duck-types the slice of the engine surface the gateway touches
    on `rep.engine`. The rollout attrs exist only when the remote
    engine is a ModelHost — `hasattr(engine, 'prepare_rollout')` is the
    gateway's feature probe, and lying about a single-model worker
    would crash rollout() mid-flight."""

    def __init__(self, replica):
        self._r = replica
        self.scheduler = _SchedulerProxy(replica)

    @property
    def num_slots(self):
        return self._r._load['num_slots']

    def __getattr__(self, name):
        if name in _ROLLOUT_ATTRS and self._r.multi_model:
            if name == 'registry':
                return self._r._registry_proxy
            return getattr(self._r, '_' + name)
        raise AttributeError(name)


class SocketReplica(ReplicaTransport):

    def __init__(self, endpoint, index=-1, metrics_url=None,
                 client_id=None, breaker=None, registry=None,
                 call_timeout=None, poll_interval=0.004):
        if breaker is None:
            # threshold 2, not the in-proc 1: a socket can blip without
            # the worker being dead — one reconnect retry is allowed,
            # two consecutive failures opens the breaker and the
            # gateway fails over. No auto-heal (reset far in the
            # future): a dead worker is replaced, not probed.
            breaker = CircuitBreaker(failure_threshold=2,
                                     reset_timeout=3600.0)
        super().__init__(index, endpoint, breaker=breaker,
                         registry=registry)
        kwargs = {} if call_timeout is None else \
            {'call_timeout': call_timeout}
        self._channel = ResilientChannel(
            endpoint, codec=JSON_CODEC, max_frame=MAX_FRAME,
            retry_policy=RetryPolicy(max_attempts=2, base_delay=0.02),
            breaker=self.breaker, **kwargs)
        self.metrics_url = metrics_url
        self._client_id = client_id or 'gw-%d-%x' % (os.getpid(),
                                                     id(self) & 0xffffff)
        self._seq = 0
        self._poll_interval = poll_interval
        self._slock = threading.Lock()
        self._shadows = {}   # wire req id (str) -> RemoteRequest
        self._load = {'state': 'ready', 'queue_depth': 0.0,
                      'occupancy': 0.0, 'num_slots': 1, 'pending': 0}
        self._multi_model = None
        self._registry_proxy = _RegistryProxy(self)
        self.engine = _EngineProxy(self)

    # ---- wire plumbing ------------------------------------------------

    def _call(self, msg, **kw):
        out = self._channel.call(msg, **kw)
        if isinstance(out, dict) and 'error' in out:
            if out.get('error_type') == 'ValueError':
                raise ValueError(out['error'])
            raise RuntimeError('worker %s: %s'
                               % (self.endpoint, out['error']))
        return out

    def _apply_load(self, load):
        if load:
            self._load = load

    def connect(self):
        """Eagerly probe the worker (status): caches multi_model and
        the first load snapshot. Call before adopting into a gateway so
        rollout()'s feature probe never does a wire call under the
        gateway lock."""
        out = self._call({'op': 'status'})
        self._multi_model = bool(out.get('multi_model'))
        self._apply_load(out.get('load'))
        return self

    @property
    def multi_model(self):
        if self._multi_model is None:
            try:
                self.connect()
            except Exception:    # noqa: BLE001 — probe, don't cache
                return False
        return self._multi_model

    # ---- transport ----------------------------------------------------

    def submit(self, prompt, **sampling):
        self._seq += 1
        seq = self._seq
        msg = {'op': 'submit', 'client': self._client_id, 'seq': seq,
               'prompt': [int(t) for t in prompt], 'sampling': sampling}
        # journaled send: retry safety comes from the worker's
        # (client, seq) dedup, so idempotent= is computed, not asserted
        out = self._call(msg, idempotent=seq is not None)
        rid = out['req_id']
        with self._slock:
            rr = self._shadows.get(rid)
            if rr is None:
                rr = self._shadows[rid] = RemoteRequest(rid)
            self._apply_load(out.get('load'))
        return rr

    def step(self):
        """One poll round-trip: pull new tokens into the shadows, ack
        consumed terminals, refresh load gauges. Raises on transport
        failure or a dead remote engine — the failover trigger."""
        with self._slock:
            live = {rid: len(rr.tokens)
                    for rid, rr in self._shadows.items() if not rr.done}
            acks = [rid for rid, rr in self._shadows.items() if rr.done]
        if not live and not acks:
            return 0
        out = self._call({'op': 'poll', 'reqs': live, 'ack': acks})
        delivered = 0
        with self._slock:
            for rid in acks:
                self._shadows.pop(rid, None)
            for rid, entry in out.get('reqs', {}).items():
                rr = self._shadows.get(rid)
                if rr is None:
                    continue
                new = entry.get('tokens') or ()
                if new:
                    rr.tokens.extend(int(t) for t in new)
                    delivered += len(new)
                if entry.get('done'):
                    rr.finish(entry)
            self._apply_load(out.get('load'))
        if self._load.get('state') == 'dead':
            raise RuntimeError('worker %s reports engine death'
                               % self.endpoint)
        if not delivered and live:
            # decode step in flight remotely: back off one interval
            # instead of hammering the socket
            time.sleep(self._poll_interval)
        return delivered

    def has_pending(self):
        with self._slock:
            # done-but-unacked shadows count: one more poll acks them
            return bool(self._shadows)

    def _n_unfinished(self):
        with self._slock:
            return sum(1 for rr in self._shadows.values() if not rr.done)

    def _shadow_list(self):
        with self._slock:
            return list(self._shadows.values())

    # ---- observability -------------------------------------------------

    def queue_depth(self):
        return float(self._load.get('queue_depth', 0.0))

    def occupancy(self):
        return float(self._load.get('occupancy', 0.0))

    def load(self):
        return (self.queue_depth()
                + self.occupancy() * self._load.get('num_slots', 1))

    def scrape_kwargs(self):
        """Federate the worker PROCESS: an HTTP target on its
        /metrics.json. A SIGKILL'd worker then shows stale-not-wrong
        (fleet_target_up -> 0, last snapshot retained)."""
        if self.metrics_url:
            return {'url': self.metrics_url}
        return {'registry': self.registry}

    # ---- lifecycle -----------------------------------------------------

    def drain(self):
        super().drain()
        try:
            self._call({'op': 'drain'})
        except Exception:   # noqa: BLE001 — draining a dead worker is moot
            pass

    # ---- rollout forwarding (reached via _EngineProxy) ------------------

    def _prepare_rollout(self, model, version):
        return self._call({'op': 'rollout_prepare', 'model': model,
                           'version': version})

    def _finish_rollout(self, model, old_version):
        self._call({'op': 'rollout_finish', 'model': model,
                    'old_version': old_version})

    def _hosts_model(self, model, version=None):
        out = self._call({'op': 'hosts_model', 'model': model,
                          'version': version})
        return out['hosts']

    def close(self):
        self._channel.close()
