"""ReplicaWorker: one engine behind a socket, spawnable as a process.

The worker owns exactly what InprocReplica owned — an engine (or a
multi-model ModelHost), a drive loop, a lifecycle state — but serves
it over the fabric wire protocol (protocol.py) so the gateway's
SocketReplica proxy can live in another process:

    python -m paddle_tpu.serving.fabric.worker --preset gpt-nano \
        --port-file /tmp/w0.json

    python -m paddle_tpu.serving.fabric.worker \
        --artifacts HOST:PORT --cache DIR --model m --version v1 \
        --fingerprint 0123abcd...   # content identity, verified on pull

Design rules inherited from the PS services (embedding_service.py):

- every op's retry semantics are declared in OP_SEMANTICS and
  lint-enforced (graftlint idempotency, two-way table<->dispatch);
- 'submit' is the one conditional op: the client journals every send
  with a (client, seq) pair and the worker dedups on it, so a retried
  submit admits exactly once and returns the SAME req_id — the
  exactly-once discipline of journaled PS pushes applied to requests;
- the handler continues the client's rpc.attempt span via
  server_span(msg, 'fabric.worker'), so a gateway-side trace walks
  route -> rpc.call -> rpc.attempt -> fabric.worker.submit across the
  process boundary;
- engines run with emit_event=False: the GATEWAY emits the one
  canonical wide event per request; the worker reports the engine-side
  stat fields (admit_t, prefill chunks, prefix hits, spec counts, KV
  page-seconds) in the final poll reply so that event is as rich as
  the in-proc one. admit_t rides as a raw time.monotonic() value —
  CLOCK_MONOTONIC is system-wide per boot on Linux, so gateway-side
  deltas against it are meaningful.

Lifecycle: /readyz on the worker's MetricsServer flips 503 the moment
a 'drain' op lands (state -> draining) while /healthz stays 200 — the
same drain-must-not-restart-the-pod split the in-proc replica has.
"""
import argparse
import json
import os
import socketserver
import sys
import threading
import time

from ...distributed.resilience import FrameError
from ...monitor import default_registry as _default_registry
from ...monitor import tracing as _tracing
from .protocol import recv_frame, send_frame
from .transport import DEAD, DRAINING, READY, STOPPED

__all__ = ['ReplicaWorker', 'WorkerHandle', 'spawn_worker', 'main',
           'OP_SEMANTICS']

# retry semantics per op, lint-enforced (tools/graftlint idempotency):
OP_SEMANTICS = {
    # journaled admission: the (client, seq) pair dedups a retried send
    # server-side, so journaled submits retry safely; an unjournaled
    # submit must stay single-attempt
    'submit': 'conditional',         # idempotent iff journaled
    'poll': 'idempotent',            # pure read at explicit offsets
    'status': 'idempotent',          # pure read
    'drain': 'idempotent',           # re-drain of a draining worker: no-op
    'rollout_prepare': 'idempotent',  # load+pin: re-pin is refcount-safe
    'rollout_finish': 'idempotent',  # unpin floors at zero
    'set_serving': 'idempotent',     # last-writer set of the same version
    'serving_version': 'idempotent',  # pure read
    'hosts_model': 'idempotent',     # pure read
    'ping': 'idempotent',            # liveness probe, pure read
    'stop': 'non_idempotent',        # second delivery hits a dead server
}


def _final_record(req):
    """Engine-side instrumentation of a finished request, shipped in
    the final poll reply so the gateway's wide event carries the same
    fields an in-proc replica would have handed it."""
    return {'outcome': getattr(req, 'outcome', None),
            'admit_t': getattr(req, '_admit_t', None),
            'arrival_t': getattr(req, '_arrival_t', None),
            'prefill_chunks': getattr(req, '_prefill_chunks', 0),
            'prefix_hit': getattr(req, '_prefix_hit', 0),
            'spec_proposed': getattr(req, '_spec_proposed', 0),
            'spec_accepted': getattr(req, '_spec_accepted', 0),
            'kv_page_seconds': getattr(req, 'kv_page_seconds', 0.0)}


class _Handler(socketserver.BaseRequestHandler):
    def setup(self):
        self.server.live_connections.add(self.request)

    def finish(self):
        self.server.live_connections.discard(self.request)

    def handle(self):
        worker = self.server.replica_worker
        while True:
            try:
                msg = recv_frame(self.request)
            except FrameError as e:
                # typed reject for a malformed/oversized frame, then
                # close: framing may be out of sync, so guessing at the
                # next header would misparse everything after it
                try:
                    send_frame(self.request,
                               {'error': repr(e),
                                'error_type': type(e).__name__})
                except OSError:
                    pass
                return
            except (ConnectionError, OSError):
                return
            if msg is None:
                return
            span = _tracing.default_tracer().server_span(
                msg, 'fabric.worker')
            try:
                op = msg.get('op')
                if op == 'submit':
                    out = worker.op_submit(msg)
                elif op == 'poll':
                    out = worker.op_poll(msg)
                elif op == 'status':
                    out = worker.op_status()
                elif op == 'drain':
                    out = worker.op_drain()
                elif op == 'rollout_prepare':
                    out = worker.op_rollout_prepare(msg)
                elif op == 'rollout_finish':
                    out = worker.op_rollout_finish(msg)
                elif op == 'set_serving':
                    out = worker.op_set_serving(msg)
                elif op == 'serving_version':
                    out = worker.op_serving_version(msg)
                elif op == 'hosts_model':
                    out = worker.op_hosts_model(msg)
                elif op == 'ping':
                    out = {'ok': True, 'state': worker.state}
                elif op == 'stop':
                    send_frame(self.request, {'ok': True})
                    worker.stop(from_wire=True)
                    return
                else:
                    out = {'error': 'unknown op %r' % op,
                           'error_type': 'ValueError'}
                send_frame(self.request, out)
            except Exception as e:  # report instead of killing the server
                span.set_error(e)
                try:
                    send_frame(self.request,
                               {'error': repr(e),
                                'error_type': type(e).__name__})
                except OSError:
                    return
            finally:
                span.finish()


class _WorkerTCPServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class ReplicaWorker:
    """One engine (or ModelHost) served over the fabric protocol.

    Usable in-process for tests (`ReplicaWorker(engine).start()`) and
    as the body of a spawned worker process (`main()`)."""

    def __init__(self, engine, host='127.0.0.1', port=0, metrics_port=0,
                 artifact_client=None):
        self.engine = engine
        self.state = READY
        self._artifacts = artifact_client
        self._requests = {}     # wire req id (str) -> live engine Request
        self._retired = {}      # wire req id (str) -> final reply payload
        self._journal = {}      # client id -> (last seq, last req id)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._stopping = False
        self._srv = _WorkerTCPServer((host, port), _Handler,
                                     bind_and_activate=True)
        self._srv.replica_worker = self
        self._srv.live_connections = set()
        self.port = self._srv.server_address[1]
        self.endpoint = '%s:%d' % (host, self.port)
        # /readyz flips 503 the moment drain lands; /metrics.json is the
        # federation scrape the gateway registers via scrape_kwargs()
        from ...monitor.server import MetricsServer
        self._metrics = MetricsServer(registry=_default_registry(),
                                      host=host, port=metrics_port,
                                      readiness=self.ready)
        self.metrics_url = None
        self._srv_thread = None
        self._drive_thread = None

    # ---- ops (handler thread) -----------------------------------------

    def op_submit(self, msg):
        client, seq = msg.get('client'), msg.get('seq')
        with self._lock:
            if self.state != READY:
                return {'error': 'worker is %s — not admitting' % self.state,
                        'error_type': 'RuntimeError'}
            if client is not None and seq is not None:
                last = self._journal.get(client)
                if last is not None and seq <= last[0]:
                    if seq == last[0]:
                        # duplicate delivery of the in-flight send:
                        # exactly-once means same answer, no re-admit
                        return {'req_id': last[1], 'dup': True,
                                'load': self._load_info()}
                    return {'error': 'stale seq %r <= %r' % (seq, last[0]),
                            'error_type': 'ValueError'}
        # admission outside the worker lock: the engine has its own
        # front-door lock, and ValueError (inadmissible) must propagate
        # as the typed reply, not poison the journal
        req = self.engine.add_request(msg['prompt'], emit_event=False,
                                      **msg.get('sampling', {}))
        rid = str(req.id)
        with self._lock:
            self._requests[rid] = req
            if client is not None and seq is not None:
                self._journal[client] = (seq, rid)
            self._cv.notify_all()
        return {'req_id': rid, 'dup': False, 'load': self._load_info()}

    def op_poll(self, msg):
        with self._lock:
            for rid in msg.get('ack', ()):
                self._retired.pop(rid, None)
            reply = {}
            for rid, offset in msg.get('reqs', {}).items():
                offset = int(offset)
                req = self._requests.get(rid)
                if req is not None and req.done:
                    # retire: freeze the final record so a RETRIED poll
                    # (idempotent) returns the same answer even after
                    # the engine recycles the request
                    rec = _final_record(req)
                    rec['tokens_all'] = [int(t) for t in req.tokens]
                    self._retired[rid] = rec
                    del self._requests[rid]
                    req = None
                    done_rec = rec
                else:
                    done_rec = self._retired.get(rid)
                if req is not None:
                    reply[rid] = {'tokens': [int(t) for t in
                                             req.tokens[offset:]],
                                  'done': False}
                elif done_rec is not None:
                    entry = {k: v for k, v in done_rec.items()
                             if k != 'tokens_all'}
                    entry['tokens'] = done_rec['tokens_all'][offset:]
                    entry['done'] = True
                    reply[rid] = entry
                else:
                    reply[rid] = {'unknown': True, 'tokens': [],
                                  'done': True, 'outcome': 'error'}
        return {'reqs': reply, 'load': self._load_info()}

    def op_status(self):
        return {'ok': True, 'state': self.state, 'pid': os.getpid(),
                'multi_model': hasattr(self.engine, 'prepare_rollout'),
                'load': self._load_info()}

    def op_drain(self):
        self._drain()
        return {'ok': True, 'state': self.state}

    def _host(self):
        eng = self.engine
        if not hasattr(eng, 'prepare_rollout'):
            raise RuntimeError('worker engine is single-model (no '
                               'ModelHost) — rollout ops unavailable')
        return eng

    def op_rollout_prepare(self, msg):
        host = self._host()
        model, version = msg['model'], msg['version']
        if (model, version) not in host.registry:
            if self._artifacts is None:
                raise KeyError('version (%r, %r) not in local registry '
                               'and no artifact source configured'
                               % (model, version))
            self._artifacts.ensure(host.registry, model, version)
        info = host.prepare_rollout(model, version)
        return {k: info[k] for k in ('cache_hits', 'cache_misses',
                                     'load_s') if k in info}

    def op_rollout_finish(self, msg):
        self._host().finish_rollout(msg['model'], msg.get('old_version'))
        return {'ok': True}

    def op_set_serving(self, msg):
        prev = self._host().registry.set_serving(msg['model'],
                                                 msg['version'])
        return {'prev': prev}

    def op_serving_version(self, msg):
        return {'version':
                self._host().registry.serving_version(msg['model'])}

    def op_hosts_model(self, msg):
        return {'hosts': bool(self._host().hosts_model(
            msg['model'], msg.get('version')))}

    def _load_info(self):
        eng = self.engine
        reg = _default_registry()
        occ = reg.get('serving_occupancy')
        return {'state': self.state,
                'queue_depth': len(eng.scheduler.queue),
                'pending': int(eng.scheduler.pending),
                'occupancy': 0.0 if occ is None else float(occ.value()),
                'num_slots': int(getattr(eng, 'num_slots', 1))}

    # ---- lifecycle -----------------------------------------------------

    def ready(self):
        return self.state == READY

    def _drain(self):
        with self._lock:
            if self.state == READY:
                self.state = DRAINING
        # engine.shutdown() stops admissions, finishes in-flight decode
        self.engine.shutdown()
        with self._lock:
            self._cv.notify_all()

    def start(self):
        self._srv_thread = threading.Thread(target=self._srv.serve_forever,
                                            daemon=True)
        self._srv_thread.start()
        self._metrics.start()
        self.metrics_url = self._metrics.url
        self._drive_thread = threading.Thread(target=self._drive,
                                              name='fabric-worker-drive',
                                              daemon=True)
        self._drive_thread.start()
        return self

    def _drive(self):
        eng = self.engine
        while True:
            with self._lock:
                while not self._stopping and not eng.scheduler.pending:
                    if self.state == DRAINING:
                        # drained empty: the ladder's terminal rung. The
                        # TCP server stays up — finished-but-unpolled
                        # requests remain answerable until acked.
                        self.state = STOPPED
                        return
                    self._cv.wait(0.02)
                if self._stopping:
                    return
            try:
                eng.step()
            except Exception:   # noqa: BLE001 — engine death is terminal
                with self._lock:
                    self.state = DEAD
                return

    def stop(self, from_wire=False):
        with self._lock:
            self._stopping = True
            if self.state in (READY, DRAINING):
                self.state = STOPPED
            self._cv.notify_all()
        if from_wire:
            # shutdown() from inside a handler thread deadlocks the
            # serve_forever loop on some platforms; detach it
            threading.Thread(target=self._srv.shutdown,
                             daemon=True).start()
        else:
            self._srv.shutdown()
        self._srv.server_close()
        self._metrics.stop()
        try:
            self.engine.shutdown()
        except Exception:   # noqa: BLE001 — already dead is fine
            pass

    def wait(self):
        """Block until the TCP server exits (the 'stop' op, typically)."""
        if self._srv_thread is not None:
            self._srv_thread.join()


# ---- process entry point ---------------------------------------------


def _build_engine_from_args(args):
    from .presets import build_engine, host_factory
    if args.artifacts:
        if not (args.model and args.version and args.cache):
            raise SystemExit('--artifacts needs --model, --version and '
                             '--cache')
        from ..registry.hosting import ModelHost
        from ..registry.registry import ModelRegistry
        from .artifacts import ArtifactClient, ArtifactVerifyError
        registry = ModelRegistry(root=args.cache)
        client = ArtifactClient(args.artifacts, args.cache)
        entry = client.ensure(registry, args.model, args.version)
        if args.fingerprint and entry.fingerprint != args.fingerprint:
            raise ArtifactVerifyError(
                'pulled (%r, %r) has fingerprint %s, expected %s'
                % (args.model, args.version, entry.fingerprint,
                   args.fingerprint))
        host = ModelHost(registry, host_factory(args.preset),
                         default_model=args.model)
        return host, client
    if args.preset:
        return build_engine(args.preset), None
    raise SystemExit('need --preset or --artifacts/--model/--version')


def main(argv=None):
    p = argparse.ArgumentParser(
        prog='python -m paddle_tpu.serving.fabric.worker',
        description='Serving fabric replica worker process')
    p.add_argument('--preset', default=None,
                   help='predictor-zoo preset name (presets.PRESETS)')
    p.add_argument('--artifacts', default=None,
                   help='ArtifactServer endpoint host:port to pull from')
    p.add_argument('--cache', default=None,
                   help='local artifact cache / registry root directory')
    p.add_argument('--model', default=None)
    p.add_argument('--version', default=None)
    p.add_argument('--fingerprint', default=None,
                   help='expected content fingerprint of the artifact')
    p.add_argument('--host', default='127.0.0.1')
    p.add_argument('--port', type=int, default=0)
    p.add_argument('--metrics-port', type=int, default=0)
    p.add_argument('--port-file', default=None,
                   help='write bound endpoints here as JSON (atomic)')
    args = p.parse_args(argv)

    engine, client = _build_engine_from_args(args)
    worker = ReplicaWorker(engine, host=args.host, port=args.port,
                           metrics_port=args.metrics_port,
                           artifact_client=client)
    worker.start()
    if args.port_file:
        from ...framework.io_save import write_bytes_atomic
        write_bytes_atomic(args.port_file, json.dumps(
            {'endpoint': worker.endpoint,
             'metrics_url': worker.metrics_url,
             'pid': os.getpid()}).encode('utf-8'))
    worker.wait()
    return 0


# ---- parent-side spawn helper ----------------------------------------


class WorkerHandle:
    """A spawned worker process + its bound endpoints."""

    def __init__(self, proc, endpoint, metrics_url, port_file):
        self.proc = proc
        self.endpoint = endpoint
        self.metrics_url = metrics_url
        self._port_file = port_file

    @property
    def pid(self):
        return self.proc.pid

    def kill(self):
        """SIGKILL — the chaos path: no drain, no goodbye."""
        self.proc.kill()

    def terminate(self):
        self.proc.terminate()

    def wait(self, timeout=None):
        return self.proc.wait(timeout)

    def cleanup(self):
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(10)
        try:
            os.unlink(self._port_file)
        except OSError:
            pass


def spawn_worker(preset=None, artifacts=None, cache=None, model=None,
                 version=None, fingerprint=None, timeout=180.0,
                 python=None, extra_env=None):
    """Spawn a ReplicaWorker process and wait for its endpoints.

    Engine bring-up (imports + first trace) dominates; `timeout` bounds
    the wait for the port file. Raises RuntimeError if the process
    exits first (its stderr goes to the parent's, so the failure is
    visible in test output)."""
    import subprocess
    import tempfile
    fd, port_file = tempfile.mkstemp(prefix='fabric-worker-',
                                     suffix='.json')
    os.close(fd)
    os.unlink(port_file)     # worker writes it atomically when bound
    cmd = [python or sys.executable, '-m',
           'paddle_tpu.serving.fabric.worker',
           '--port-file', port_file]
    if preset:
        cmd += ['--preset', preset]
    if artifacts:
        cmd += ['--artifacts', artifacts, '--cache', cache,
                '--model', model, '--version', version]
        if fingerprint:
            cmd += ['--fingerprint', fingerprint]
    env = dict(os.environ)
    env.update(extra_env or {})
    proc = subprocess.Popen(cmd, env=env)
    deadline = time.monotonic() + timeout
    while not os.path.exists(port_file):
        if proc.poll() is not None:
            raise RuntimeError('worker process exited with %r before '
                               'binding' % proc.returncode)
        if time.monotonic() > deadline:
            proc.kill()
            raise RuntimeError('worker did not bind within %.0fs'
                               % timeout)
        time.sleep(0.05)
    with open(port_file) as f:
        info = json.load(f)
    return WorkerHandle(proc, info['endpoint'], info['metrics_url'],
                        port_file)


if __name__ == '__main__':
    sys.exit(main())
