"""Serving metrics: throughput, per-token latency percentiles, occupancy.

Fed by the engine with wall-clock timestamps (injectable clock for
deterministic tests). The latency distribution that matters for serving
is PER-TOKEN (inter-token gap) plus time-to-first-token — a mean hides
exactly the tail that continuous batching is supposed to fix, hence
p50/p99.
"""
import time

__all__ = ['ServingMetrics', 'percentile']


def percentile(values, q):
    """Nearest-rank percentile (q in [0, 100]) without numpy."""
    if not values:
        return None
    xs = sorted(values)
    if len(xs) == 1:
        return xs[0]
    # linear interpolation between closest ranks (numpy default method)
    pos = (len(xs) - 1) * (q / 100.0)
    lo = int(pos)
    hi = min(lo + 1, len(xs) - 1)
    frac = pos - lo
    return xs[lo] * (1.0 - frac) + xs[hi] * frac


class ServingMetrics:
    def __init__(self, clock=None):
        self._clock = clock or time.monotonic
        self._start = None
        self._end = None
        self._arrival = {}        # rid -> t
        self._first_token = {}    # rid -> t
        self._last_token = {}     # rid -> t of the latest token
        self._gaps = []           # inter-token gaps (incl. arrival->first)
        self._tokens = 0
        self._occupancy = []      # per-step occupied-slot fractions

    def now(self):
        return self._clock()

    def on_arrival(self, rid, t=None):
        t = self.now() if t is None else t
        self._arrival[rid] = t
        if self._start is None:
            self._start = t

    def on_tokens(self, rid, count, t=None):
        """`count` tokens became visible for request rid at time t.

        Decode runs in bursts of K steps per dispatch, so K tokens land
        at once; the burst's gap is spread over its tokens — the honest
        accounting, since a consumer reading the stream experiences the
        burst wait once per K tokens.
        """
        if count <= 0:
            return
        t = self.now() if t is None else t
        prev = self._last_token.get(rid)
        if rid not in self._first_token:
            self._first_token[rid] = t
            prev = self._arrival.get(rid, t)
        if prev is not None:
            self._gaps.extend([(t - prev) / count] * count)
        self._last_token[rid] = t
        self._tokens += count
        self._end = t

    def on_step(self, occupied, num_slots):
        self._occupancy.append(occupied / float(num_slots))

    def report(self):
        elapsed = ((self._end - self._start)
                   if self._start is not None and self._end is not None
                   else 0.0)
        ttft = [self._first_token[r] - self._arrival[r]
                for r in self._first_token if r in self._arrival]
        return {
            'tokens': self._tokens,
            'elapsed_s': elapsed,
            'tok_per_s': self._tokens / elapsed if elapsed > 0 else 0.0,
            'latency_p50_ms': _ms(percentile(self._gaps, 50)),
            'latency_p99_ms': _ms(percentile(self._gaps, 99)),
            'ttft_p50_ms': _ms(percentile(ttft, 50)),
            'occupancy_mean': (sum(self._occupancy) / len(self._occupancy)
                               if self._occupancy else 0.0),
        }


def _ms(x):
    return None if x is None else x * 1e3
