"""Serving metrics: throughput, per-token latency percentiles, occupancy.

Fed by the engine with wall-clock timestamps (injectable clock for
deterministic tests). The latency distribution that matters for serving
is PER-TOKEN (inter-token gap) plus time-to-first-token — a mean hides
exactly the tail that continuous batching is supposed to fix, hence
p50/p99.

Two outputs from the same events:

- ``report()`` — the in-process dict the benches and tests consume
  (unchanged public API);
- the shared monitor registry (paddle_tpu/monitor) — labeled counters /
  gauges / histograms any MetricsServer scrape sees, so a serving
  process is observable from outside without touching the engine.
  Latency targets for dashboards live in docs/observability.md.
"""
import time

from ..monitor import tracing as _tracing
from ..monitor.events import ModelLabeler, TenantLabeler
from ..monitor.registry import default_registry
from ..monitor.telemetry import (record_qos_schema,
                                 record_serving_schema,
                                 record_serving_request_schema,
                                 record_tenant_schema)

__all__ = ['ServingMetrics', 'percentile']


def percentile(values, q):
    """Linear-interpolation percentile (q in [0, 100]) without numpy —
    interpolates between the two closest ranks, matching numpy's default
    ('linear') method, NOT nearest-rank."""
    if not values:
        return None
    xs = sorted(values)
    if len(xs) == 1:
        return xs[0]
    pos = (len(xs) - 1) * (q / 100.0)
    lo = int(pos)
    hi = min(lo + 1, len(xs) - 1)
    frac = pos - lo
    return xs[lo] * (1.0 - frac) + xs[hi] * frac


class ServingMetrics:
    def __init__(self, clock=None, registry=None):
        self._clock = clock or time.monotonic
        self.registry = registry if registry is not None \
            else default_registry()
        self._start = None
        self._end = None
        self._arrival = {}        # rid -> t
        self._first_token = {}    # rid -> t
        self._last_token = {}     # rid -> t of the latest token
        self._gaps = []           # inter-token gaps (incl. arrival->first)
        self._tokens = 0
        self._occupancy = []      # per-step occupied-slot fractions
        r = self.registry
        # per-request families come from the single-source schema table
        # (monitor/telemetry.py SERVING_REQUEST_FAMILIES) — the same
        # table dryrun_registry and the committed baseline register
        req = record_serving_request_schema(r)
        self._m_requests = req['serving_requests_total']
        self._m_admitted = req['serving_requests_admitted_total']
        self._m_retired = req['serving_requests_retired_total']
        self._m_tokens = req['serving_tokens_total']
        self._m_ttft = req['serving_ttft_seconds']
        self._m_gap = req['serving_inter_token_seconds']
        self._m_queue = req['serving_queue_depth']
        self._m_occupancy = req['serving_occupancy']
        self._m_prefill = req['serving_prefill_tokens_total']
        # paged-engine families; registered unconditionally (zeros for
        # the slot engine) so the scrape schema does not depend on which
        # engine a process happens to run
        paged = record_serving_schema(r)
        self._m_pages = paged['serving_kv_pages_in_use']
        self._m_prefix_hits = paged['serving_prefix_cache_hits_total']
        self._m_prefix_misses = paged['serving_prefix_cache_misses_total']
        self._m_spec_proposed = paged['serving_spec_tokens_proposed_total']
        self._m_spec_accepted = paged['serving_spec_tokens_accepted_total']
        self._m_exemplars = _tracing.register_metrics(
            r)['trace_exemplars_total']
        # per-tenant attribution families (bounded cardinality: the
        # labeler interns a capped tenant set + hashed overflow buckets)
        tenant = record_tenant_schema(r)
        self._m_tenant_requests = tenant['tenant_requests_total']
        self._m_tenant_tokens = tenant['tenant_tokens_total']
        self._m_tenant_ttft = tenant['tenant_ttft_seconds']
        self._m_tenant_kv = tenant['tenant_kv_byte_seconds_total']
        # QoS families (preempt/resume counters); the admission-side
        # members of the same table are driven by the gateway — both
        # register the full schema so scrapes agree regardless of layer
        qos = record_qos_schema(r)
        self._m_qos_preempted = qos['qos_preempted_total']
        self._m_qos_resumed = qos['qos_resumed_total']
        self._labeler = TenantLabeler()
        self._model_labeler = ModelLabeler()
        self._prefill_tokens = 0
        self._prefix_hits = 0
        self._prefix_misses = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._pages_in_use = 0

    def now(self):
        return self._clock()

    def on_arrival(self, rid, t=None):
        t = self.now() if t is None else t
        self._arrival[rid] = t
        if self._start is None:
            self._start = t
        self._m_requests.inc()

    def on_admitted(self, rid, t=None):
        self._m_admitted.inc()

    def on_retired(self, rid, t=None):
        self._m_retired.inc()

    def on_queue_depth(self, depth):
        self._m_queue.set(depth)

    def on_tokens(self, rid, count, t=None, trace_id=None):
        """`count` tokens became visible for request rid at time t.

        Decode runs in bursts of K steps per dispatch, so K tokens land
        at once; the burst's gap is spread over its tokens — the honest
        accounting, since a consumer reading the stream experiences the
        burst wait once per K tokens.

        A non-None trace_id rides the TTFT / inter-token histogram
        observations as an exemplar, so an outlier bucket in a scrape
        links back to the trace that produced it.
        """
        if count <= 0:
            return
        t = self.now() if t is None else t
        prev = self._last_token.get(rid)
        if rid not in self._first_token:
            self._first_token[rid] = t
            prev = self._arrival.get(rid, t)
            if rid in self._arrival:
                self._m_ttft.observe(t - self._arrival[rid],
                                     exemplar=trace_id)
                if trace_id is not None:
                    self._m_exemplars.inc()
        if prev is not None:
            gap = (t - prev) / count
            self._gaps.extend([gap] * count)
            for _ in range(count):
                self._m_gap.observe(gap, exemplar=trace_id)
            if trace_id is not None:
                self._m_exemplars.inc(count)
        self._last_token[rid] = t
        self._tokens += count
        self._m_tokens.inc(count)
        self._end = t

    def on_step(self, occupied, num_slots):
        frac = occupied / float(num_slots)
        self._occupancy.append(frac)
        self._m_occupancy.set(frac)

    def on_prefill_tokens(self, count):
        """`count` prompt tokens were actually forwarded through the
        model (prefix-cache hits never reach here — the win IS the
        missing increments)."""
        self._prefill_tokens += count
        self._m_prefill.inc(count)

    def on_pages_in_use(self, pages):
        self._pages_in_use = pages
        self._m_pages.set(pages)

    def on_prefix_lookup(self, hits, misses):
        """Deltas: `hits` full blocks served from the prefix cache,
        `misses` full blocks that had to prefill, since last call."""
        if hits:
            self._prefix_hits += hits
            self._m_prefix_hits.inc(hits)
        if misses:
            self._prefix_misses += misses
            self._m_prefix_misses.inc(misses)

    def tenant_label(self, tenant):
        """The bounded metric label for `tenant` (None -> 'default')."""
        return self._labeler.label(tenant)

    def model_label(self, model):
        """The bounded metric label for `model` (None stays None — a
        request without a named model is unattributed, not 'default')."""
        return self._model_labeler.label(model)

    def on_tenant_tokens(self, label, count):
        """`count` generated tokens attributed to tenant `label` (a
        value from tenant_label, never a raw caller string)."""
        if count > 0:
            self._m_tenant_tokens.labels(label).inc(count)

    def on_tenant_ttft(self, label, seconds):
        self._m_tenant_ttft.labels(label).observe(seconds)

    def on_tenant_retired(self, label, kv_byte_seconds):
        """One request of tenant `label` finished having integrated
        `kv_byte_seconds` of KV-cache residency."""
        self._m_tenant_requests.labels(label).inc()
        if kv_byte_seconds > 0:
            self._m_tenant_kv.labels(label).inc(kv_byte_seconds)

    def on_preempted(self, label):
        """One resident of tenant `label` had its KV pages evicted to
        make room for a higher-priority request."""
        self._m_qos_preempted.labels(label).inc()

    def on_resumed(self, label):
        """One previously preempted request of tenant `label` was
        re-admitted (fast-forwarded through the prefix cache)."""
        self._m_qos_resumed.labels(label).inc()

    def on_spec(self, proposed, accepted):
        """One speculative verify pass: `proposed` draft tokens went in,
        `accepted` matched the model's own picks."""
        self._spec_proposed += proposed
        self._spec_accepted += accepted
        self._m_spec_proposed.inc(proposed)
        if accepted:
            self._m_spec_accepted.inc(accepted)

    def report(self):
        elapsed = ((self._end - self._start)
                   if self._start is not None and self._end is not None
                   else 0.0)
        ttft = [self._first_token[r] - self._arrival[r]
                for r in self._first_token if r in self._arrival]
        lookups = self._prefix_hits + self._prefix_misses
        return {
            'tokens': self._tokens,
            'elapsed_s': elapsed,
            'tok_per_s': self._tokens / elapsed if elapsed > 0 else 0.0,
            'latency_p50_ms': _ms(percentile(self._gaps, 50)),
            'latency_p99_ms': _ms(percentile(self._gaps, 99)),
            'ttft_p50_ms': _ms(percentile(ttft, 50)),
            'occupancy_mean': (sum(self._occupancy) / len(self._occupancy)
                               if self._occupancy else 0.0),
            'prefill_tokens': self._prefill_tokens,
            'pages_in_use': self._pages_in_use,
            'prefix_hits': self._prefix_hits,
            'prefix_misses': self._prefix_misses,
            'prefix_hit_rate': (self._prefix_hits / lookups
                                if lookups else 0.0),
            'spec_proposed': self._spec_proposed,
            'spec_accepted': self._spec_accepted,
            'spec_accept_rate': (self._spec_accepted / self._spec_proposed
                                 if self._spec_proposed else 0.0),
        }


def _ms(x):
    return None if x is None else x * 1e3
