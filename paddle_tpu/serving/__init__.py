"""Continuous-batching serving for decoder-only LMs (Orca/vLLM-style).

The decode matmuls of a cached autoregressive model are batch-starved
when requests are served one at a time: `generate()` runs [1, hidden]
GEMMs no matter how many requests are waiting. Continuous batching keeps
a fixed pool of KV-cache *slots* and admits/retires requests per decode
step, so the compiled step always runs at full slot occupancy with ONE
static shape — no retrace across request churn.

    engine = ContinuousBatchingEngine(model, num_slots=8)
    req = engine.add_request([1, 2, 3], max_new_tokens=16)
    engine.run()                 # or step() / stream(req) / serve threads
    req.tokens                   # generated ids, identical to generate()

Two engines share that skeleton:

- ContinuousBatchingEngine — every slot reserves max_len KV rows;
- PagedContinuousBatchingEngine — block-granular KV pool with prefix
  sharing and optional speculative decoding (paged_engine.py).

Layering: kv_cache.py owns slot/page bookkeeping, scheduler.py owns the
request queue + admission/prefill policy, engine.py + paged_engine.py
own the jitted programs (chunked prefill, fixed-K decode burst, spec
verify) and the thread-safe front door, metrics.py turns step
timestamps into tok/s + latency percentiles. See docs/serving.md.
"""
from .engine import ContinuousBatchingEngine
from .fabric import (PrefixAffinityRouter, ReplicaWorker, SocketReplica,
                     spawn_worker)
from .gateway import (AutoscalePolicy, GatewayRequest, ModelAffinityRouter,
                      QosPolicy, ServingGateway, TenantClass)
from .kv_cache import (PageAllocator, PrefixCache, SlotAllocator,
                       build_paged_pools, build_slot_caches)
from .metrics import ServingMetrics
from .paged_engine import NGramProposer, PagedContinuousBatchingEngine
from .registry import ModelHost, ModelRegistry, RegistryEntry
from .scheduler import PagedScheduler, Request, Scheduler

__all__ = ['ContinuousBatchingEngine', 'PagedContinuousBatchingEngine',
           'SlotAllocator', 'PageAllocator', 'PrefixCache',
           'NGramProposer', 'build_slot_caches', 'build_paged_pools',
           'ServingMetrics', 'Request', 'Scheduler', 'PagedScheduler',
           'ServingGateway', 'GatewayRequest', 'AutoscalePolicy',
           'QosPolicy', 'TenantClass', 'ModelAffinityRouter',
           'ModelRegistry', 'RegistryEntry', 'ModelHost',
           'SocketReplica', 'ReplicaWorker', 'PrefixAffinityRouter',
           'spawn_worker']
