"""Continuous-batching serving for decoder-only LMs (Orca/vLLM-style).

The decode matmuls of a cached autoregressive model are batch-starved
when requests are served one at a time: `generate()` runs [1, hidden]
GEMMs no matter how many requests are waiting. Continuous batching keeps
a fixed pool of KV-cache *slots* and admits/retires requests per decode
step, so the compiled step always runs at full slot occupancy with ONE
static shape — no retrace across request churn.

    engine = ContinuousBatchingEngine(model, num_slots=8)
    req = engine.add_request([1, 2, 3], max_new_tokens=16)
    engine.run()                 # or step() / stream(req) / serve threads
    req.tokens                   # generated ids, identical to generate()

Layering: kv_cache.py owns slot bookkeeping, scheduler.py owns the
request queue + admission/prefill policy, engine.py owns the two jitted
programs (chunked prefill, fixed-K decode burst) and the thread-safe
front door, metrics.py turns step timestamps into tok/s + latency
percentiles. See docs/serving.md.
"""
from .engine import ContinuousBatchingEngine
from .kv_cache import SlotAllocator, build_slot_caches
from .metrics import ServingMetrics
from .scheduler import Request, Scheduler

__all__ = ['ContinuousBatchingEngine', 'SlotAllocator', 'build_slot_caches',
           'ServingMetrics', 'Request', 'Scheduler']
