"""Open-loop trace replay against the real ServingGateway.

`replay` submits a `Trace`'s requests with arrival-time-faithful pacing
(open loop: the clock, not completions, drives submission — slow
servers queue, they don't slow the workload down), reconstructing each
request's prompt tokens, tenant and output budget from the trace
columns. The gateway emits the canonical 18-field wide events exactly
as production traffic would, so a replayed run and a simulated run of
the SAME trace are directly comparable — that comparison is the
calibration gate (simulator.ttft_divergence via
tools/capacity_report.py).

`measure` wraps the full calibration recipe: install a fresh
RequestLog, build + warm a gateway, replay, and hand back the run's
wide events (sliced out of the log with the since_ts filter so warmup
and earlier traffic never pollute the fit) ready for
ServiceModel.from_events.

Serving imports happen inside functions: `paddle_tpu.capacity` stays
importable in stdlib+numpy contexts (tools/, monitor-only tests), and
pulls jax only when a real gateway is actually driven.
"""
import time

__all__ = ['ReplayResult', 'replay', 'measure']


class ReplayResult:
    """What one open-loop replay did, in host wall-time terms."""

    def __init__(self, requests, completed, wall_s, tokens, max_lag_s,
                 handles=()):
        self.requests = requests
        self.completed = completed   # finished within the wait budget
        self.wall_s = wall_s
        self.tokens = tokens
        self.max_lag_s = max_lag_s   # worst submit-behind-schedule, s
        self.handles = list(handles)  # GatewayRequest per trace index

    @property
    def tokens_per_sec(self):
        return self.tokens / self.wall_s if self.wall_s > 0 else 0.0

    @property
    def completed_ratio(self):
        return self.completed / self.requests if self.requests else 1.0

    def to_dict(self):
        return {'requests': self.requests, 'completed': self.completed,
                'completed_ratio': self.completed_ratio,
                'wall_s': self.wall_s,
                'tokens': self.tokens, 'max_lag_s': self.max_lag_s,
                'tokens_per_sec': self.tokens_per_sec}


def replay(gateway, trace, speed=1.0, max_new_tokens=None, seed=0,
           timeout=600.0, before_submit=None, registry=None):
    """Replay `trace` through a start()ed gateway; returns ReplayResult.

    speed: time compression — 2.0 replays a trace twice as fast as
    recorded (arrival gaps divide by `speed`). max_new_tokens overrides
    the trace's per-request output budgets (benches cap decode work).
    seed: sampling seed for every request — engines are deterministic
    per (prompt, sampling, seed), which is what makes failover
    exact-token and replays reproducible. before_submit(i) runs just
    before request i is submitted — the hook bench_serving_gateway uses
    to kill a replica mid-burst at the same point the retired inline
    loop did. Requests still unfinished after `timeout` seconds (each)
    are left behind and counted out of `completed` — the chaos bench's
    completed_ratio, not an exception.
    """
    if speed <= 0:
        raise ValueError('speed must be positive')
    prompts = trace.prompts()
    tenants = trace.tenants()
    models = trace.models() if hasattr(trace, 'models') else None
    new_tokens = trace.new_tokens.tolist()
    arrival = trace.arrival.tolist()

    fams = None
    if registry is not None:
        from ..monitor.telemetry import record_capacity_schema
        fams = record_capacity_schema(registry)

    t0 = time.monotonic()
    max_lag = 0.0
    handles = []
    for i in range(len(trace)):
        target = t0 + arrival[i] / speed
        now = time.monotonic()
        if target > now:
            time.sleep(target - now)
        else:
            max_lag = max(max_lag, now - target)
        if before_submit is not None:
            before_submit(i)
        mnt = int(max_new_tokens if max_new_tokens is not None
                  else new_tokens[i])
        extra = {} if models is None else {'model': models[i]}
        handles.append(gateway.submit(prompts[i], max_new_tokens=mnt,
                                      tenant=tenants[i], seed=seed,
                                      **extra))
    for h in handles:
        h.wait(timeout)
    wall = time.monotonic() - t0
    tokens = sum(len(h.tokens) for h in handles)
    # done-with-error handles are shed/failed requests (an admission
    # reject finishes instantly) — they must not inflate completed
    completed = sum(1 for h in handles if h.done and h.error is None)
    if fams is not None:
        fams['capacity_requests_replayed_total'].inc(len(handles))
        fams['capacity_replay_runs_total'].inc()
        fams['capacity_replay_lag_seconds'].observe(max_lag)
    return ReplayResult(len(handles), completed, wall, tokens, max_lag,
                        handles=handles)


def measure(engine_factory, trace, replicas=1, speed=1.0,
            max_new_tokens=None, warmup_prompt=None, timeout=600.0,
            registry=None, log_capacity=None):
    """Calibration run: replay `trace` through a fresh in-proc gateway
    and return (events, ReplayResult) where `events` are the replay's
    own wide events — warmup excluded via the RequestLog since_ts
    filter. Feed the events straight to ServiceModel.from_events.

    engine_factory: zero-arg callable building one engine replica (the
    same factory ServingGateway takes). warmup_prompt: token list used
    for one blocking generate() before the clock starts, so compile
    time never lands in the measured TTFTs (default: the trace's first
    prompt).
    """
    from ..monitor import events as _events
    from ..serving.gateway.gateway import ServingGateway

    log = _events.RequestLog(capacity=max(2048, 4 * len(trace))
                             if log_capacity is None else log_capacity)
    prev = _events.default_request_log()
    _events.set_default_request_log(log)
    try:
        gw = ServingGateway(engine_factory, replicas=replicas,
                            registry=registry)
        warm = warmup_prompt if warmup_prompt is not None \
            else trace.prompts()[0]
        gw.generate([warm], max_new_tokens=4, tenant='warmup')
        gw.start()
        try:
            mark = time.monotonic()
            result = replay(gw, trace, speed=speed,
                            max_new_tokens=max_new_tokens,
                            timeout=timeout, registry=registry)
        finally:
            gw.shutdown()
        events = log.events(since_ts=mark)
        return events, result
    finally:
        _events.set_default_request_log(prev)
