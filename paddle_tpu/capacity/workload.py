"""Workload language: seeded, deterministic request traces.

A `WorkloadSpec` is a declarative, JSON-serializable description of a
serving workload — arrival process (Poisson, diurnal cycle, correlated
bursts, all-at-zero burst), prompt/output length distributions (fixed,
ladder, lognormal, Zipf), tenant mix (round-robin, weighted, Zipf skew)
and shared-prefix structure. `generate()` turns a spec into a `Trace`:
a columnar, wide-event-schema-aligned request list (arrival_t, tenant,
prompt_tokens, output_tokens, prefix group) that the replay harness
feeds to the real gateway and the fleet simulator consumes directly.

Determinism is the contract: the same (spec, seed) produces a
byte-identical trace — arrivals, lengths, tenants AND prompt token ids
— so a bench rung, a replay and a simulation all see the same workload,
and the spec's canonical hash recorded in a bench row names the trace
exactly. The RNG stream discipline mirrors the historical bench_extra
generators bit-for-bit: arrivals come from `RandomState(seed)`
exponential draws (the old `_poisson_arrivals`), prompt tokens from a
second `RandomState(seed)` consumed strictly in request order (shared
prefixes drawn at first use), so stored bench bests keyed to the old
hand-rolled traces stay comparable.

Traces round-trip through JSONL (`Trace.to_jsonl`/`from_jsonl`), and
recorded wide events — a `RequestLog` sink or dryrun `request_event`
lines — load into the same in-memory form via `trace_from_events` /
`load_trace`, which is how production traffic becomes a replayable,
simulatable workload. Prompt token ids are materialized lazily
(`Trace.prompts()`): a million-request trace for the simulator never
allocates them.
"""
import hashlib
import json
import math
import zlib

import numpy as np

__all__ = ['WorkloadSpec', 'Trace', 'generate', 'trace_from_events',
           'load_trace', 'poisson_arrivals']

_TWO_PI = 2.0 * math.pi


def _stream_seed(seed, name):
    """Seed for an auxiliary RNG stream. The 'arrival' and 'prompt'
    streams use `seed` verbatim (bench_extra parity); everything else
    derives a stable per-purpose stream so adding a knob never shifts
    the draws of an existing one."""
    return (int(seed) ^ zlib.crc32(name.encode('utf-8'))) & 0x7FFFFFFF


def poisson_arrivals(n, mean_gap_s, seed=0):
    """Cumulative Poisson-process arrival offsets (seconds), seeded —
    bit-identical to the retired bench_extra._poisson_arrivals."""
    gaps = np.random.RandomState(seed).exponential(mean_gap_s, size=n)
    return np.concatenate([[0.0], np.cumsum(gaps)[:-1]])


def _canon(obj):
    """JSON-safe canonical form: tuples -> lists, numpy scalars -> py."""
    if isinstance(obj, dict):
        return {str(k): _canon(v) for k, v in sorted(obj.items())}
    if isinstance(obj, (list, tuple)):
        return [_canon(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    return obj


class WorkloadSpec:
    """Declarative workload description. Grammar (all dicts JSON-safe;
    docs/capacity.md spells out every knob):

      arrival: {'process': 'poisson', 'mean_gap_s': g,
                ['burst': {'prob': p, 'size': m, 'jitter_s': j}]}
             | {'process': 'diurnal', 'mean_gap_s': g, 'period_s': T,
                'peak_to_trough': r}
             | {'process': 'burst'}                # everything at t=0
      lengths / output:
               {'dist': 'fixed', 'len': L}
             | {'dist': 'ladder', 'lens': [...]}   # round-robin ladder
             | {'dist': 'lognormal', 'median': M, 'sigma': s,
                'min': lo, 'max': hi}
             | {'dist': 'zipf', 'a': a, 'min': lo, 'max': hi}
      tenants: {'mode': 'round_robin' | 'weighted',
                'tenants': [{'name': n, ['weight': w],
                             ['lengths': {...}]}, ...]}
             | {'mode': 'zipf', 'count': K, 'a': a}
      models:  {'mode': 'zipf', 'count': K, 'a': a}   # 'model_%03d' names
             | {'mode': 'round_robin' | 'weighted',
                'models': [{'name': n, ['weight': w]}, ...]}
      prefix:  {'len': P, 'groups': G, 'prob': p}  # shared-prefix heads

    `lengths` draws the TAIL length when a request carries a shared
    prefix (prompt_tokens = prefix len + tail), matching the paged
    bench's shared-system-prompt workload.
    """

    def __init__(self, requests, seed=0, vocab_size=512, arrival=None,
                 lengths=None, output=None, tenants=None, prefix=None,
                 models=None):
        if requests < 1:
            raise ValueError('requests must be >= 1')
        self.requests = int(requests)
        self.seed = int(seed)
        self.vocab_size = int(vocab_size)
        self.arrival = dict(arrival or {'process': 'poisson',
                                        'mean_gap_s': 0.01})
        self.lengths = dict(lengths or {'dist': 'fixed', 'len': 16})
        self.output = dict(output or {'dist': 'fixed', 'len': 32})
        self.tenants = dict(tenants) if tenants else None
        self.prefix = dict(prefix) if prefix else None
        self.models = dict(models) if models else None

    def to_dict(self):
        d = {'requests': self.requests, 'seed': self.seed,
             'vocab_size': self.vocab_size,
             'arrival': self.arrival, 'lengths': self.lengths,
             'output': self.output, 'tenants': self.tenants,
             'prefix': self.prefix}
        # only when set: a single-model spec must hash identically to
        # specs serialized before the models knob existed, or every
        # stored bench best would silently orphan
        if self.models:
            d['models'] = self.models
        return _canon(d)

    @classmethod
    def from_dict(cls, d):
        return cls(requests=d['requests'], seed=d.get('seed', 0),
                   vocab_size=d.get('vocab_size', 512),
                   arrival=d.get('arrival'), lengths=d.get('lengths'),
                   output=d.get('output'), tenants=d.get('tenants'),
                   prefix=d.get('prefix'), models=d.get('models'))

    def canonical_json(self):
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(',', ':'))

    @property
    def hash(self):
        """12-hex content hash naming the trace exactly — the value
        bench rows record as `workload_spec`."""
        return hashlib.sha256(
            self.canonical_json().encode('utf-8')).hexdigest()[:12]

    def generate(self):
        return generate(self)

    def __repr__(self):
        return 'WorkloadSpec(%s)' % self.canonical_json()


# ---------------------------------------------------------------------------
# generation


def _gen_arrivals(spec):
    n, cfg = spec.requests, spec.arrival
    proc = cfg.get('process', 'poisson')
    if proc == 'burst':
        return np.zeros(n, dtype=np.float64)
    if proc == 'poisson':
        arr = poisson_arrivals(n, float(cfg['mean_gap_s']), spec.seed)
        burst = cfg.get('burst')
        if burst:
            # correlated bursts: selected requests re-anchor onto the
            # most recent organic arrival (plus jitter) — the thundering
            # herd shape a mean-rate Poisson process can never produce
            rng = np.random.RandomState(_stream_seed(spec.seed, 'burst'))
            mask = rng.rand(n) < float(burst.get('prob', 0.0))
            mask[0] = False
            anchor = np.maximum.accumulate(
                np.where(~mask, np.arange(n), 0))
            jitter = float(burst.get('jitter_s', 0.0)) * rng.rand(n)
            arr = np.where(mask, arr[anchor] + jitter, arr)
            arr = np.sort(arr, kind='stable')
        return arr
    if proc == 'diurnal':
        # rate(t) = base * (1 + amp*sin(2*pi*t/T)); amp chosen so
        # peak/trough rate ratio equals the requested value. Sequential
        # because each gap depends on the modulated rate at its start.
        mean_gap = float(cfg['mean_gap_s'])
        period = float(cfg['period_s'])
        ratio = float(cfg.get('peak_to_trough', 4.0))
        amp = (ratio - 1.0) / (ratio + 1.0)
        base_rate = 1.0 / mean_gap
        draws = np.random.RandomState(spec.seed).exponential(1.0, size=n)
        arr = np.empty(n, dtype=np.float64)
        t = 0.0
        for i in range(n):
            arr[i] = t
            rate = base_rate * (1.0 + amp * math.sin(_TWO_PI * t / period))
            t += draws[i] / max(rate, 1e-12 * base_rate)
        return arr
    raise ValueError('unknown arrival process %r' % (proc,))


def _gen_lengths(cfg, n, rng, counters=None, key=None):
    """Length array for one distribution. `ladder`/`fixed` consume no
    RNG (bench parity); heavy-tailed dists draw from `rng`."""
    dist = cfg.get('dist', 'fixed')
    if dist == 'fixed':
        return np.full(n, int(cfg['len']), dtype=np.int64)
    if dist == 'ladder':
        lens = np.asarray([int(x) for x in cfg['lens']], dtype=np.int64)
        if counters is None:
            return lens[np.arange(n) % len(lens)]
        # per-tenant ladder position: requests of the same tenant walk
        # the ladder in their own submission order
        out = np.empty(n, dtype=np.int64)
        for j in range(n):
            c = counters.get(key, 0)
            out[j] = lens[c % len(lens)]
            counters[key] = c + 1
        return out
    lo = int(cfg.get('min', 1))
    hi = cfg.get('max')
    if dist == 'lognormal':
        vals = rng.lognormal(math.log(float(cfg['median'])),
                             float(cfg.get('sigma', 0.6)), size=n)
        out = np.rint(vals).astype(np.int64)
    elif dist == 'zipf':
        out = rng.zipf(float(cfg.get('a', 1.3)), size=n) + lo - 1
    else:
        raise ValueError('unknown length dist %r' % (dist,))
    out = np.maximum(out, lo)
    if hi is not None:
        out = np.minimum(out, int(hi))
    return out


def _gen_tenants(spec):
    """(tenant_names tuple, tenant_id array, per-tenant length cfgs)."""
    n, cfg = spec.requests, spec.tenants
    if not cfg:
        return (None,), np.zeros(n, dtype=np.int64), {}
    mode = cfg.get('mode', 'round_robin')
    if mode == 'zipf':
        count = int(cfg['count'])
        names = tuple('tenant_%03d' % i for i in range(count))
        rng = np.random.RandomState(_stream_seed(spec.seed, 'tenant'))
        tid = np.minimum(rng.zipf(float(cfg.get('a', 1.2)), size=n) - 1,
                         count - 1).astype(np.int64)
        return names, tid, {}
    entries = list(cfg['tenants'])
    names = tuple(e['name'] for e in entries)
    per_len = {i: e['lengths'] for i, e in enumerate(entries)
               if e.get('lengths')}
    if mode == 'round_robin':
        tid = np.arange(n, dtype=np.int64) % len(names)
    elif mode == 'weighted':
        w = np.asarray([float(e.get('weight', 1.0)) for e in entries])
        rng = np.random.RandomState(_stream_seed(spec.seed, 'tenant'))
        tid = rng.choice(len(names), size=n, p=w / w.sum())
        tid = tid.astype(np.int64)
    else:
        raise ValueError('unknown tenant mode %r' % (mode,))
    return names, tid, per_len


def _gen_models(spec):
    """(model_names tuple or None, model_id array). Own RNG stream
    ('model') so adding a model mix never shifts tenant/length draws —
    the same discipline as every other knob."""
    n, cfg = spec.requests, getattr(spec, 'models', None)
    if not cfg:
        return None, np.zeros(n, dtype=np.int64)
    mode = cfg.get('mode', 'zipf')
    if mode == 'zipf':
        count = int(cfg['count'])
        names = tuple('model_%03d' % i for i in range(count))
        rng = np.random.RandomState(_stream_seed(spec.seed, 'model'))
        mid = np.minimum(rng.zipf(float(cfg.get('a', 1.2)), size=n) - 1,
                         count - 1).astype(np.int64)
        return names, mid
    entries = list(cfg['models'])
    names = tuple(e['name'] for e in entries)
    if mode == 'round_robin':
        mid = np.arange(n, dtype=np.int64) % len(names)
    elif mode == 'weighted':
        w = np.asarray([float(e.get('weight', 1.0)) for e in entries])
        rng = np.random.RandomState(_stream_seed(spec.seed, 'model'))
        mid = rng.choice(len(names), size=n, p=w / w.sum())
        mid = mid.astype(np.int64)
    else:
        raise ValueError('unknown model mode %r' % (mode,))
    return names, mid


def generate(spec):
    """Spec -> Trace. Columnar and prompt-free: generating a
    million-request trace for the simulator takes well under a second
    and never allocates token arrays."""
    n = spec.requests
    arrival = _gen_arrivals(spec)
    names, tid, per_len = _gen_tenants(spec)
    model_names, mid = _gen_models(spec)

    len_rng = np.random.RandomState(_stream_seed(spec.seed, 'lengths'))
    if per_len:
        tails = np.empty(n, dtype=np.int64)
        counters = {}
        for t in range(len(names)):
            idx = np.nonzero(tid == t)[0]
            if not len(idx):
                continue
            cfg = per_len.get(t, spec.lengths)
            tails[idx] = _gen_lengths(cfg, len(idx), len_rng,
                                      counters=counters, key=t)
    else:
        tails = _gen_lengths(spec.lengths, n, len_rng)

    out_rng = np.random.RandomState(_stream_seed(spec.seed, 'output'))
    new_tokens = np.maximum(_gen_lengths(spec.output, n, out_rng), 1)

    group = np.full(n, -1, dtype=np.int64)
    prefix_len = np.zeros(n, dtype=np.int64)
    pfx = spec.prefix
    if pfx and int(pfx.get('len', 0)) > 0:
        groups = int(pfx.get('groups', 1))
        prob = float(pfx.get('prob', 1.0))
        if groups == 1 and prob >= 1.0:
            group[:] = 0              # no RNG: bench paged-rung parity
        else:
            rng = np.random.RandomState(_stream_seed(spec.seed, 'prefix'))
            hit = rng.rand(n) < prob
            group = np.where(hit, rng.randint(0, groups, size=n), -1)
        prefix_len = np.where(group >= 0, int(pfx['len']), 0)

    order = np.argsort(arrival, kind='stable')
    return Trace(arrival=arrival[order],
                 prompt_len=(tails + prefix_len)[order],
                 new_tokens=new_tokens[order], tenant_id=tid[order],
                 tenant_names=names, prefix_group=group[order],
                 prefix_len=prefix_len[order],
                 model_id=mid[order], model_names=model_names,
                 meta={'spec': spec.to_dict(), 'spec_hash': spec.hash,
                       'vocab_size': spec.vocab_size, 'source': 'spec'})


# ---------------------------------------------------------------------------
# the Trace form


class Trace:
    """Columnar request trace, sorted by arrival time. Arrival times are
    relative seconds (t=0 is the first request). prompt_len is the TOTAL
    prompt length (shared prefix included)."""

    def __init__(self, arrival, prompt_len, new_tokens, tenant_id,
                 tenant_names, prefix_group, prefix_len, meta=None,
                 model_id=None, model_names=None):
        self.arrival = np.asarray(arrival, dtype=np.float64)
        self.prompt_len = np.asarray(prompt_len, dtype=np.int64)
        self.new_tokens = np.asarray(new_tokens, dtype=np.int64)
        self.tenant_id = np.asarray(tenant_id, dtype=np.int64)
        self.tenant_names = tuple(tenant_names)
        self.prefix_group = np.asarray(prefix_group, dtype=np.int64)
        self.prefix_len = np.asarray(prefix_len, dtype=np.int64)
        # model_names None == single-model trace (every request targets
        # the deployment default); model_id is then all zeros
        self.model_names = tuple(model_names) if model_names else None
        self.model_id = (np.asarray(model_id, dtype=np.int64)
                         if model_id is not None
                         else np.zeros(len(self.arrival), dtype=np.int64))
        self.meta = dict(meta or {})
        self._prompts = None

    def __len__(self):
        return int(len(self.arrival))

    @property
    def duration_s(self):
        return float(self.arrival[-1]) if len(self.arrival) else 0.0

    @property
    def spec_hash(self):
        return self.meta.get('spec_hash')

    def arrivals(self):
        return [float(t) for t in self.arrival]

    def tenants(self):
        names = self.tenant_names
        return [names[t] for t in self.tenant_id]

    def tenant_mix(self):
        mix = {}
        for t in self.tenant_id:
            name = self.tenant_names[t]
            mix[name] = mix.get(name, 0) + 1
        return mix

    def models(self):
        """Per-request model names, or None for a single-model trace."""
        if self.model_names is None:
            return None
        names = self.model_names
        return [names[m] for m in self.model_id]

    def model_mix(self):
        if self.model_names is None:
            return {}
        mix = {}
        for m in self.model_id:
            name = self.model_names[m]
            mix[name] = mix.get(name, 0) + 1
        return mix

    def prompts(self, vocab_size=None):
        """Materialize prompt token ids (cached). Drawn strictly in
        request order from RandomState(seed), shared prefixes at first
        use — the exact draw order of the historical bench generators,
        so replay prompts match the retired hand-rolled ones token for
        token."""
        if self._prompts is not None:
            return self._prompts
        vocab = int(vocab_size or self.meta.get('vocab_size') or 512)
        seed = int(self.meta.get('spec', {}).get('seed', 0))
        rng = np.random.RandomState(seed)
        heads = {}
        prompts = []
        for i in range(len(self)):
            g = int(self.prefix_group[i])
            head = []
            if g >= 0:
                if g not in heads:
                    heads[g] = [int(t) for t in rng.randint(
                        0, vocab, int(self.prefix_len[i]))]
                head = heads[g]
            tail_n = int(self.prompt_len[i]) - len(head)
            prompts.append(head + [int(t) for t in
                                   rng.randint(0, vocab, tail_n)])
        self._prompts = prompts
        return prompts

    # -- serialization ------------------------------------------------------

    def to_jsonl(self):
        """Canonical JSONL: one meta line, then one wide-event-named
        line per request. Byte-deterministic for a given trace (the
        determinism the tests pin)."""
        lines = [json.dumps({'trace_meta': _canon(self.meta)},
                            sort_keys=True, separators=(',', ':'))]
        names = self.tenant_names
        for i in range(len(self)):
            row = {'request_id': i, 'arrival_t': float(self.arrival[i]),
                   'tenant': names[self.tenant_id[i]],
                   'prompt_tokens': int(self.prompt_len[i]),
                   'output_tokens': int(self.new_tokens[i]),
                   'prefix_group': int(self.prefix_group[i]),
                   'prefix_len': int(self.prefix_len[i])}
            # only multi-model traces carry the column — single-model
            # JSONL stays byte-identical to pre-models output
            if self.model_names is not None:
                row['model'] = self.model_names[self.model_id[i]]
            lines.append(json.dumps(row, sort_keys=True,
                                    separators=(',', ':')))
        return '\n'.join(lines) + '\n'

    @classmethod
    def from_jsonl(cls, text):
        meta, rows = {}, []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if not isinstance(obj, dict):
                continue
            if 'trace_meta' in obj:
                meta = obj['trace_meta'] or {}
            elif 'arrival_t' in obj:
                rows.append(obj)
        return _rows_to_trace(rows, meta)


def _rows_to_trace(rows, meta):
    if not rows:
        raise ValueError('no trace rows found')
    rows.sort(key=lambda r: (float(r.get('arrival_t') or 0.0)))
    t0 = float(rows[0].get('arrival_t') or 0.0)
    names, name_idx = [], {}
    mnames, mname_idx = [], {}
    multi_model = any(r.get('model') is not None for r in rows)
    tid = np.empty(len(rows), dtype=np.int64)
    mid = np.zeros(len(rows), dtype=np.int64)
    arrival = np.empty(len(rows), dtype=np.float64)
    plen = np.empty(len(rows), dtype=np.int64)
    ntok = np.empty(len(rows), dtype=np.int64)
    group = np.empty(len(rows), dtype=np.int64)
    pfx = np.empty(len(rows), dtype=np.int64)
    for i, r in enumerate(rows):
        t = r.get('tenant')
        if t not in name_idx:
            name_idx[t] = len(names)
            names.append(t)
        tid[i] = name_idx[t]
        if multi_model:
            m = r.get('model')
            if m not in mname_idx:
                mname_idx[m] = len(mnames)
                mnames.append(m)
            mid[i] = mname_idx[m]
        arrival[i] = float(r.get('arrival_t') or 0.0) - t0
        plen[i] = max(1, int(r.get('prompt_tokens') or 1))
        ntok[i] = max(1, int(r.get('output_tokens') or 1))
        group[i] = int(r.get('prefix_group', -1))
        pfx[i] = int(r.get('prefix_len', 0) or 0)
    return Trace(arrival=arrival, prompt_len=plen, new_tokens=ntok,
                 tenant_id=tid, tenant_names=tuple(names),
                 prefix_group=group, prefix_len=pfx,
                 model_id=mid if multi_model else None,
                 model_names=tuple(mnames) if multi_model else None,
                 meta=meta)


def trace_from_events(events, meta=None):
    """Recorded wide events (RequestLog.events() dicts / sink lines) ->
    Trace. Events without an arrival_t are skipped (they never entered
    the system); arrivals rebase to t=0. Prefix-group identity is not
    recoverable from a recorded event (only the hit count is), so
    loaded traces carry no shared-prefix structure. Time-range slicing
    belongs upstream: RequestLog.events(since_ts=..., until_ts=...)."""
    rows = [e for e in events
            if isinstance(e, dict) and e.get('arrival_t') is not None]
    if not rows:
        raise ValueError('no wide events with arrival_t')
    m = dict(meta or {})
    m.setdefault('source', 'events')
    return _rows_to_trace(
        [{'arrival_t': e['arrival_t'], 'tenant': e.get('tenant'),
          'model': e.get('model'),
          'prompt_tokens': e.get('prompt_tokens'),
          'output_tokens': e.get('output_tokens')} for e in rows], m)


def load_trace(path=None, text=None):
    """Trace from a file or captured text: accepts trace JSONL
    (to_jsonl output), a RequestLog JSONL sink, or dryrun captures with
    `request_event(N)[tag]: {json}` lines — whichever the content turns
    out to be."""
    if path is not None:
        with open(path, errors='replace') as f:
            text = f.read()
    if not text:
        raise ValueError('load_trace needs a path or text')
    from ..monitor.events import parse_event_lines
    embedded = [ev for _, ev in parse_event_lines(text)]
    if embedded:
        return trace_from_events(embedded)
    rows, meta, saw_event = [], {}, False
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if not isinstance(obj, dict):
            continue
        if 'trace_meta' in obj:
            meta = obj['trace_meta'] or {}
        elif 'request_id' in obj and 'finish_t' in obj:
            saw_event = True
            rows.append(obj)
        elif 'arrival_t' in obj:
            rows.append(obj)
    if saw_event:
        return trace_from_events(rows, meta=meta)
    return _rows_to_trace(rows, meta)
