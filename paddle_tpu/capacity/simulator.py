"""Discrete-event fleet simulator: gateway + N replicas in fake time.

Answers "how many replicas for this workload within this TTFT SLO?"
without hardware. Each simulated replica reproduces the engine's
scheduling shape (scheduler.py): slot admission, one prefill chunk per
resident prompt per step, one shared decode burst per step for every
slot whose prompt is consumed — so a request occupies a slot for
ceil(prompt/chunk) + ceil(new_tokens/block) steps, and step WALL TIME is
the two-parameter service model below. The simulator advances replicas
step-by-step in simulated seconds, so queueing, slot occupancy,
prefix-cache hits, replica failover and the PR 8 autoscaler policy all
emerge from the same mechanics the real gateway has.

Service model (per replica):

    step_s = prefilling_slots * prefill_chunk_s
             + (decode_burst_s if any slot is decoding)

calibrated three ways: `ServiceModel.from_events` fits the two
parameters from a short measured run's wide events (the calibration
gate's path), `from_bench_rows` backs them out of stored bench rows,
and `from_roofline` parameterizes them analytically from the PR 9 cost
model (monitor/perf/costmodel.py PEAKS).

Validation is distributional: `ttft_divergence` / `compare_events`
report the K-S statistic and p50/p99 relative error between simulated
and real TTFTs of the SAME trace; tools/capacity_report.py gates on
them. `sweep_replicas` then runs the calibrated model across replica
counts — a million-request sweep completes in seconds on CPU because
the per-step inner loop is O(num_slots) plain-int work and traces stay
columnar (no prompts, no per-token events).
"""
import heapq

import numpy as np

__all__ = ['ServiceModel', 'SimResult', 'simulate', 'sweep_replicas',
           'sweep_qos', 'ks_statistic', 'ttft_divergence',
           'compare_events', 'ttfts_of_events']


class ServiceModel:
    """Two-parameter wall-time model of one replica's engine step."""

    def __init__(self, prefill_chunk_s, decode_burst_s, prefill_chunk=32,
                 decode_block=8, num_slots=8):
        if prefill_chunk_s < 0 or decode_burst_s <= 0:
            raise ValueError('service times must be positive')
        self.prefill_chunk_s = float(prefill_chunk_s)
        self.decode_burst_s = float(decode_burst_s)
        self.prefill_chunk = int(prefill_chunk)
        self.decode_block = int(decode_block)
        self.num_slots = int(num_slots)

    def to_dict(self):
        return {'prefill_chunk_s': self.prefill_chunk_s,
                'decode_burst_s': self.decode_burst_s,
                'prefill_chunk': self.prefill_chunk,
                'decode_block': self.decode_block,
                'num_slots': self.num_slots}

    @classmethod
    def from_events(cls, events, prefill_chunk=32, decode_block=8,
                    num_slots=8, trace=None, replicas=1,
                    router='least_loaded'):
        """Calibrate from measured wide events (a short replay through
        the real gateway). Decode: the engine delivers decode_block
        tokens per burst, and the first token is stamped at the end of
        the FIRST burst — so first_token->finish spans
        ceil(out/block) - 1 bursts:
        decode_burst_s = median((finish-first) / (ceil(out/block)-1)).

        Prefill: first_token lands one chunked prefill plus one burst
        after admission, but under load (ft - admit) also contains the
        co-resident prefill work the SIMULATOR will model again — so
        the direct median((first-admit-burst)/chunks) overestimates the
        solo chunk cost by the contention factor and the sim
        double-counts it. When the measured run's `trace` is given, the
        chunk cost is instead found by bisection: the value whose
        simulated p50 TTFT (replicas/router as measured) matches the
        measured p50. The gate still validates honestly — K-S and p99
        probe the whole distribution, not the matched median."""
        dec, pre = [], []
        for e in events:
            ft, fin = e.get('first_token_t'), e.get('finish_t')
            out = int(e.get('output_tokens') or 0)
            bursts = -(-out // decode_block) - 1
            if ft is not None and fin is not None and bursts >= 1:
                dec.append((fin - ft) / bursts)
        if not dec:
            raise ValueError('no events with decode timing to calibrate '
                             'from (need output_tokens > decode_block)')
        burst_s = max(float(np.median(dec)), 1e-9)
        for e in events:
            ad, ft = e.get('admit_t'), e.get('first_token_t')
            chunks = int(e.get('prefill_chunks') or 0)
            if ad is not None and ft is not None and chunks > 0:
                pre.append(max(0.0, (ft - ad) - burst_s) / chunks)
        chunk_s = float(np.median(pre)) if pre else burst_s
        if trace is not None:
            target = float(np.median(ttfts_of_events(events)))
            lo, hi = 0.0, max(chunk_s, burst_s, 1e-6) * 2.0
            for _ in range(20):
                mid = (lo + hi) / 2.0
                m = cls(mid, burst_s, prefill_chunk=prefill_chunk,
                        decode_block=decode_block, num_slots=num_slots)
                p50 = simulate(trace, m, replicas=replicas,
                               router=router,
                               advance_every=1).ttft_percentiles(
                                   (50,))[50]
                if p50 < target:
                    lo = mid
                else:
                    hi = mid
            chunk_s = (lo + hi) / 2.0
        return cls(chunk_s, burst_s, prefill_chunk=prefill_chunk,
                   decode_block=decode_block, num_slots=num_slots)

    @classmethod
    def from_bench_rows(cls, rows, metric='serving_cb_tokens_per_sec',
                        prefill_chunk=32, decode_block=8, num_slots=None):
        """Back the burst pace out of a stored serving bench row:
        saturated continuous batching delivers slots*block tokens per
        burst, so burst_s = slots*block / tokens_per_sec. Coarse (the
        row's tok/s includes prefill overhead) — prefer from_events when
        a measured run is available."""
        best = None
        for r in rows:
            if (r.get('metric') == metric
                    and isinstance(r.get('value'), (int, float))
                    and r['value'] > 0):
                if best is None or r['value'] > best['value']:
                    best = r
        if best is None:
            raise ValueError('no usable %r row' % (metric,))
        slots = int(num_slots or best.get('num_slots') or 8)
        burst_s = slots * decode_block / float(best['value'])
        return cls(burst_s, burst_s, prefill_chunk=prefill_chunk,
                   decode_block=decode_block, num_slots=slots)

    @classmethod
    def from_roofline(cls, param_count, param_bytes, platform=None,
                      prefill_chunk=32, decode_block=8, num_slots=8):
        """Analytic floor from the PR 9 cost model: one decode token
        step streams the weights once and does 2*params*slots FLOPs; a
        prefill chunk does 2*params*chunk FLOPs over the same weights."""
        from ..monitor.perf.costmodel import roofline
        tok = roofline(2.0 * param_count * num_slots, param_bytes,
                       platform=platform)['ideal_step_s']
        chunk = roofline(2.0 * param_count * prefill_chunk, param_bytes,
                         platform=platform)['ideal_step_s']
        return cls(chunk, tok * decode_block, prefill_chunk=prefill_chunk,
                   decode_block=decode_block, num_slots=num_slots)


class _Replica:
    """One simulated engine: local clock + FIFO queue + slot table.
    Advanced lazily to the fleet's routing time; each iteration of
    `advance` is ONE engine step."""

    __slots__ = ('t', 'queue', 'active', 'slots', 'seen_prefix', 'alive',
                 'draining', 'outstanding', 'busy_slot_s', 'ready')

    def __init__(self, t0, slots):
        self.t = float(t0)
        self.queue = []          # (req_idx, arrival_t) FIFO (index head)
        self.active = []         # [req_idx, chunks_left, tokens_left]
        self.slots = slots
        self.seen_prefix = set()
        self.alive = True
        self.draining = False
        self.outstanding = 0
        self.busy_slot_s = 0.0
        self.ready = []          # QoS staging heap: (-priority, req_idx)


class SimResult:
    """Columnar per-request outcomes of one simulation.

    `outcome` / `priority` / `reject_reason` columns are present only
    for QoS runs (simulate(..., qos=...)); without a policy they are
    None and every request is implicitly admitted ('ok')."""

    def __init__(self, trace, admit, first, finish, failovers, replica_of,
                 prefix_hits, chunks, replica_timeline, wall_s,
                 outcome=None, priority=None, reject_reason=None):
        self.trace = trace
        self.admit = admit
        self.first = first
        self.finish = finish
        self.failovers = failovers
        self.replica_of = replica_of
        self.prefix_hits = prefix_hits
        self.chunks = chunks
        self.replica_timeline = replica_timeline   # [(sim_t, n_alive)]
        self.wall_s = wall_s                       # host seconds to run
        self.outcome = outcome                     # 'ok' | 'rejected'
        self.priority = priority
        self.reject_reason = reject_reason

    def __len__(self):
        return len(self.trace)

    @property
    def max_replicas(self):
        return max(n for _, n in self.replica_timeline)

    def ok_mask(self):
        """Admitted requests — the ones latency statistics make sense
        for (a shed request never produced a token)."""
        if self.outcome is None:
            return np.ones(len(self), dtype=bool)
        return self.outcome == 'ok'

    def ttft(self):
        return (self.first - self.trace.arrival)[self.ok_mask()]

    def queue_wait(self):
        return (self.admit - self.trace.arrival)[self.ok_mask()]

    def ttft_percentiles(self, qs=(50, 99)):
        t = self.ttft()
        return {q: float(np.percentile(t, q)) for q in qs}

    def ttft_percentiles_by_model(self, qs=(50, 99)):
        """{model name: {q: ttft}} over admitted requests; empty for a
        single-model trace (no named models to break down by)."""
        names = getattr(self.trace, 'model_names', None)
        if not names:
            return {}
        t = self.first - self.trace.arrival
        m = self.ok_mask()
        out = {}
        for idx, name in enumerate(names):
            mask = m & (self.trace.model_id == idx)
            if mask.any():
                out[name] = {q: float(np.percentile(t[mask], q))
                             for q in qs}
        return out

    def ttft_percentiles_by_priority(self, qs=(50, 99)):
        """{priority: {q: ttft}} over admitted requests — the graceful-
        degradation read: premium classes should hold their tail while
        the background class absorbs the shedding."""
        if self.priority is None:
            return {0: self.ttft_percentiles(qs)}
        t = self.first - self.trace.arrival
        m = self.ok_mask()
        out = {}
        for p in sorted(set(int(x) for x in self.priority)):
            mask = m & (self.priority == p)
            if mask.any():
                out[int(p)] = {q: float(np.percentile(t[mask], q))
                               for q in qs}
        return out

    def summary(self, slo_ttft_s=None):
        p = self.ttft_percentiles((50, 90, 99))
        out = {'requests': len(self), 'max_replicas': self.max_replicas,
               'sim_duration_s': float(self.finish.max()),
               'wall_s': round(self.wall_s, 3),
               'ttft_p50_s': p[50], 'ttft_p90_s': p[90],
               'ttft_p99_s': p[99],
               'queue_wait_p99_s': float(np.percentile(self.queue_wait(),
                                                       99)),
               'failovers': int(self.failovers.sum()),
               'prefix_hit_requests': int(self.prefix_hits.sum())}
        if self.outcome is not None:
            rej = int((self.outcome == 'rejected').sum())
            out['rejected'] = rej
            out['shed_rate'] = rej / float(len(self))
        if slo_ttft_s is not None:
            out['slo_ttft_s'] = float(slo_ttft_s)
            out['slo_ok'] = bool(p[99] <= slo_ttft_s)
        return out

    def to_events(self):
        """Wide-event-schema dicts (one per request) so simulated runs
        join the same offline tooling as real ones. Only sensible for
        calibration-scale runs — a million dicts defeats the columnar
        point."""
        tr = self.trace
        names = tr.tenant_names
        mnames = getattr(tr, 'model_names', None)
        out = []
        for i in range(len(tr)):
            shed = (self.outcome is not None
                    and self.outcome[i] == 'rejected')
            out.append({
                'request_id': 'sim-%d' % i,
                'tenant': names[tr.tenant_id[i]],
                'model': (mnames[tr.model_id[i]]
                          if mnames is not None else None),
                'priority': (int(self.priority[i])
                             if self.priority is not None else 0),
                'trace_id': None,
                'arrival_t': float(tr.arrival[i]),
                # a shed request never reached a replica: no admit, no
                # first token (ttfts_of_events skips the Nones)
                'admit_t': None if shed else float(self.admit[i]),
                'first_token_t': None if shed else float(self.first[i]),
                'finish_t': float(self.finish[i]),
                'queue_wait_s': (0.0 if shed else
                                 float(self.admit[i] - tr.arrival[i])),
                'prefill_chunks': int(self.chunks[i]),
                'prompt_tokens': int(tr.prompt_len[i]),
                'output_tokens': 0 if shed else int(tr.new_tokens[i]),
                'prefix_hit_tokens': int(tr.prefix_len[i])
                if self.prefix_hits[i] else 0,
                'spec_proposed': 0, 'spec_accepted': 0,
                'kv_page_seconds': (0.0 if shed else
                                    float(self.finish[i] - self.admit[i])),
                'failovers': int(self.failovers[i]),
                'replicas': ([] if shed else
                             ['sim://replica-%d' % self.replica_of[i]]),
                'outcome': ('rejected' if shed else 'ok')})
        return out


def _burn_rate(ttft_log, now, slo, window):
    recent = [v for (t, v) in ttft_log if now - t <= window]
    if not recent:
        return 0.0
    return sum(1 for v in recent if v > slo) / float(len(recent))


def simulate(trace, model, replicas=2, router='least_loaded', policy=None,
             autoscale_tick_s=None, kill_at=None, advance_every=None,
             registry=None, qos=None):
    """Run `trace` through a simulated fleet of `replicas` engines.

    router: 'least_loaded' (the gateway's policy, replicas advanced to
    each arrival before routing) or 'round_robin' (cheaper; the default
    pick for million-request sweeps via `advance_every` batching).
    policy: an AutoscalePolicy-shaped object; its decide() is evaluated
    every `autoscale_tick_s` simulated seconds and +1/-1 deltas add or
    drain replicas, exactly as ServingGateway.autoscale_tick applies
    them. kill_at: {replica_index: sim_time} hard failures — queued and
    resident requests re-route with failovers+1 and restart service.
    advance_every: advance replicas every N arrivals instead of every
    arrival (default 1 when n <= 20k, else 1024 — the batching that
    keeps million-request sweeps in seconds).
    qos: a capacity.qos.QosPolicy — the gateway's admission layer in
    simulated time. Arrivals failing the per-tenant rate/quota check
    shed at the front door (outcome 'rejected', no replica time), and
    replica queues serve highest priority first, FIFO within a class.
    The sim deliberately does NOT model KV preemption — admission +
    priority ordering dominate fleet-level tails, and the pessimistic
    error (a resident low-priority request holding its slot) is the
    safe direction for capacity planning. NOTE: the policy object is
    STATEFUL (buckets, inflight counts) and gets consumed by the run —
    pass a fresh instance per simulate() call (sweep_qos does).
    """
    import time as _time
    host0 = _time.monotonic()
    n = len(trace)
    if n < 1:
        raise ValueError('empty trace')
    if advance_every is None:
        advance_every = 1 if n <= 20000 else 1024
    chunk_s = model.prefill_chunk_s
    burst_s = model.decode_burst_s
    chunk = model.prefill_chunk
    block = model.decode_block
    slots = model.num_slots

    # plain-python columns: the inner loop is integer/float arithmetic
    # and numpy scalar boxing would dominate it
    arrival = trace.arrival.tolist()
    prompt_len = trace.prompt_len.tolist()
    new_tokens = trace.new_tokens.tolist()
    prefix_group = trace.prefix_group.tolist()
    prefix_len = trace.prefix_len.tolist()

    admit = [0.0] * n
    first = [0.0] * n
    finish = [0.0] * n
    failovers = [0] * n
    replica_of = [0] * n
    prefix_hits = [False] * n
    chunks_of = [0] * n

    # QoS columns (only materialized when a policy is active)
    tenant_of = prio = outcome = reason_of = None
    if qos is not None:
        names = trace.tenant_names
        tids = trace.tenant_id.tolist()
        prio_of_tid = [int(qos.priority_of(nm)) for nm in names]
        tenant_of = [names[t] for t in tids]
        prio = [prio_of_tid[t] for t in tids]
        outcome = ['ok'] * n
        reason_of = [None] * n

    pool = [_Replica(0.0, slots) for _ in range(int(replicas))]
    timeline = [(0.0, len(pool))]
    ttft_log = []
    slo = getattr(policy, 'slo_ttft_s', 1.0)
    window = getattr(policy, 'window_s', 30.0)
    if policy is not None and autoscale_tick_s is None:
        autoscale_tick_s = max(getattr(policy, 'sustain_s', 1.0) / 2.0,
                               1e-3)
    next_tick = autoscale_tick_s if policy is not None else None

    def advance(rep, until, ridx):
        """Engine steps until the local clock passes `until` or the
        replica runs dry. One loop iteration == one engine step; a step
        in flight completes past `until` (steps are not preemptible)."""
        t = rep.t
        queue = rep.queue
        qh = 0  # consumed queue head (popped in bulk afterwards)
        while True:
            act = rep.active
            if not act:
                if qh:
                    del queue[:qh]
                    qh = 0
                if prio is not None and rep.ready:
                    pass          # admissible work is already staged
                elif not queue:
                    break
                else:
                    # idle: jump the local clock to the head arrival
                    t = max(t, queue[0][1])
            if t >= until:
                break
            # ADMIT arrived requests into free slots at the step top.
            # With a QoS policy, arrived entries stage through a
            # priority heap first — highest class served first, trace
            # order within a class — so the pick stays O(log n) even
            # when deep overload piles up an arrived backlog (a linear
            # best-scan goes quadratic exactly when QoS matters most).
            if prio is not None:
                while qh < len(queue) and queue[qh][1] <= t:
                    e = queue[qh]
                    qh += 1
                    heapq.heappush(rep.ready, (-prio[e[0]], e[0]))
            while len(act) < rep.slots:
                if prio is None:
                    if qh >= len(queue) or queue[qh][1] > t:
                        break
                    ri = queue[qh][0]
                    qh += 1
                else:
                    if not rep.ready:
                        break
                    ri = heapq.heappop(rep.ready)[1]
                admit[ri] = t
                g = prefix_group[ri]
                eff = prompt_len[ri]
                if g >= 0:
                    if g in rep.seen_prefix:
                        eff = eff - prefix_len[ri]
                        if eff < 1:
                            eff = 1
                        prefix_hits[ri] = True
                    else:
                        rep.seen_prefix.add(g)
                nchunks = (eff + chunk - 1) // chunk
                chunks_of[ri] = nchunks
                act.append([ri, nchunks, new_tokens[ri]])
            if not act:
                # head not yet arrived: idle until it does
                t = max(t, queue[qh][1])
                continue
            if qh > 512:
                del queue[:qh]
                qh = 0
            # PREFILL one chunk per consuming prompt, then one shared
            # DECODE burst for every consumed slot — scheduler.py's step
            npre = 0
            decoding = False
            for rec in act:
                if rec[1] > 0:
                    rec[1] -= 1
                    npre += 1
                if rec[1] == 0:
                    decoding = True
            dt = npre * chunk_s + (burst_s if decoding else 0.0)
            t += dt
            rep.busy_slot_s += dt * len(act)
            if decoding:
                done_any = False
                for rec in act:
                    if rec[1] == 0:
                        ri = rec[0]
                        left = rec[2]
                        if left == new_tokens[ri]:
                            first[ri] = t
                            ttft_log.append((t, t - arrival[ri]))
                        left -= block
                        rec[2] = left
                        if left <= 0:
                            finish[ri] = t
                            replica_of[ri] = ridx
                            rep.outstanding -= 1
                            if qos is not None:
                                qos.finish(tenant_of[ri])
                            done_any = True
                if done_any:
                    rep.active = [r for r in act if r[2] > 0]
        if qh:
            del queue[:qh]
        rep.t = max(t, rep.t)

    def advance_all(until):
        for ridx, r in enumerate(pool):
            if r.alive:
                advance(r, until, ridx)

    def route(i, arr, fo=0):
        live = [r for r in pool if r.alive and not r.draining]
        if not live:
            live = [r for r in pool if r.alive]
        if not live:
            raise RuntimeError('all simulated replicas are dead at '
                               't=%.3f' % arr)
        if router == 'round_robin':
            rep = live[(i + fo) % len(live)]
        else:
            rep = min(live, key=lambda r: r.outstanding)
        rep.queue.append((i, arr))
        rep.outstanding += 1

    def kill(idx, now):
        rep = pool[idx]
        if not rep.alive:
            return
        rep.alive = False
        orphans = [ri for (ri, _) in rep.queue]
        orphans += [e[1] for e in rep.ready]
        orphans += [rec[0] for rec in rep.active if rec[2] > 0]
        rep.queue = []
        rep.ready = []
        rep.active = []
        rep.outstanding = 0
        timeline.append((now, sum(1 for r in pool if r.alive)))
        for ri in orphans:
            failovers[ri] += 1
            route(ri, now, fo=failovers[ri])

    def tick(now):
        live = [r for r in pool if r.alive]
        occ = (sum(len(r.active) for r in live)
               / float(max(1, sum(r.slots for r in live))))
        qd = sum(len(r.queue) for r in live)
        burn = _burn_rate(ttft_log[-4096:], now, slo, window)
        d = policy.decide(now, burn, occ, qd, len(live))
        if d.delta > 0:
            pool.append(_Replica(now, slots))
            timeline.append((now, sum(1 for r in pool if r.alive)))
        elif d.delta < 0:
            victims = [r for r in live if not r.draining]
            if len(victims) > 1:
                min(victims, key=lambda r: r.outstanding).draining = True
                timeline.append(
                    (now, sum(1 for r in pool
                              if r.alive and not r.draining)))

    pending_kills = sorted((kill_at or {}).items(), key=lambda kv: kv[1])
    i = 0
    while i < n:
        now = arrival[i]
        while pending_kills and pending_kills[0][1] <= now:
            idx, kt = pending_kills.pop(0)
            advance_all(kt)
            kill(idx, kt)
        if next_tick is not None and now >= next_tick:
            advance_all(next_tick)
            tick(next_tick)
            next_tick += autoscale_tick_s
            continue
        stop = min(i + advance_every, n)
        if router != 'round_robin' or advance_every == 1:
            advance_all(now)
        broke = False
        for j in range(i, stop):
            if next_tick is not None and arrival[j] >= next_tick:
                stop = j
                broke = True
                break
            if pending_kills and pending_kills[0][1] <= arrival[j]:
                stop = j
                broke = True
                break
            if qos is not None:
                ok, why = qos.admit(arrival[j], tenant_of[j])
                if not ok:
                    # shed at the front door: the request costs no
                    # replica time and its "latency" is undefined
                    outcome[j] = 'rejected'
                    reason_of[j] = why
                    admit[j] = first[j] = finish[j] = arrival[j]
                    continue
            route(j, arrival[j], fo=0)
        if not broke and router == 'round_robin' and stop > i:
            advance_all(arrival[stop - 1])
        # stop == i only when a tick/kill interrupted at the batch head;
        # the top-of-loop handlers then consume it before routing resumes
        i = stop

    # drain: apply any kills past the last arrival, then run every
    # surviving replica dry (the autoscaler holds during drain — no
    # arrivals means no routing for a new replica to absorb)
    while pending_kills:
        idx, kt = pending_kills.pop(0)
        advance_all(kt)
        kill(idx, kt)
    while True:
        busy = False
        for ridx, r in enumerate(pool):
            if r.alive and (r.queue or r.ready or r.active):
                advance(r, float('inf'), ridx)
                busy = True
        if not busy:
            break

    wall = _time.monotonic() - host0
    res = SimResult(trace,
                    np.asarray(admit), np.asarray(first),
                    np.asarray(finish),
                    np.asarray(failovers, dtype=np.int64),
                    np.asarray(replica_of, dtype=np.int64),
                    np.asarray(prefix_hits, dtype=bool),
                    np.asarray(chunks_of, dtype=np.int64),
                    timeline, wall,
                    outcome=(None if outcome is None
                             else np.asarray(outcome)),
                    priority=(None if prio is None
                              else np.asarray(prio, dtype=np.int64)),
                    reject_reason=(None if reason_of is None
                                   else np.asarray(reason_of,
                                                   dtype=object)))
    if registry is not None:
        from ..monitor.telemetry import record_capacity_schema
        fams = record_capacity_schema(registry)
        fams['sim_requests_total'].inc(n)
        fams['sim_runs_total'].inc()
        fams['sim_last_p99_ttft_seconds'].set(
            res.ttft_percentiles((99,))[99])
    return res


def sweep_replicas(trace, model, counts=(1, 2, 4, 8, 16), slo_ttft_s=1.0,
                   percentile=99, router='round_robin',
                   advance_every=None, registry=None):
    """Simulate `trace` at each replica count; report the TTFT tail per
    point and the minimum count whose p<percentile> TTFT meets the SLO
    (None when no swept count does — scale the sweep, not the claim)."""
    points = []
    min_replicas = None
    for c in sorted(set(int(c) for c in counts)):
        res = simulate(trace, model, replicas=c, router=router,
                       advance_every=advance_every, registry=registry)
        p = res.ttft_percentiles((50, percentile))
        ok = p[percentile] <= slo_ttft_s
        point = {'replicas': c, 'ttft_p50_s': p[50],
                 'ttft_p%d_s' % percentile: p[percentile],
                 'sim_wall_s': round(res.wall_s, 3),
                 'meets_slo': bool(ok)}
        by_model = res.ttft_percentiles_by_model((percentile,))
        if by_model:
            # only multi-model traces carry the column, so single-model
            # sweep output stays byte-stable for downstream parsers
            point['ttft_by_model'] = {m: v[percentile]
                                      for m, v in sorted(by_model.items())}
        points.append(point)
        if ok and min_replicas is None:
            min_replicas = c
    return {'slo_ttft_s': float(slo_ttft_s), 'percentile': int(percentile),
            'requests': len(trace), 'points': points,
            'min_replicas': min_replicas}


def sweep_qos(trace, model, policies, replicas=2, slo_ttft_s=1.0,
              percentile=99, router='round_robin', advance_every=None):
    """Simulate the same trace and fleet under each admission policy.

    `policies`: [(name, QosPolicy-or-dict)] pairs (or a {name: policy}
    dict). Policies are re-materialized per run via to_dict/from_dict —
    QosPolicy instances are stateful, and a sweep must not leak bucket
    levels across points. Each point reports the overall admitted-TTFT
    tail, the shed rate, and the per-priority-class tail; `meets_slo`
    asks whether the HIGHEST priority class holds the SLO — the
    graceful-degradation question, not the aggregate one.
    """
    from .qos import QosPolicy
    if isinstance(policies, dict):
        policies = sorted(policies.items())
    points = []
    for name, pol in policies:
        spec = pol if isinstance(pol, dict) else pol.to_dict()
        res = simulate(trace, model, replicas=replicas, router=router,
                       advance_every=advance_every,
                       qos=QosPolicy.from_dict(spec))
        s = res.summary()
        by = res.ttft_percentiles_by_priority((percentile,))
        top = max(by) if by else 0
        points.append({
            'policy': name,
            'ttft_p%d_s' % percentile:
                res.ttft_percentiles((percentile,))[percentile],
            'rejected': s.get('rejected', 0),
            'shed_rate': s.get('shed_rate', 0.0),
            'by_priority': {str(p): v[percentile]
                            for p, v in sorted(by.items())},
            'meets_slo': bool(by and by[top][percentile] <= slo_ttft_s)})
    return {'slo_ttft_s': float(slo_ttft_s), 'percentile': int(percentile),
            'requests': len(trace), 'replicas': int(replicas),
            'points': points}


# ---------------------------------------------------------------------------
# sim-vs-real divergence


def ks_statistic(a, b):
    """Two-sample Kolmogorov-Smirnov statistic: sup |F_a - F_b|."""
    a = np.sort(np.asarray(a, dtype=np.float64))
    b = np.sort(np.asarray(b, dtype=np.float64))
    if not len(a) or not len(b):
        return 1.0
    grid = np.concatenate([a, b])
    fa = np.searchsorted(a, grid, side='right') / float(len(a))
    fb = np.searchsorted(b, grid, side='right') / float(len(b))
    return float(np.max(np.abs(fa - fb)))


def _rel_err(sim, real):
    return abs(sim - real) / max(abs(real), 1e-12)


def ttft_divergence(sim_ttfts, real_ttfts):
    """K-S plus p50/p99 relative error between two TTFT samples (any
    units, as long as both sides agree)."""
    sim = np.asarray(sim_ttfts, dtype=np.float64)
    real = np.asarray(real_ttfts, dtype=np.float64)
    if not len(sim) or not len(real):
        raise ValueError('both TTFT samples must be non-empty')
    sp50, sp99 = np.percentile(sim, 50), np.percentile(sim, 99)
    rp50, rp99 = np.percentile(real, 50), np.percentile(real, 99)
    return {'ks': ks_statistic(sim, real),
            'p50_rel_err': _rel_err(sp50, rp50),
            'p99_rel_err': _rel_err(sp99, rp99),
            'sim_p50_s': float(sp50), 'sim_p99_s': float(sp99),
            'real_p50_s': float(rp50), 'real_p99_s': float(rp99),
            'sim_n': int(len(sim)), 'real_n': int(len(real))}


def ttfts_of_events(events):
    """TTFT seconds from wide events (first_token_t - arrival_t),
    skipping requests that never produced a token."""
    out = []
    for e in events:
        a, f = e.get('arrival_t'), e.get('first_token_t')
        if a is not None and f is not None:
            out.append(f - a)
    return out


def compare_events(sim_events, real_events, min_samples=3):
    """Per-tenant + overall ttft_divergence between two wide-event sets
    (the capacity_report join). Tenants with fewer than `min_samples`
    TTFTs on either side are reported but not compared."""
    def split(events):
        by = {}
        for e in events:
            a, f = e.get('arrival_t'), e.get('first_token_t')
            if a is None or f is None:
                continue
            by.setdefault(e.get('tenant') or 'default', []).append(f - a)
        return by

    sim_by, real_by = split(sim_events), split(real_events)
    out = {'overall': ttft_divergence(
        [v for vs in sim_by.values() for v in vs],
        [v for vs in real_by.values() for v in vs]), 'tenants': {}}
    for tenant in sorted(set(sim_by) | set(real_by)):
        s, r = sim_by.get(tenant, []), real_by.get(tenant, [])
        if len(s) >= min_samples and len(r) >= min_samples:
            out['tenants'][tenant] = ttft_divergence(s, r)
        else:
            out['tenants'][tenant] = {'skipped': 'insufficient samples',
                                      'sim_n': len(s), 'real_n': len(r)}
    return out


def min_replicas_for(trace, model, slo_ttft_s, counts=(1, 2, 4, 8, 16),
                     percentile=99, **kw):
    """Convenience: sweep and return (min_replicas, sweep dict)."""
    sweep = sweep_replicas(trace, model, counts=counts,
                           slo_ttft_s=slo_ttft_s, percentile=percentile,
                           **kw)
    return sweep['min_replicas'], sweep
