"""Capacity planning: workload language, trace replay, fleet simulator.

One `Trace` form, three doors:

- `workload`  — declarative, seeded trace generation (Poisson/diurnal
  arrivals, heavy-tailed lengths, tenant skew, shared prefixes) and
  loaders for recorded wide-event JSONL. Same spec + same seed ==
  byte-identical trace.
- `replay`    — open-loop, arrival-faithful replay of a Trace against
  the real ServingGateway; the single arrival generator behind the
  serving bench rungs.
- `simulator` — discrete-event gateway+replicas simulation with a
  calibrated two-parameter service model; validates against replayed
  runs by TTFT-distribution divergence, then sweeps replica counts at
  million-request scale in seconds.

This package imports numpy and the stdlib-only monitor/ layer eagerly;
jax-backed serving machinery loads only inside replay's functions.
"""
from .qos import QosPolicy, TenantClass, TokenBucket
from .replay import ReplayResult, measure, replay
from .simulator import (ServiceModel, SimResult, compare_events,
                        ks_statistic, min_replicas_for, simulate,
                        sweep_qos, sweep_replicas, ttft_divergence,
                        ttfts_of_events)
from .workload import (Trace, WorkloadSpec, generate, load_trace,
                       poisson_arrivals, trace_from_events)

__all__ = [
    'Trace', 'WorkloadSpec', 'generate', 'load_trace',
    'poisson_arrivals', 'trace_from_events',
    'ReplayResult', 'replay', 'measure',
    'QosPolicy', 'TenantClass', 'TokenBucket',
    'ServiceModel', 'SimResult', 'simulate', 'sweep_replicas',
    'sweep_qos', 'min_replicas_for', 'ks_statistic', 'ttft_divergence',
    'compare_events', 'ttfts_of_events',
]
