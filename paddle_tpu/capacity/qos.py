"""Pure multi-tenant QoS admission policy: token buckets, concurrency
quotas, priorities.

This module is the policy half of the QoS enforcement plane. It is
deliberately free of clocks, locks and serving imports — every decision
takes `now` as an argument (the AutoscalePolicy discipline: the caller
owns time, tests drive a fake clock), and the caller serializes access
(the gateway calls under its one lock; the simulator is single-
threaded). The same `QosPolicy` object therefore drives three
consumers without adaptation:

- `ServingGateway(admission=policy)` — real traffic, real clock;
- `capacity.simulator.simulate(trace, ..., qos=policy)` — the same
  admission decisions at million-request scale in virtual time;
- `tools/capacity_report.py --qos-policy` — policy sweeps from JSON.

Vocabulary (closed sets — metrics label budgets depend on this):

- rejection reasons: ``'rate'`` (token bucket empty), ``'quota'``
  (per-tenant concurrency cap), ``'queue_full'`` (bounded pending
  queue overflowed), ``'deadline'`` (parked past max_queue_wait_s).
  `admit` itself only produces the first two; the queue-shaped reasons
  belong to the queue owner (gateway / simulator).
- priority: plain int, higher wins. Ties are FIFO.
"""
import math

__all__ = ['REJECT_REASONS', 'TokenBucket', 'TenantClass', 'QosPolicy']

REJECT_REASONS = ('rate', 'quota', 'queue_full', 'deadline')


class TokenBucket:
    """Classic token bucket in continuous time: `rate` tokens/s refill,
    `burst` capacity. No clock inside — `take`/`level` are functions of
    the caller's `now`, so virtual (simulator) and real time both
    work, and tests never sleep."""

    def __init__(self, rate, burst):
        if rate <= 0:
            raise ValueError('rate must be positive')
        if burst <= 0:
            raise ValueError('burst must be positive')
        self.rate = float(rate)
        self.burst = float(burst)
        self._level = float(burst)
        self._t = None                  # time of the last refill

    def _refill(self, now):
        if self._t is None:
            self._t = now
        elif now > self._t:
            self._level = min(self.burst,
                              self._level + (now - self._t) * self.rate)
            self._t = now

    def take(self, now, n=1):
        """Spend `n` tokens if available; False leaves the level
        untouched (a rejected request must not consume credit)."""
        self._refill(now)
        if self._level + 1e-9 < n:
            return False
        self._level -= n
        return True

    def level(self, now):
        self._refill(now)
        return self._level


class TenantClass:
    """One tenant class's limits: requests/s (`rate` + `burst`),
    concurrent in-flight cap (`max_concurrent`), scheduling `priority`.
    None for a limit means unlimited."""

    def __init__(self, name='default', rate=None, burst=None,
                 max_concurrent=None, priority=0):
        self.name = str(name)
        self.rate = None if rate is None else float(rate)
        # burst defaults to one second of rate (min 1) — the smallest
        # bucket that still admits a steady stream at exactly `rate`
        self.burst = (float(burst) if burst is not None
                      else None if rate is None
                      else max(1.0, math.ceil(rate)))
        self.max_concurrent = (None if max_concurrent is None
                               else int(max_concurrent))
        self.priority = int(priority)

    def to_dict(self):
        d = {'name': self.name, 'priority': self.priority}
        if self.rate is not None:
            d['rate'] = self.rate
            d['burst'] = self.burst
        if self.max_concurrent is not None:
            d['max_concurrent'] = self.max_concurrent
        return d

    @classmethod
    def from_dict(cls, d):
        return cls(name=d.get('name', 'default'), rate=d.get('rate'),
                   burst=d.get('burst'),
                   max_concurrent=d.get('max_concurrent'),
                   priority=d.get('priority', 0))


class QosPolicy:
    """Admission policy over a set of tenant classes.

    `classes`: {tenant name: TenantClass} (or an iterable of
    TenantClass, keyed by their names). Tenants without a class fall
    back to `default` (an unlimited priority-0 TenantClass unless
    given). `max_pending` bounds the owner's pending queue;
    `max_queue_wait_s` is the parked-request deadline — both are
    advisory numbers the queue owner enforces, carried here so one JSON
    blob describes the whole policy.

    Mutable per-tenant state (bucket level, in-flight count) lives on
    the policy, keyed by the tenant name the caller passes — gateways
    pass bounded TenantLabeler labels, so state cardinality is bounded
    too. Call `admit` once per arriving request and `finish` exactly
    once per admitted request that terminates.
    """

    def __init__(self, classes=None, default=None, max_pending=None,
                 max_queue_wait_s=None):
        self.classes = {}
        if classes:
            it = classes.values() if isinstance(classes, dict) \
                else classes
            for c in it:
                self.classes[c.name] = c
        self.default = default if default is not None else TenantClass()
        self.max_pending = None if max_pending is None else int(max_pending)
        self.max_queue_wait_s = (None if max_queue_wait_s is None
                                 else float(max_queue_wait_s))
        self._buckets = {}              # tenant -> TokenBucket
        self._inflight = {}             # tenant -> admitted, unfinished

    def class_of(self, tenant):
        key = 'default' if tenant is None else str(tenant)
        return self.classes.get(key, self.default)

    def priority_of(self, tenant):
        return self.class_of(tenant).priority

    def _bucket(self, tenant, cls):
        if cls.rate is None:
            return None
        b = self._buckets.get(tenant)
        if b is None:
            b = self._buckets[tenant] = TokenBucket(cls.rate, cls.burst)
        return b

    def admit(self, now, tenant):
        """One admission decision: (True, None) or (False, reason) with
        reason in {'rate', 'quota'}. Admission takes one bucket token
        and one in-flight slot; rejection takes neither."""
        key = 'default' if tenant is None else str(tenant)
        cls = self.class_of(key)
        if cls.max_concurrent is not None and \
                self._inflight.get(key, 0) >= cls.max_concurrent:
            return False, 'quota'
        b = self._bucket(key, cls)
        if b is not None and not b.take(now):
            return False, 'rate'
        self._inflight[key] = self._inflight.get(key, 0) + 1
        return True, None

    def finish(self, tenant):
        """Release the in-flight slot `admit` took. Exactly once per
        admitted request, at any terminal outcome."""
        key = 'default' if tenant is None else str(tenant)
        n = self._inflight.get(key, 0)
        if n > 0:
            self._inflight[key] = n - 1

    def inflight(self, tenant):
        key = 'default' if tenant is None else str(tenant)
        return self._inflight.get(key, 0)

    def bucket_level(self, tenant, now):
        """Remaining credit for the tenant's bucket (None: unlimited)."""
        key = 'default' if tenant is None else str(tenant)
        b = self._bucket(key, self.class_of(key))
        return None if b is None else b.level(now)

    def to_dict(self):
        d = {'classes': [c.to_dict() for _, c in
                         sorted(self.classes.items())],
             'default': self.default.to_dict()}
        if self.max_pending is not None:
            d['max_pending'] = self.max_pending
        if self.max_queue_wait_s is not None:
            d['max_queue_wait_s'] = self.max_queue_wait_s
        return d

    @classmethod
    def from_dict(cls, d):
        return cls(
            classes=[TenantClass.from_dict(c)
                     for c in d.get('classes', ())],
            default=(TenantClass.from_dict(d['default'])
                     if 'default' in d else None),
            max_pending=d.get('max_pending'),
            max_queue_wait_s=d.get('max_queue_wait_s'))
